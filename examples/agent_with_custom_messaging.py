"""Agent demonstrating the messaging plugin seam with a custom transport.

Equivalent of the reference's AgentWithNettyMessaging (examples/.../
AgentWithNettyMessaging.java:58-67): the default agent uses the
wire-compatible gRPC transport; this one injects the framed-TCP transport via
set_messaging_client_and_server -- the same seam any user transport plugs
into (IMessagingClient/IMessagingServer, messaging/base.py).

    python examples/agent_with_custom_messaging.py --listen-address 127.0.0.1:1234
"""

import argparse
import logging
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from rapid_tpu import ClusterBuilder, Endpoint, Settings
from rapid_tpu.messaging.tcp import TcpClientServer


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--listen-address", required=True)
    parser.add_argument("--seed-address")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    listen = Endpoint.from_string(args.listen_address)
    settings = Settings()
    transport = TcpClientServer(listen, settings)  # the custom transport
    builder = (
        ClusterBuilder(listen)
        .use_settings(settings)
        .set_messaging_client_and_server(transport, transport)
    )
    cluster = (
        builder.join(Endpoint.from_string(args.seed_address))
        if args.seed_address
        else builder.start()
    )
    logging.info("started %s over custom TCP messaging", cluster)
    while True:
        time.sleep(1)
        logging.info("membership size=%d", cluster.get_membership_size())


if __name__ == "__main__":
    main()
