"""Socket-hosted TPU swarm gateway (see rapid_tpu/cli/gateway.py for the
implementation; this shim keeps the reference's examples/ layout).

    python examples/swarm_gateway.py --listen-address 127.0.0.1:4000 \
        --n-virtual 1000
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root

from rapid_tpu.cli.gateway import main

if __name__ == "__main__":
    main()
