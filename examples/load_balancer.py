"""End-to-end application scenario: a workload router on live membership.

The paper's closing evaluation (docs/atc-2018-camera-ready.pdf §7 Fig. 13)
runs nginx in front of 50 backends, fails 10 of them at once, and shows
Rapid removing the whole set in a SINGLE view change -- the application
reroutes immediately instead of bleeding errors through ten separate
reconfigurations. This example is that scenario on the TPU-hosted plane:

- a ``SwarmGateway`` hosts N virtual backends (the simulated fleet),
- the router is a real member: the untouched ClusterBuilder stack joined
  through the gateway, its backend pool maintained ONLY by VIEW_CHANGE
  subscriptions (ClusterEvents.java:19-24 -- no health checks of its own,
  membership IS the health signal),
- requests are routed through the serving plane's client-side router
  (rapid_tpu.serving.RendezvousRouter -- rendezvous hashing over the live
  pool, byte-identical to the routing this example originally hand-rolled;
  the parity is asserted below), so a view change moves only the failed
  backends' keys,
- a correlated burst kills 10 backends; the membership protocol cuts all
  of them in one view change and the router's very next routes are clean.

    python examples/load_balancer.py --backends 50 --fail 10
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Dict

sys.path.insert(0, ".")

import numpy as np  # noqa: E402

from rapid_tpu import ClusterBuilder, Endpoint, Settings  # noqa: E402
from rapid_tpu.placement import rendezvous_route, weight_seed  # noqa: E402
from rapid_tpu.serving import RendezvousRouter  # noqa: E402
from rapid_tpu.messaging.gateway import (  # noqa: E402
    GatewayRoutedClient,
    GatewaySwarmBroadcaster,
    SwarmGateway,
)
from rapid_tpu.messaging.tcp import TcpClientServer  # noqa: E402

# The router implementation this example originally hand-rolled now lives
# in the serving plane (rapid_tpu/serving/router.py) as its client-side
# routing surface; the alias keeps this example's historical name working
# for anything that imported it.
ViewChangeRouter = RendezvousRouter


def run_scenario(
    backends: int = 50,
    fail: int = 10,
    seed: int = 23,
    requests_per_check: int = 200,
    quiet: bool = False,
) -> Dict[str, object]:
    """The Fig.-13 shape; returns the measurements the caller asserts on."""
    from rapid_tpu.messaging.ports import free_port_base

    def say(msg: str) -> None:
        if not quiet:
            print(msg)

    base = free_port_base(4)
    settings = Settings(
        failure_detector_interval_ms=100,
        batching_window_ms=50,
    )
    gateway = SwarmGateway(
        Endpoint.from_parts("127.0.0.1", base),
        n_virtual=backends,
        seed=seed,
        settings=settings,
        pump_interval_ms=50,
    )
    gateway.start()
    router_cluster = None
    try:
        gateway.warm()
        router_addr = Endpoint.from_parts("127.0.0.1", base + 1)
        transport = TcpClientServer(router_addr, settings)
        client = GatewayRoutedClient(
            router_addr, gateway.address, transport, settings
        )
        router_cluster = (
            ClusterBuilder(router_addr)
            .use_settings(settings)
            .set_messaging_client_and_server(client, transport)
            .set_broadcaster_factory(
                lambda c, rng, routed=client: GatewaySwarmBroadcaster(routed)
            )
            .join(gateway.seed_endpoint(), timeout=90)
        )
        router = ViewChangeRouter(router_cluster, router_addr)
        say(f"router joined: {len(router.backends())} backends live")
        assert len(router.backends()) == backends

        # steady-state traffic before the failure
        keys = [b"req-%d" % i for i in range(requests_per_check)]
        before = {k: router.route(k) for k in keys}
        assert all(v is not None for v in before.values())

        # routing parity: the serving plane's router must route every key
        # byte-identically to the rendezvous hashing this example
        # originally computed inline
        pool = router.backends()
        seeds = {b: weight_seed(b) for b in pool}
        assert all(
            before[k] == rendezvous_route(k, pool, seeds) for k in keys
        ), "serving-plane router diverged from direct rendezvous routing"

        # the correlated burst: fail `fail` backends at once
        victims = np.arange(2, 2 + fail)
        victim_eps = {gateway.bridge.endpoint(int(v)) for v in victims}
        changes_before = router.view_changes
        gateway.bridge.sim.crash(victims)
        say(f"crashed {fail} backends; waiting for the cut...")
        deadline = time.time() + 120
        while (
            time.time() < deadline
            and router_cluster.get_membership_size() != backends + 1 - fail
        ):
            time.sleep(0.05)
        assert router_cluster.get_membership_size() == backends + 1 - fail

        # Fig. 13's claim: ONE view change removed the whole failed set
        view_changes = router.view_changes - changes_before
        cut = {c.endpoint for c in router.last_down}
        say(f"view changes: {view_changes}; cut size: {len(cut)}")

        # and the router's next routes never touch a dead backend
        after = {k: router.route(k) for k in keys}
        dead_routes = [k for k, b in after.items() if b in victim_eps]
        moved = [k for k in keys if before[k] != after[k]]
        say(
            f"routes to dead backends after the change: {len(dead_routes)}; "
            f"keys remapped: {len(moved)}/{len(keys)}"
        )
        return {
            "view_changes": view_changes,
            "cut": cut,
            "victims": victim_eps,
            "dead_routes": dead_routes,
            "moved": len(moved),
            "keys": len(keys),
            "config_id_router": router_cluster.get_current_configuration_id(),
            "config_id_swarm": gateway.configuration_id(),
        }
    finally:
        if router_cluster is not None:
            router_cluster.shutdown()
        gateway.shutdown()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--backends", type=int, default=50)
    parser.add_argument("--fail", type=int, default=10)
    parser.add_argument("--seed", type=int, default=23)
    parser.add_argument(
        "--platform", default="cpu",
        help="jax platform for the swarm engine (cpu default: an injected "
        "accelerator plugin would otherwise claim the backend, and a dead "
        "remote-TPU tunnel hangs device init)",
    )
    args = parser.parse_args()
    if args.platform:
        import jax

        # config value, not the env var: an injected plugin (e.g. the axon
        # remote-TPU relay) monkeypatches backend init and ignores the env
        jax.config.update("jax_platforms", args.platform)
    out = run_scenario(args.backends, args.fail, args.seed)
    ok = (
        out["view_changes"] == 1
        and out["cut"] == out["victims"]
        and not out["dead_routes"]
        and out["config_id_router"] == out["config_id_swarm"]
    )
    print(
        f"single view change: {out['view_changes'] == 1}; exact cut: "
        f"{out['cut'] == out['victims']}; clean routes: "
        f"{not out['dead_routes']}; config ids match: "
        f"{out['config_id_router'] == out['config_id_swarm']}"
    )
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
