"""Run the membership simulator over a multi-host ("dcn", "ici") mesh.

On a TPU pod slice, launch one copy of this script per host:

    python examples/multihost_sim.py --coordinator host0:8476 \
        --num-processes 4 --process-id $RANK --n 400000

Single-host (or the forced CPU backend) needs no flags: the degenerate
1-host mesh runs the identical sharded program.

Off pod hardware the same cross-process runtime can be exercised with the
CPU backend -- one OS process per simulated "host", each owning a few forced
CPU devices (this is how tests/test_multihost_processes.py drives it):

    python examples/multihost_sim.py --coordinator 127.0.0.1:8476 \
        --num-processes 2 --process-id $RANK --cpu-devices-per-host 2 --n 256

The sharded round step row-shards the per-edge state over every mesh axis
and performs one reduction naming both axes; XLA decomposes it into an
intra-host ICI reduction plus a cross-host DCN exchange (see
rapid_tpu/shard/engine.py).
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--coordinator", help="host:port of process 0")
    parser.add_argument("--num-processes", type=int)
    parser.add_argument("--process-id", type=int)
    parser.add_argument("--n", type=int, default=10_000)
    parser.add_argument("--fail-fraction", type=float, default=0.01)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument(
        "--cpu-devices-per-host", type=int, default=0,
        help="force the CPU backend with this many local devices per "
        "process (multi-host validation without pod hardware)",
    )
    args = parser.parse_args()

    if args.cpu_devices_per_host:
        # Pin the CPU backend BEFORE anything initializes it: the config
        # value (not the JAX_PLATFORMS env var, which an injected
        # accelerator plugin can bypass) is what backends() respects --
        # and jax.distributed.initialize below must run before backend
        # init, so __graft_entry__._force_cpu_mesh (which initializes to
        # assert) cannot be used on this path.
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu_devices_per_host)

    from rapid_tpu.shard.engine import make_multihost_mesh
    from rapid_tpu.sim.driver import Simulator

    mesh = make_multihost_mesh(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    n_dev = int(np.prod(list(mesh.shape.values())))
    capacity = ((args.n + n_dev - 1) // n_dev) * n_dev  # divisible over mesh
    print(f"mesh {dict(mesh.shape)}; {args.n} members in capacity {capacity}")

    sim = Simulator(args.n, capacity=capacity, seed=args.seed, mesh=mesh)
    rng = np.random.default_rng(args.seed)
    victims = rng.choice(args.n, max(1, int(args.n * args.fail_fraction)), replace=False)
    sim.crash(victims)
    record = sim.run_until_decision(max_rounds=16, batch=16)
    assert record is not None and set(record.cut) == set(victims)
    print(
        f"cut {len(record.cut)} nodes in {record.virtual_time_ms} ms protocol "
        f"time ({record.wall_time_s * 1e3:.1f} ms wall); "
        f"config {record.configuration_id}"
    )


if __name__ == "__main__":
    main()
