"""Run the membership simulator over a multi-host ("dcn", "ici") mesh.

On a TPU pod slice, launch one copy of this script per host:

    python examples/multihost_sim.py --coordinator host0:8476 \
        --num-processes 4 --process-id $RANK --n 400000

Single-host (or the forced CPU backend) needs no flags: the degenerate
1-host mesh runs the identical sharded program.

The sharded round step row-shards the per-edge state over every mesh axis
and performs one reduction naming both axes; XLA decomposes it into an
intra-host ICI reduction plus a cross-host DCN exchange (see
rapid_tpu/shard/engine.py).
"""

import argparse

import numpy as np


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--coordinator", help="host:port of process 0")
    parser.add_argument("--num-processes", type=int)
    parser.add_argument("--process-id", type=int)
    parser.add_argument("--n", type=int, default=10_000)
    parser.add_argument("--fail-fraction", type=float, default=0.01)
    parser.add_argument("--seed", type=int, default=42)
    args = parser.parse_args()

    from rapid_tpu.shard.engine import make_multihost_mesh
    from rapid_tpu.sim.driver import Simulator

    mesh = make_multihost_mesh(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    n_dev = int(np.prod(list(mesh.shape.values())))
    capacity = ((args.n + n_dev - 1) // n_dev) * n_dev  # divisible over mesh
    print(f"mesh {dict(mesh.shape)}; {args.n} members in capacity {capacity}")

    sim = Simulator(args.n, capacity=capacity, seed=args.seed, mesh=mesh)
    rng = np.random.default_rng(args.seed)
    victims = rng.choice(args.n, max(1, int(args.n * args.fail_fraction)), replace=False)
    sim.crash(victims)
    record = sim.run_until_decision(max_rounds=16, batch=16)
    assert record is not None and set(record.cut) == set(victims)
    print(
        f"cut {len(record.cut)} nodes in {record.virtual_time_ms} ms protocol "
        f"time ({record.wall_time_s * 1e3:.1f} ms wall); "
        f"config {record.configuration_id}"
    )


if __name__ == "__main__":
    main()
