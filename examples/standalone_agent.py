"""Standalone cluster agent (see rapid_tpu/cli/agent.py for the
implementation; this shim keeps the reference's examples/ layout).

    python examples/standalone_agent.py --listen-address 127.0.0.1:1234
    python examples/standalone_agent.py --listen-address 127.0.0.1:1235 \
        --seed-address 127.0.0.1:1234
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root

from rapid_tpu.cli.agent import main

if __name__ == "__main__":
    main()
