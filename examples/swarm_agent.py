"""A real membership node inside a TPU-hosted virtual swarm.

Demonstrates the TpuSimMessaging bridge (rapid_tpu/sim/bridge.py): a node
built on the standard Cluster API joins a swarm of N simulated virtual peers,
watches a correlated crash burst get cut by the simulated protocol, then
leaves gracefully. Everything crosses the same two plugin seams a real
deployment would use (messaging + failure detection); configuration ids stay
bit-identical between the real node and the device-resident simulation.

    python examples/swarm_agent.py --virtual-nodes 1000 --crash-percent 1
"""

from __future__ import annotations

import argparse
import random
import sys

import numpy as np

sys.path.insert(0, ".")

from rapid_tpu import ClusterBuilder, Endpoint, Settings  # noqa: E402
from rapid_tpu.events import ClusterEvents  # noqa: E402
from rapid_tpu.messaging.inprocess import (  # noqa: E402
    InProcessClient,
    InProcessNetwork,
    InProcessServer,
)
from rapid_tpu.runtime.scheduler import VirtualScheduler  # noqa: E402
from rapid_tpu.sim.bridge import TpuSimMessaging  # noqa: E402


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--virtual-nodes", type=int, default=1000)
    parser.add_argument("--crash-percent", type=float, default=1.0)
    parser.add_argument("--seed", type=int, default=42)
    args = parser.parse_args()

    scheduler = VirtualScheduler()
    network = InProcessNetwork(scheduler)
    print(f"hosting {args.virtual_nodes} virtual nodes on the device ...")
    swarm = TpuSimMessaging(
        network,
        n_virtual=args.virtual_nodes,
        capacity=args.virtual_nodes + 16,
        seed=args.seed,
    )

    address = Endpoint.from_parts("real-node", 9000)
    settings = Settings()
    builder = (
        ClusterBuilder(address)
        .set_messaging_client_and_server(
            InProcessClient(address, network, settings),
            InProcessServer(address, network),
        )
        .use_scheduler(scheduler)
        .use_settings(settings)
        .use_rng(random.Random(args.seed))
        .add_subscription(
            ClusterEvents.VIEW_CHANGE,
            lambda cid, changes: print(
                f"  VIEW_CHANGE config={cid} changes={len(changes)}"
            ),
        )
    )

    promise = builder.join_async(swarm.endpoint(0))
    scheduler.run_for(50)
    record = swarm.pump()
    joined = scheduler.run_until(promise.done, 10_000)
    assert record is not None and joined
    cluster = promise.result(0)
    print(
        f"joined: {cluster.get_membership_size()} members, "
        f"config id {cluster.get_current_configuration_id()} "
        f"(swarm agrees: {cluster.get_current_configuration_id() == swarm.sim.configuration_id()})"
    )

    n_crash = max(1, int(args.virtual_nodes * args.crash_percent / 100))
    victims = np.random.default_rng(args.seed).choice(
        args.virtual_nodes, size=n_crash, replace=False
    )
    print(f"crashing {n_crash} virtual nodes ...")
    swarm.sim.crash(victims)
    record = swarm.pump(max_rounds=16, batch=16)
    if record is None or set(record.cut) != set(victims):
        raise RuntimeError(f"unexpected cut: {record}")
    scheduler.run_for(500)  # the real node tallies the swarm's votes
    print(
        f"cut decided in {record.virtual_time_ms} virtual ms; real node now "
        f"sees {cluster.get_membership_size()} members "
        f"(parity: {cluster.get_current_configuration_id() == swarm.sim.configuration_id()})"
    )

    done = cluster.leave_gracefully_async()
    scheduler.run_for(50)
    swarm.pump(max_rounds=8)
    scheduler.run_until(done.done, 30_000)
    print(f"left gracefully; swarm is back to {swarm.sim.membership_size} members")


if __name__ == "__main__":
    main()
