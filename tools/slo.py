#!/usr/bin/env python
"""rapid-slo: the SLO plane's alert view over the cluster-status RPC.

Polls one or more members and renders each node's burn-rate alerts with
their churn-episode attribution, correlated against the same journal tail
the status response carries -- the operator's one-liner for "are we
burning budget, and which membership event did it":

    SLO burning: p99 latency (serving.latency:fast, burn 42.1x),
      attributed to view-change episode 7 (3 nodes evicted, 41 partitions moved)

    python tools/slo.py 127.0.0.1:1234 127.0.0.1:1235
    python tools/slo.py --json 127.0.0.1:1234

Exit code 0 when no alert is firing anywhere, 1 on unreachable targets,
3 when any member reports a firing burn alert (greppable for probes).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # runnable as a script from anywhere in the tree
    sys.path.insert(0, _REPO)

from rapid_tpu import Endpoint, Settings  # noqa: E402
from rapid_tpu.messaging.tcp import TcpClientServer  # noqa: E402
from rapid_tpu.slo import describe, episodes_from_journal  # noqa: E402
from rapid_tpu.types import ClusterStatusResponse  # noqa: E402

if __package__ in (None, ""):
    from statusz import fetch_status
else:  # pragma: no cover - imported as a package module
    from .statusz import fetch_status

# human labels for the declared SLOs (fallback: the catalog name itself)
SLO_LABELS = {
    "serving.latency": "p99 latency",
    "serving.availability": "availability",
}


def render_slo(status: ClusterStatusResponse) -> str:
    """Pure renderer: one line per (SLO, window-pair) alert, firing alerts
    first, each attributed against the episodes parsed from the journal
    tail the same response carries."""
    lines = [f"{status.sender}  config={status.configuration_id}"]
    if not status.slo_names:
        lines.append("  (no SLO plane -- settings.slo.enabled is off)")
        return "\n".join(lines)
    episodes = episodes_from_journal(status.journal)
    by_trace = {int(e.trace_id): e for e in episodes if e.trace_id}
    rows = sorted(
        zip(status.slo_names, status.slo_burn_milli, status.slo_firing,
            status.slo_attributed_trace),
        key=lambda row: (-row[2], row[0]),
    )
    for name, burn_milli, firing, trace in rows:
        slo, _, window = name.partition(":")
        label = SLO_LABELS.get(slo, slo)
        burn = burn_milli / 1000.0
        if firing:
            episode = by_trace.get(int(trace))
            attributed = (
                describe(episode) if episode is not None
                else f"episode trace {trace}" if trace
                else "unattributed (no overlapping membership episode)"
            )
            lines.append(
                f"  SLO burning: {label} ({name}, burn {burn:.1f}x), "
                f"attributed to {attributed}"
            )
        else:
            lines.append(f"  SLO ok: {label} ({name}) burn={burn:.2f}x")
    return "\n".join(lines)


def to_json(status: ClusterStatusResponse) -> dict:
    episodes = episodes_from_journal(status.journal)
    by_trace = {int(e.trace_id): e for e in episodes if e.trace_id}
    alerts = {}
    for name, burn_milli, firing, trace in zip(
        status.slo_names, status.slo_burn_milli, status.slo_firing,
        status.slo_attributed_trace,
    ):
        episode = by_trace.get(int(trace)) if trace else None
        alerts[name] = {
            "burn": burn_milli / 1000.0,
            "firing": bool(firing),
            "attributed_trace": int(trace),
            "attributed": describe(episode) if episode is not None else None,
        }
    return {
        "node": str(status.sender),
        "configuration_id": status.configuration_id,
        "alerts": alerts,
        "firing": sum(1 for a in alerts.values() if a["firing"]),
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="poll rapid-tpu agents' SLO burn-rate alerts"
    )
    parser.add_argument("targets", nargs="+", help="host:port of live agents")
    parser.add_argument("--timeout", type=float, default=5.0)
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit one JSON object per target")
    args = parser.parse_args(argv)
    # client half only: no start() means no listening socket is ever bound
    client = TcpClientServer(Endpoint(b"127.0.0.1", 0), Settings())
    rc = 0
    firing_total = 0
    try:
        for raw in args.targets:
            target = Endpoint.from_string(raw)
            try:
                status = fetch_status(client, target, args.timeout)
            except Exception as exc:  # noqa: BLE001 -- report, keep polling
                print(f"{raw}: unreachable ({exc})", file=sys.stderr)
                rc = 1
                continue
            firing_total += sum(status.slo_firing)
            if args.as_json:
                print(json.dumps(to_json(status), sort_keys=True))
            else:
                print(render_slo(status))
    finally:
        client.shutdown()
    if firing_total:
        print(
            f"WARNING: {firing_total} burn alert(s) firing", file=sys.stderr
        )
        rc = max(rc, 3)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
