#!/usr/bin/env python
"""perfscope: render, diff, and regression-gate rapid-tpu profiling data.

Three subcommands over the profiling plane's artifacts:

``render`` -- per-phase device attribution as a flamegraph-style breakdown.
Input is any JSON file carrying ``profile.phase_ms{phase=...}`` histograms:
an ``observability.json_snapshot()`` dump, a ``tools/statusz.py --json``
line (the last scraped history snapshot is used), or raw
``MetricsHistory.to_wire`` lines. ``--trace-out`` additionally writes a
Chrome-trace (chrome://tracing / Perfetto) file with one slice per phase,
scaled to the measured mean, so the breakdown is inspectable next to any
device trace.

``diff`` -- compare two bench JSON artifacts (the single line bench.py
prints): headline wall, per-size sweep walls, and compile counts, with a
regression threshold (rc 3 when the new artifact is slower beyond it).

``check`` -- gate one bench artifact against BASELINE.json's north-star
budget plus the per-dimension budget table (DIMENSION_BUDGETS: serving
tail latency, lost acked writes, SLO availability/goodput and firing burn
alerts, messaging throughput, gray-detection speedup), rc 3 on any
breach -- the CI-shaped form of the same comparison.

``trend`` -- the headline + per-dimension trajectory across a SERIES of
bench runs (the repo's BENCH_rNN.json wrappers or raw bench lines), so
the perf history stops being hand-maintained prose. Runs whose wrapper
carries rc 17 (bench.py's accelerator-unreachable watchdog exit) or no
parseable artifact are rendered as OUTAGE markers -- an unreachable
device is an environment fact, never plotted as a regression; rc 3 only
when two *measured* neighbours drift beyond the threshold.

    python tools/perfscope.py render metrics.json
    python tools/perfscope.py diff old_bench.json new_bench.json
    python tools/perfscope.py check bench.json
    python tools/perfscope.py trend BENCH_r*.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # runnable as a script from anywhere in the tree
    sys.path.insert(0, _REPO)

# phase order matches the pipeline: profiling/phases.py PHASES
PHASE_ORDER = ("fd_scan", "cut_detector", "consensus_count", "host_transfer")
BAR_WIDTH = 24
DEFAULT_THRESHOLD = 0.10  # 10% slower = regression
NORTH_STAR_BUDGET_MS = 5000.0  # BASELINE.json: "converging ... in <5s"


def parse_rendered(name: str) -> Tuple[str, Dict[str, str]]:
    """``name{k=v,...}`` -> (base name, labels). The inverse of
    observability._render for the label values profiling emits."""
    if "{" not in name or not name.endswith("}"):
        return name, {}
    base, raw = name[:-1].split("{", 1)
    labels: Dict[str, str] = {}
    for part in raw.split(","):
        if "=" in part:
            key, value = part.split("=", 1)
            labels[key] = value
    return base, labels


def _hist_count_sum(value: object) -> Optional[Tuple[float, float]]:
    """(count, sum) from either exporter dialect: json_snapshot's
    {"count","sum",...} dict or the history ring's [count, sum] pair."""
    if isinstance(value, dict) and "count" in value and "sum" in value:
        return float(value["count"]), float(value["sum"])
    if isinstance(value, (list, tuple)) and len(value) == 2:
        return float(value[0]), float(value[1])
    return None


def extract_phases(doc: object) -> Tuple[Dict[str, Tuple[float, float]], Optional[Tuple[float, float]]]:
    """Pull ``profile.phase_ms`` per-phase (count, sum) and the
    ``profile.step_ms`` (count, sum) out of whatever profiling artifact the
    caller loaded (see module docstring for the accepted shapes)."""
    hists: Dict[str, object] = {}
    if isinstance(doc, dict):
        if isinstance(doc.get("histograms"), dict):  # json_snapshot dump
            hists = doc["histograms"]
        elif isinstance(doc.get("history"), list) and doc["history"]:
            last = doc["history"][-1]  # statusz --json: newest snapshot
            if isinstance(last, dict) and isinstance(
                last.get("histograms"), dict
            ):
                hists = last["histograms"]
    elif isinstance(doc, list) and doc:  # raw history snapshot list
        last = doc[-1]
        if isinstance(last, dict) and isinstance(last.get("histograms"), dict):
            hists = last["histograms"]
    phases: Dict[str, Tuple[float, float]] = {}
    step: Optional[Tuple[float, float]] = None
    for rendered, value in hists.items():
        base, labels = parse_rendered(str(rendered))
        pair = _hist_count_sum(value)
        if pair is None:
            continue
        if base == "profile.phase_ms" and "phase" in labels:
            prev = phases.get(labels["phase"], (0.0, 0.0))
            phases[labels["phase"]] = (prev[0] + pair[0], prev[1] + pair[1])
        elif base == "profile.step_ms":
            prev = step if step is not None else (0.0, 0.0)
            step = (prev[0] + pair[0], prev[1] + pair[1])
    return phases, step


def load_profile_doc(path: str) -> object:
    """A profiling artifact: one JSON document, or JSON lines (a scraped
    history carriage / several statusz lines -- the last parseable line
    wins, matching 'newest snapshot')."""
    text = open(path).read()
    try:
        return json.loads(text)
    except ValueError:
        docs = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                docs.append(json.loads(line))
            except ValueError:
                continue
        return docs


def render_breakdown(phases: Dict[str, Tuple[float, float]],
                     step: Optional[Tuple[float, float]]) -> str:
    """The flamegraph-style per-phase breakdown: one bar per phase, widths
    proportional to total attributed wall time."""
    rows = [
        (phase, phases[phase])
        for phase in PHASE_ORDER
        if phase in phases
    ] + sorted(
        (phase, pair) for phase, pair in phases.items()
        if phase not in PHASE_ORDER
    )
    total_ms = sum(pair[1] for _, pair in rows)
    lines = ["per-phase device attribution:"]
    if not rows or total_ms <= 0:
        lines.append("  (no profile.phase_ms samples -- profiling off?)")
        return "\n".join(lines)
    width = max(len(name) for name, _ in rows)
    for name, (count, total) in rows:
        frac = total / total_ms
        bar = "#" * max(1, round(frac * BAR_WIDTH))
        mean = total / count if count else 0.0
        lines.append(
            f"  {name:<{width}}  {bar:<{BAR_WIDTH}}  {frac * 100:5.1f}%"
            f"  mean {mean:.3f}ms  n={int(count)}"
        )
    if step is not None and step[0] > 0:
        step_mean = step[1] / step[0]
        device_ms = sum(
            pair[1] for name, pair in rows if name != "host_transfer"
        )
        device_n = max(
            (pair[0] for name, pair in rows if name != "host_transfer"),
            default=0.0,
        )
        device_mean = device_ms / device_n if device_n else 0.0
        coverage = (device_mean / step_mean * 100.0) if step_mean else 0.0
        lines.append(
            f"  device step: mean {step_mean:.3f}ms (profile.step_ms,"
            f" n={int(step[0])}); device phases cover {coverage:.1f}%"
        )
    return "\n".join(lines)


def chrome_trace_events(phases: Dict[str, Tuple[float, float]]) -> Dict[str, object]:
    """One synthetic 'mean dispatch' frame as Chrome-trace complete events:
    the device phases stacked sequentially (they really are sequential
    prefixes of one step), host_transfer after them."""
    events: List[Dict[str, object]] = []
    cursor_us = 0.0
    for phase in PHASE_ORDER:
        pair = phases.get(phase)
        if pair is None or pair[0] <= 0:
            continue
        mean_us = pair[1] / pair[0] * 1000.0
        events.append({
            "name": phase, "ph": "X", "pid": 0, "tid": 0,
            "ts": cursor_us, "dur": mean_us,
            "cat": "profile", "args": {"samples": int(pair[0])},
        })
        cursor_us += mean_us
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# --------------------------------------------------------------------------- #
# bench artifact diffing
# --------------------------------------------------------------------------- #


def _bench_line(text: str) -> Optional[dict]:
    """The first line of ``text`` that parses as a bench artifact (a dict
    with a 'metric' key), or None -- shared by the file and wrapper-tail
    loaders."""
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict) and "metric" in doc:
            return doc
    return None


def load_bench_artifact(path: str) -> dict:
    """The bench's single JSON line (tolerating surrounding log lines: the
    first line that parses as a dict with a 'metric' key wins). Also
    accepts the repo's BENCH_rNN.json run wrapper, unwrapping its
    "parsed" artifact (or re-scanning its "tail" for older wrappers)."""
    text = open(path).read()
    try:
        whole = json.loads(text)
    except ValueError:
        whole = None
    if isinstance(whole, dict) and "rc" in whole and "tail" in whole:
        doc = whole.get("parsed")
        if not isinstance(doc, dict):
            doc = _bench_line(str(whole.get("tail", "")))
    else:
        doc = _bench_line(text)
    if doc is None:
        raise ValueError(f"{path}: no bench JSON artifact line found")
    return doc


# rc 17 is bench.py's watchdog exit: the accelerator never answered, so
# the run measured the environment, not the code (BENCH_r03-r05 carry it)
OUTAGE_RC = 17


def load_trend_entry(path: str) -> dict:
    """One point on the perf-history trajectory. Accepts the repo's
    BENCH_rNN.json run wrapper ({"n", "rc", "tail", "parsed"}) or a raw
    bench artifact file; returns {path, n, rc, artifact} where artifact is
    None for an outage (watchdog rc, or nothing parseable)."""
    text = open(path).read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "rc" in doc and "tail" in doc:
        artifact = doc.get("parsed")
        if not isinstance(artifact, dict):  # older wrappers: re-scan tail
            artifact = _bench_line(str(doc.get("tail", "")))
        rc = int(doc.get("rc", 0))
        if rc != 0:
            artifact = None  # a failed run's partial line proves nothing
        return {"path": path, "n": doc.get("n"), "rc": rc,
                "artifact": artifact}
    artifact = _bench_line(text)
    return {"path": path, "n": None, "rc": 0 if artifact else None,
            "artifact": artifact}


def trend_report(entries: List[dict],
                 threshold: float = DEFAULT_THRESHOLD) -> Tuple[str, List[str]]:
    """The trajectory report plus regression descriptions. Entries sort by
    run number (wrapper "n") with path as tiebreaker; outage entries are
    rendered in place but never compared -- each measured run diffs
    against the previous *measured* run, skipping outages between them."""
    entries = sorted(
        entries,
        key=lambda e: (e["n"] if isinstance(e["n"], int) else 1 << 30,
                       e["path"]),
    )
    measured = [e for e in entries if e["artifact"] is not None]
    lines: List[str] = [
        f"bench trend across {len(entries)} runs "
        f"({len(measured)} measured, {len(entries) - len(measured)} outage):"
    ]
    regressions: List[str] = []

    def run_label(entry: dict) -> str:
        if isinstance(entry["n"], int):
            return f"r{entry['n']:02d}"
        name = entry["path"].rsplit("/", 1)[-1]
        return name[:-5] if name.endswith(".json") else name

    width = max((len(run_label(e)) for e in entries), default=3)
    headline_max = max(
        (float(e["artifact"].get("value") or 0.0) for e in measured),
        default=0.0,
    )
    prev: Optional[dict] = None
    for entry in entries:
        label = run_label(entry)
        artifact = entry["artifact"]
        if artifact is None or artifact.get("value") is None:
            why = (
                f"rc {entry['rc']}" if entry["rc"] not in (0, None)
                else "no artifact"
            )
            lines.append(
                f"  {label:<{width}}  {'OUTAGE':<{BAR_WIDTH}}  ({why}: "
                "accelerator/environment, not a perf point)"
            )
            continue
        value = float(artifact["value"])
        bar = "#" * max(
            1, round(value / headline_max * BAR_WIDTH)
        ) if headline_max > 0 else ""
        suffix = ""
        if prev is not None:
            old_v = float(prev["artifact"]["value"])
            pct = (value - old_v) / old_v * 100.0 if old_v else 0.0
            suffix = f"  ({pct:+.1f}% vs {run_label(prev)})"
            if old_v > 0 and value > old_v * (1.0 + threshold):
                regressions.append(
                    f"headline {run_label(prev)} -> {label}: "
                    f"{old_v:.1f} -> {value:.1f} ms"
                )
        lines.append(
            f"  {label:<{width}}  {bar:<{BAR_WIDTH}}  {value:8.1f} ms"
            f"{suffix}"
        )
        prev = entry
    # per-dimension trajectories: every budget-table path any measured
    # artifact carries, one row per dimension leaf
    seen_paths: List[Tuple[str, Tuple[str, ...]]] = []
    for dimension, path, _, _ in DIMENSION_BUDGETS:
        if (dimension, path) in seen_paths:
            continue
        if any(_walk(e["artifact"], path) is not None for e in measured):
            seen_paths.append((dimension, path))
    for dimension, path in seen_paths:
        label = ".".join(path)
        points = []
        for entry in entries:
            if entry["artifact"] is None:
                points.append(f"{run_label(entry)}=outage")
                continue
            got = _walk(entry["artifact"], path)
            points.append(
                f"{run_label(entry)}={got:g}" if got is not None
                else f"{run_label(entry)}=--"
            )
        lines.append(f"  {dimension:<9} {label}: {' '.join(points)}")
    return "\n".join(lines), regressions


# Per-dimension budgets for the ``check`` subcommand, beyond the headline
# north-star gate. Each row is (dimension, path, op, limit): ``path`` walks
# the bench artifact dict; a row whose path is absent is skipped (partial
# or outage artifacts gate only on what they carry), a present leaf must
# satisfy ``op limit`` or check exits 3. Limits are deliberately loose
# floors/ceilings -- they catch order-of-magnitude regressions and
# invariant breaks (lost acked writes, an SLO burn alert still firing at
# end of run), not machine-to-machine jitter; ``diff`` is the tool for
# relative drift.
DIMENSION_BUDGETS: Tuple[Tuple[str, Tuple[str, ...], str, float], ...] = (
    ("serving", ("serving_qps", "steady", "p99_ms"), "<=", 25.0),
    ("serving", ("serving_qps", "lost_acked_writes"), "<=", 0.0),
    ("serving", ("serving_qps", "throughput_qps"), ">=", 100.0),
    ("slo", ("serving_qps", "slo", "serving.availability", "availability"),
     ">=", 0.99),
    ("slo", ("serving_qps", "slo", "serving.availability", "goodput_ratio"),
     ">=", 0.95),
    ("slo", ("serving_qps", "slo", "serving.latency", "alerts", "fast",
             "firing"), "<=", 0.0),
    ("messaging", ("messaging_throughput", "broadcast_storm",
                   "messages_per_s"), ">=", 1.0),
    ("gray", ("gray_detection_ms", "gray_slow_node", "speedup"), ">=", 2.0),
    ("gray", ("gray_detection_ms", "gray_flapping", "speedup"), ">=", 2.0),
    # hierarchy dimension: the flat-vs-hierarchical A/B must seat >= 10x
    # the flat anchor's members, reach composed agreement within a loose
    # protocol-time ceiling (FD detection dominates at ~10-11s virtual;
    # the ceiling catches a detection/agreement blowup, not jitter), and
    # bill at least one parent round doing it
    ("hierarchy", ("hierarchy_scale", "member_ceiling_ratio"), ">=", 10.0),
    ("hierarchy", ("hierarchy_scale", "agreement_virtual_ms"), "<=", 15000.0),
    ("hierarchy", ("hierarchy_scale", "hierarchical", "parent_rounds"),
     ">=", 1.0),
)

_BUDGET_OPS = {
    "<=": lambda got, limit: got <= limit,
    ">=": lambda got, limit: got >= limit,
}


def _walk(doc: object, path: Tuple[str, ...]) -> Optional[float]:
    """Dict-walk ``path`` into a bench artifact; numeric leaf (bools count
    as 0/1) or None when any step is missing."""
    node = doc
    for key in path:
        if not isinstance(node, dict) or key not in node:
            return None
        node = node[key]
    if isinstance(node, (bool, int, float)):
        return float(node)
    return None


def check_budgets(doc: dict, budget_ms: float = NORTH_STAR_BUDGET_MS
                  ) -> Tuple[List[str], List[str]]:
    """The headline north-star gate plus every DIMENSION_BUDGETS row whose
    path the artifact carries; (report lines, breach descriptions)."""
    lines: List[str] = []
    breaches: List[str] = []
    value = doc.get("value")
    if value is not None:
        verdict = "within" if value <= budget_ms else "OVER"
        lines.append(
            f"headline {value:.1f} ms vs budget {budget_ms:.0f} ms "
            f"({value / budget_ms * 100.0:.1f}%): {verdict}"
        )
        if value > budget_ms:
            breaches.append(f"headline {value:.1f} ms > {budget_ms:.0f} ms")
    for dimension, path, op, limit in DIMENSION_BUDGETS:
        got = _walk(doc, path)
        if got is None:
            continue  # dimension absent from this artifact: nothing to gate
        label = ".".join(path)
        ok = _BUDGET_OPS[op](got, limit)
        lines.append(
            f"{dimension:<9} {label} = {got:g} (budget {op} {limit:g}): "
            f"{'within' if ok else 'OVER'}"
        )
        if not ok:
            breaches.append(
                f"{dimension}: {label} = {got:g}, budget {op} {limit:g}"
            )
    return lines, breaches


def diff_artifacts(old: dict, new: dict,
                   threshold: float = DEFAULT_THRESHOLD) -> Tuple[str, List[str]]:
    """Human-readable diff of two bench artifacts plus the list of
    regression descriptions (new slower than old beyond ``threshold``)."""
    lines: List[str] = []
    regressions: List[str] = []

    def compare(label: str, old_v, new_v) -> None:
        if old_v is None or new_v is None:
            lines.append(f"  {label}: {old_v} -> {new_v}")
            return
        delta = new_v - old_v
        pct = (delta / old_v * 100.0) if old_v else 0.0
        lines.append(
            f"  {label}: {old_v:.1f} -> {new_v:.1f} ms"
            f" ({delta:+.1f}, {pct:+.1f}%)"
        )
        if old_v > 0 and new_v > old_v * (1.0 + threshold):
            regressions.append(f"{label}: {old_v:.1f} -> {new_v:.1f} ms")

    lines.append(
        f"bench diff ({old.get('backend')}/"
        f"{old.get('device_kind')} -> {new.get('backend')}/"
        f"{new.get('device_kind')}):"
    )
    compare("headline", old.get("value"), new.get("value"))
    old_sweep = {
        e["n"]: e for e in old.get("sweep", ())
        if isinstance(e, dict) and "n" in e
    }
    new_sweep = {
        e["n"]: e for e in new.get("sweep", ())
        if isinstance(e, dict) and "n" in e
    }
    for n in sorted(set(old_sweep) | set(new_sweep)):
        a, b = old_sweep.get(n), new_sweep.get(n)
        compare(
            f"sweep n={n}",
            a.get("warmed_wall_ms") if a else None,
            b.get("warmed_wall_ms") if b else None,
        )
        compiles_a = a.get("jit_compiles_steady") if a else None
        compiles_b = b.get("jit_compiles_steady") if b else None
        if compiles_b not in (None, 0) and compiles_b != compiles_a:
            regressions.append(
                f"sweep n={n}: jit_compiles_steady {compiles_a} -> "
                f"{compiles_b} (steady-state recompile)"
            )
    return "\n".join(lines), regressions


# --------------------------------------------------------------------------- #
# CLI
# --------------------------------------------------------------------------- #


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="render/diff/gate rapid-tpu profiling artifacts"
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_render = sub.add_parser("render", help="per-phase attribution breakdown")
    p_render.add_argument("artifact", help="json_snapshot / statusz --json / "
                          "history-lines file")
    p_render.add_argument("--trace-out", default=None,
                          help="also write a Chrome-trace JSON of the phases")

    p_diff = sub.add_parser("diff", help="diff two bench JSON artifacts")
    p_diff.add_argument("old")
    p_diff.add_argument("new")
    p_diff.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                        help="regression threshold as a fraction "
                        f"(default {DEFAULT_THRESHOLD})")

    p_check = sub.add_parser(
        "check", help="gate one bench artifact against the north-star "
        "budget and the per-dimension budget table"
    )
    p_check.add_argument("artifact")
    p_check.add_argument("--budget-ms", type=float, default=NORTH_STAR_BUDGET_MS,
                         help="headline budget (default: the BASELINE.json "
                         "north-star 5000ms)")

    p_trend = sub.add_parser(
        "trend", help="headline + per-dimension trajectory across a series "
        "of bench runs (BENCH_rNN.json wrappers or raw artifacts); outage "
        "runs are marked, never counted as regressions"
    )
    p_trend.add_argument("artifacts", nargs="+",
                         help="bench run files, e.g. BENCH_r*.json")
    p_trend.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                         help="regression threshold between consecutive "
                         f"measured runs (default {DEFAULT_THRESHOLD})")

    args = parser.parse_args(argv)

    if args.cmd == "render":
        phases, step = extract_phases(load_profile_doc(args.artifact))
        print(render_breakdown(phases, step))
        if args.trace_out:
            with open(args.trace_out, "w") as fh:
                json.dump(chrome_trace_events(phases), fh)
            print(f"wrote Chrome trace to {args.trace_out}", file=sys.stderr)
        return 0 if phases else 2

    if args.cmd == "diff":
        text, regressions = diff_artifacts(
            load_bench_artifact(args.old), load_bench_artifact(args.new),
            threshold=args.threshold,
        )
        print(text)
        for reg in regressions:
            print(f"REGRESSION: {reg}", file=sys.stderr)
        return 3 if regressions else 0

    if args.cmd == "trend":
        entries = [load_trend_entry(path) for path in args.artifacts]
        text, regressions = trend_report(entries, threshold=args.threshold)
        print(text)
        for reg in regressions:
            print(f"REGRESSION: {reg}", file=sys.stderr)
        if regressions:
            return 3
        return 0 if any(e["artifact"] for e in entries) else 2

    # check
    doc = load_bench_artifact(args.artifact)
    if doc.get("value") is None:
        print(f"{args.artifact}: no headline value (outage artifact?)",
              file=sys.stderr)
        return 2
    lines, breaches = check_budgets(doc, args.budget_ms)
    for line in lines:
        print(line)
    for breach in breaches:
        print(f"BUDGET BREACH: {breach}", file=sys.stderr)
    return 3 if breaches else 0


if __name__ == "__main__":
    raise SystemExit(main())
