"""Turnkey direct-JVM parity anchor (BASELINE config 1).

Runs the literal BASELINE.json config-1 scenario -- a 10-node localhost ring
of the UNTOUCHED reference agent (`standalone-agent.jar`,
StandaloneAgent.java:94-116) bootstrapped through a rapid-tpu seed over the
wire-compatible gRPC transport, then one crash-stop failure -- and records
cut-set AND configuration-id parity into BASELINE.md.

Parity evidence is direct, not transitive: every surviving JVM agent logs
``View change detected: {changes} {configurationId}``
(StandaloneAgent.java:82-84), so the final configuration id each JVM
process holds is parsed from its own log and compared bit-for-bit against
the rapid-tpu seed's ``get_current_configuration_id()``.

Usage:
    python tools/jvm_anchor.py [--reference /root/reference] [--jar JAR]
                               [--nodes 10] [--no-write] [--keep-logs]

Without a java runtime (this build image has none) the tool SKIPS cleanly,
exit 0, and records the anchor as pending. Where java exists it will use
``--jar``/``$RAPID_TPU_JVM_JAR``, an already-built
``<reference>/examples/target/standalone-agent.jar``, or build one with
maven (`examples/pom.xml:60-89` shades it).
"""

from __future__ import annotations

import argparse
import datetime as _dt
import os
import re
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_MD = os.path.join(REPO, "BASELINE.md")
ANCHOR_RE = re.compile(r"^\*\*Direct JVM anchor\*\*:.*$", re.M)

VIEW_CHANGE_RE = re.compile(r"View change detected: .* (-?\d+)\s*$", re.M)


def record(status: str, write: bool) -> None:
    line = f"**Direct JVM anchor**: {status}"
    print(line)
    if not write:
        return
    text = open(BASELINE_MD).read()
    if ANCHOR_RE.search(text):
        text = ANCHOR_RE.sub(line, text)
    else:
        marker = "## Build targets (from BASELINE.json)"
        addition = f"{line}\n\n{marker}"
        assert marker in text, "BASELINE.md layout changed"
        text = text.replace(marker, addition, 1)
    open(BASELINE_MD, "w").write(text)
    print(f"recorded in {BASELINE_MD}")


def find_or_build_jar(reference: str, jar_arg: str) -> str | None:
    candidates = [
        jar_arg,
        os.environ.get("RAPID_TPU_JVM_JAR", ""),
        os.path.join(reference, "examples", "target", "standalone-agent.jar"),
    ]
    for c in candidates:
        if c and os.path.exists(c):
            return c
    mvn = shutil.which("mvn")
    if mvn is None:
        return None
    print("building standalone-agent.jar with maven (first run is slow)...")
    try:
        subprocess.run(
            [mvn, "-q", "-DskipTests", "package"],
            cwd=reference, check=True, timeout=1800,
        )
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as e:
        print(f"maven build failed: {e}")
        return None
    built = os.path.join(reference, "examples", "target", "standalone-agent.jar")
    return built if os.path.exists(built) else None


def last_config_id(log_path: str) -> int | None:
    try:
        hits = VIEW_CHANGE_RE.findall(open(log_path, errors="replace").read())
    except OSError:
        return None
    return int(hits[-1]) if hits else None


def run_anchor(jar: str, nodes: int, logs_dir: str) -> tuple[bool, str]:
    """The scenario. Returns (ok, summary)."""
    sys.path.insert(0, REPO)
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from harness import free_port_base  # noqa: E402

    from rapid_tpu import ClusterBuilder, Endpoint, Settings  # noqa: E402
    from rapid_tpu.messaging.grpc_transport import (  # noqa: E402
        GrpcClient,
        GrpcServer,
    )

    java = shutil.which("java")
    base = free_port_base(nodes + 1)
    seed_addr = Endpoint.from_parts("127.0.0.1", base)
    settings = Settings()
    seed = (
        ClusterBuilder(seed_addr)
        .use_settings(settings)
        .set_messaging_client_and_server(
            GrpcClient(seed_addr, settings), GrpcServer(seed_addr)
        )
        .start()
    )
    procs: list[subprocess.Popen] = []
    logs: list[str] = []
    try:
        for i in range(1, nodes):
            log_path = os.path.join(logs_dir, f"agent-{i}.log")
            logs.append(log_path)
            log = open(log_path, "w")
            procs.append(
                subprocess.Popen(
                    [
                        java, "-jar", jar,
                        "--listenAddress", f"127.0.0.1:{base + i}",
                        "--seedAddress", f"127.0.0.1:{base}",
                    ],
                    stdout=log, stderr=subprocess.STDOUT,
                )
            )
            # stagger slightly: the reference's own integration harness
            # boots agents sequentially (RapidNodeRunner.java:64-87)
            time.sleep(0.5)
        deadline = time.time() + 180
        while time.time() < deadline and seed.get_membership_size() != nodes:
            for i, p in enumerate(procs):
                if p.poll() is not None:
                    return False, f"agent {i + 1} exited early (see {logs[i]})"
            time.sleep(0.5)
        if seed.get_membership_size() != nodes:
            return False, f"bootstrap incomplete: {seed.get_membership_size()}/{nodes}"

        # crash-stop the last agent (config 1's single failure)
        victim = procs.pop()
        victim_ep = Endpoint.from_parts("127.0.0.1", base + nodes - 1)
        victim_log = logs.pop()
        victim.kill()
        victim.wait(timeout=10)
        deadline = time.time() + 120
        while time.time() < deadline and seed.get_membership_size() != nodes - 1:
            time.sleep(0.5)
        members = seed.get_memberlist()
        if len(members) != nodes - 1 or victim_ep in members:
            return False, (
                f"cut not applied: size {len(members)}, victim present: "
                f"{victim_ep in members}"
            )
        # settle, then compare configuration ids bit-for-bit
        time.sleep(3.0)
        seed_config = seed.get_current_configuration_id()
        jvm_configs = {p: last_config_id(p) for p in logs}
        mismatched = {
            p: c for p, c in jvm_configs.items() if c != seed_config
        }
        if mismatched:
            return False, (
                f"config-id mismatch: seed {seed_config}, JVM logs "
                f"{ {os.path.basename(p): c for p, c in mismatched.items()} }"
            )
        return True, (
            f"{nodes}-node ring, 1 crash-stop: cut exact "
            f"(victim removed everywhere), configuration id {seed_config} "
            f"bit-identical across the rapid-tpu seed and "
            f"{len(logs)} surviving JVM agents"
        )
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        seed.shutdown()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--reference", default="/root/reference")
    ap.add_argument("--jar", default="")
    ap.add_argument("--nodes", type=int, default=10)
    ap.add_argument("--no-write", action="store_true",
                    help="print the anchor row; do not touch BASELINE.md")
    ap.add_argument("--keep-logs", action="store_true")
    args = ap.parse_args()
    write = not args.no_write
    today = _dt.date.today().isoformat()

    if shutil.which("java") is None:
        record(
            "pending — no java runtime in this environment; run "
            "`python tools/jvm_anchor.py` wherever java (and the jar or "
            "maven) is available",
            write,
        )
        print("SKIP: no java runtime on PATH")
        return 0
    jar = find_or_build_jar(args.reference, args.jar)
    if jar is None:
        record(
            "pending — java present but standalone-agent.jar not found and "
            "maven unavailable/failed; pass --jar or install maven",
            write,
        )
        print("SKIP: no standalone-agent.jar")
        return 0

    logs_dir = (
        tempfile.mkdtemp(prefix="jvm_anchor_")
        if not args.keep_logs
        else os.path.join(REPO, "jvm_anchor_logs")
    )
    os.makedirs(logs_dir, exist_ok=True)
    print(f"jar: {jar}\nlogs: {logs_dir}")
    ok, summary = run_anchor(jar, args.nodes, logs_dir)
    if ok:
        record(f"verified {today} — {summary}", write)
        return 0
    record(f"FAILED {today} — {summary}", write)
    return 1


if __name__ == "__main__":
    sys.exit(main())
