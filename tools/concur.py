"""Concurrency correctness analyzer (stdlib-only, AST-based).

The static half of the PR-7 concurrency suite (the runtime half is
rapid_tpu/runtime/lockdep.py). It inventories every lock attribute created in
``rapid_tpu/`` (``threading.Lock/RLock/Condition`` or the ``make_lock`` /
``make_rlock`` / ``make_condition`` lockdep seam), builds an interprocedural
lock-acquisition graph, classifies which *execution context* each method runs
in (thread target, timer callback, pool submit, transport callback, the
serialized protocol executor, plain caller), and reports:

- ``lock-order``: cycles in the held-lock -> acquired-lock graph (potential
  deadlocks), propagated through resolvable intra-package calls.
- ``unguarded-write``: an attribute written from >= 2 execution contexts with
  no common lock held and no ``# guarded-by: <x>`` declaration; and writes to
  a ``# guarded-by: <lock-attr>``-declared attribute that do not hold that
  lock.
- ``blocking-under-lock``: blocking operations (socket ops, ``sleep``,
  ``.result()``, ``.wait()`` on anything but the held condition, thread
  ``.join()``) reached while a lock is held, directly or through resolvable
  calls.
- ``unbalanced-acquire``: manual ``.acquire()`` outside ``with`` that has no
  matching ``.release()`` in a ``finally`` block of the same function.
- ``jit-purity``: Python side effects (wall-clock reads, host ``random``,
  ``print``, ``global``, attribute mutation, host syncs like ``.item()`` /
  ``np.asarray``) inside functions staged through ``jax.jit`` /
  ``pallas_call`` / ``shard_map``, which would silently break replay
  determinism (traced once, side effect never replayed).

Conventions the analyzer understands (see ARCHITECTURE.md "Concurrency
discipline"):

- ``# guarded-by: <attr>`` on an attribute's ``__init__`` assignment, where
  ``<attr>`` names a lock attribute of the same class: every later write must
  hold that lock. Any other value (e.g. ``protocol-executor``,
  ``protocol-thread``) declares a serialization discipline the heuristics
  cannot see and exempts the attribute from the multi-context rule.
- A nested ``def task(): ...`` handed to ``*.execute(...)`` runs on the
  single protocol executor: all such tasks share one context.
- ``cond.wait()`` while holding ``cond`` itself is the one legal blocking
  call under a lock.

Suppress single findings with ``# noqa: RULE`` (shared with tools/check.py).

Usage: python tools/concur.py [paths...]   (default: rapid_tpu)
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

if __package__ in (None, ""):
    sys.path.insert(0, str(Path(__file__).resolve().parent))
    from lintlib import Finding, iter_py_files, noqa_lines, parse, suppressed
else:  # pragma: no cover - imported as a package module
    from .lintlib import Finding, iter_py_files, noqa_lines, parse, suppressed

DEFAULT_PATHS = ["rapid_tpu"]

LOCK_FACTORIES = {
    "Lock": "lock", "RLock": "rlock", "Condition": "cond",
    "make_lock": "lock", "make_rlock": "rlock", "make_condition": "cond",
}
LOCKISH_TOKENS = ("lock", "cond", "mutex")

# attribute types that are safe to share without an explicit guard
THREADSAFE_TYPES = {
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "Event",
    "Semaphore", "BoundedSemaphore", "Barrier", "local", "count",
    "ThreadPoolExecutor", "ContextVar",
} | set(LOCK_FACTORIES)

# method calls that mutate their receiver in place
MUTATORS = {
    "append", "add", "pop", "popitem", "clear", "update", "extend",
    "discard", "remove", "insert", "setdefault", "appendleft", "popleft",
    "move_to_end", "sort", "rotate",
}

SOCKET_BLOCKERS = {"recv", "recvfrom", "recv_into", "accept", "connect",
                   "create_connection", "getaddrinfo", "sendall"}

INIT_CTX = "init"


def _name_of(expr: ast.expr) -> Optional[str]:
    """Terminal name of a Name/Attribute chain ('self._x.frob' -> 'frob')."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _is_lockish(name: Optional[str]) -> bool:
    return name is not None and any(t in name.lower() for t in LOCKISH_TOKENS)


def _unparse(expr: ast.expr) -> str:
    try:
        return ast.unparse(expr)
    except Exception:  # noqa: BLE001 - best-effort label only
        return "<expr>"


def _lock_kind_of_value(value: ast.expr) -> Optional[str]:
    """'lock'/'rlock'/'cond' if the assigned value creates a lock."""
    if isinstance(value, ast.Call):
        fname = _name_of(value.func)
        if fname in LOCK_FACTORIES:
            return LOCK_FACTORIES[fname]
    return None


def _class_names_of_value(value: ast.expr) -> Set[str]:
    """Candidate class names instantiated by an assignment's value
    (handles ``A(...)``, ``mod.A(...)``, ``A(...) if c else B(...)``)."""
    out: Set[str] = set()
    if isinstance(value, ast.Call):
        name = _name_of(value.func)
        if name and name[:1].isupper():
            out.add(name)
    elif isinstance(value, ast.IfExp):
        out |= _class_names_of_value(value.body)
        out |= _class_names_of_value(value.orelse)
    return out


class FuncNode:
    """One function/method/nested-def/lambda, with everything the rules need."""

    def __init__(self, qual: str, path: Path, module: str,
                 cls: Optional["ClassInfo"], name: str, node: ast.AST) -> None:
        self.qual = qual
        self.path = path
        self.module = module
        self.cls = cls
        self.name = name
        self.node = node
        self.contexts: Set[str] = set()
        self.inherit_from: List["FuncNode"] = []   # contexts flow from these
        # (descriptor, line, lockids held at the call); descriptor:
        # ("self", m) | ("plain", n) | ("attr", base_attr, m)
        self.calls: List[Tuple[tuple, int, Tuple[str, ...]]] = []
        # calls made while >= 1 lock held: (descriptor, held lockids, line)
        self.calls_under_lock: List[Tuple[tuple, Tuple[str, ...], int]] = []
        self.acquires: Set[str] = set()            # lockids acquired directly
        self.edges: List[Tuple[str, str, int]] = []  # held -> acquired
        # attribute writes: (attr, line, frozenset(held lockids))
        self.writes: List[Tuple[str, int, frozenset]] = []
        # direct blocking ops: (reason, line, held lockids at that point)
        self.blocking: List[Tuple[str, int, Tuple[str, ...]]] = []
        self.manual_acquires: List[Tuple[str, int]] = []   # (recv, line)
        self.finally_releases: Set[str] = set()            # recv strings
        self.trans_acquires: Set[str] = set()
        self.blocks_because: Optional[str] = None

    def __repr__(self) -> str:
        return f"<Func {self.qual} ctx={sorted(self.contexts)}>"


class ClassInfo:
    def __init__(self, name: str, module: str, path: Path,
                 node: ast.ClassDef) -> None:
        self.name = name
        self.module = module
        self.path = path
        self.node = node
        self.methods: Dict[str, FuncNode] = {}
        self.lock_attrs: Dict[str, str] = {}       # attr -> kind
        self.attr_classes: Dict[str, Set[str]] = {}
        self.func_attrs: Dict[str, FuncNode] = {}  # attr -> stored nested def
        self.guards: Dict[str, str] = {}           # attr -> guarded-by value
        self.attr_types_safe: Set[str] = set()     # thread-safe typed attrs
        self.class_guard: Optional[str] = None     # class-wide guarded-by
        self.bases: List[str] = [
            b for b in (_name_of(x) for x in node.bases) if b
        ]


class ModuleInfo:
    def __init__(self, path: Path, stem: str, source: str,
                 tree: ast.Module) -> None:
        self.path = path
        self.stem = stem
        self.source = source
        self.tree = tree
        self.noqa = noqa_lines(source)
        self.guard_comments = _guard_comments(source)
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncNode] = {}
        self.module_locks: Dict[str, str] = {}     # NAME -> kind


def _guard_comments(source: str) -> Dict[int, str]:
    """line -> declared guard from a ``# guarded-by: <x>`` comment."""
    out: Dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), 1):
        if "# guarded-by:" in line:
            _, _, tail = line.partition("# guarded-by:")
            value = tail.split("#")[0].strip()
            if value:
                out[i] = value
    return out


class Analyzer:
    def __init__(self, files: List[Path]) -> None:
        self.modules: List[ModuleInfo] = []
        self.class_registry: Dict[str, List[ClassInfo]] = {}
        self.func_registry: Dict[str, List[FuncNode]] = {}  # by bare name
        self.all_funcs: List[FuncNode] = []
        self.findings: List[Finding] = []
        for f in files:
            try:
                source, tree = parse(f)
            except SyntaxError:
                continue  # tools/check.py owns syntax reporting
            self.modules.append(ModuleInfo(f, f.stem, source, tree))

    # -- reporting ---------------------------------------------------------

    def report(self, mod: ModuleInfo, line: int, rule: str, msg: str) -> None:
        if suppressed(mod.noqa, line, rule):
            return
        self.findings.append(Finding(mod.path, line, rule, msg))

    # -- phase 1: inventory ------------------------------------------------

    def inventory(self) -> None:
        for mod in self.modules:
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    ci = ClassInfo(node.name, mod.stem, mod.path, node)
                    mod.classes[node.name] = ci
                    self.class_registry.setdefault(node.name, []).append(ci)
                    self._inventory_class(mod, ci)
                elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    value = node.value
                    kind = _lock_kind_of_value(value) if value else None
                    if kind:
                        for t in targets:
                            if isinstance(t, ast.Name):
                                mod.module_locks[t.id] = kind
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = FuncNode(f"{mod.stem}::{node.name}", mod.path,
                                  mod.stem, None, node.name, node)
                    mod.functions[node.name] = fn
                    self.func_registry.setdefault(node.name, []).append(fn)
                    self.all_funcs.append(fn)

    def _inventory_class(self, mod: ModuleInfo, ci: ClassInfo) -> None:
        # a guarded-by on the ``class X:`` line declares one serialization
        # discipline for every attribute of the class (e.g. the sim plane)
        ci.class_guard = mod.guard_comments.get(ci.node.lineno)
        for item in ci.node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = FuncNode(f"{mod.stem}::{ci.name}.{item.name}", mod.path,
                              mod.stem, ci, item.name, item)
                ci.methods[item.name] = fn
                self.all_funcs.append(fn)
            elif isinstance(item, (ast.Assign, ast.AnnAssign)):
                # class-level lock attributes (e.g. _SharedAioLoop._lock)
                targets = (item.targets if isinstance(item, ast.Assign)
                           else [item.target])
                value = item.value
                kind = _lock_kind_of_value(value) if value else None
                for t in targets:
                    if isinstance(t, ast.Name):
                        if kind:
                            ci.lock_attrs[t.id] = kind
                        guard = mod.guard_comments.get(item.lineno)
                        if guard:
                            ci.guards[t.id] = guard
        # attribute metadata from every method body (chiefly __init__)
        for meth in ci.methods.values():
            for stmt in ast.walk(meth.node):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                value = stmt.value
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id in ("self", "cls")):
                        continue
                    attr = t.attr
                    if value is not None:
                        kind = _lock_kind_of_value(value)
                        if kind:
                            ci.lock_attrs[attr] = kind
                        for cname in _class_names_of_value(value):
                            ci.attr_classes.setdefault(attr, set()).add(cname)
                        if isinstance(value, ast.Call):
                            vname = _name_of(value.func)
                            if vname in THREADSAFE_TYPES:
                                ci.attr_types_safe.add(attr)
                    guard = mod.guard_comments.get(t.lineno)
                    if guard and attr not in ci.guards:
                        ci.guards[attr] = guard

    # -- phase 2: per-function walk ----------------------------------------

    def scan_bodies(self) -> None:
        for mod in self.modules:
            for ci in mod.classes.values():
                for meth in list(ci.methods.values()):
                    self._walk_function(mod, ci, meth)
            for fn in list(mod.functions.values()):
                self._walk_function(mod, None, fn)

    def _lock_id(self, mod: ModuleInfo, ci: Optional[ClassInfo],
                 fn: FuncNode, expr: ast.expr) -> Optional[str]:
        """Identity of the lock denoted by a ``with`` expression, or None if
        the expression is not lock-like."""
        if isinstance(expr, ast.Attribute):
            base, attr = expr.value, expr.attr
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                if ci is not None and attr in ci.lock_attrs:
                    return f"{ci.name}.{attr}"
                if _is_lockish(attr):
                    owner = ci.name if ci else mod.stem
                    return f"{owner}.{attr}"
                return None
            if _is_lockish(attr):
                # obj.lock -- resolve obj's class if we can
                if isinstance(base, ast.Name):
                    for classes in self._param_classes(fn, base.id):
                        if attr in classes.lock_attrs:
                            return f"{classes.name}.{attr}"
                return f"?.{attr}"
            return None
        if isinstance(expr, ast.Name):
            if expr.id in mod.module_locks:
                return f"{mod.stem}.{expr.id}"
            if _is_lockish(expr.id):
                return f"{mod.stem}.{expr.id}"
            return None
        return None

    def _param_classes(self, fn: FuncNode, pname: str) -> List[ClassInfo]:
        """ClassInfos for a parameter, from its annotation if present."""
        node = fn.node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return []
        for arg in list(node.args.args) + list(node.args.kwonlyargs):
            if arg.arg != pname or arg.annotation is None:
                continue
            ann = arg.annotation
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                name = ann.value.strip().strip('"').strip("'")
            else:
                name = _name_of(ann)
            if name and name in self.class_registry:
                return self.class_registry[name]
        return []

    def _walk_function(self, mod: ModuleInfo, ci: Optional[ClassInfo],
                       fn: FuncNode) -> None:
        node = fn.node
        body = node.body if not isinstance(node, ast.Lambda) else [
            ast.Expr(value=node.body)
        ]
        self._scan_block(mod, ci, fn, body, held=[])

    def _scan_block(self, mod: ModuleInfo, ci: Optional[ClassInfo],
                    fn: FuncNode, stmts: List[ast.stmt],
                    held: List[Tuple[str, str]]) -> None:
        for stmt in stmts:
            self._scan_stmt(mod, ci, fn, stmt, held)

    def _scan_stmt(self, mod: ModuleInfo, ci: Optional[ClassInfo],
                   fn: FuncNode, stmt: ast.stmt,
                   held: List[Tuple[str, str]]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._register_nested(mod, ci, fn, stmt)
            return
        if isinstance(stmt, ast.With):
            pushed = 0
            for item in stmt.items:
                expr = item.context_expr
                lock = self._lock_id(mod, ci, fn, expr)
                if lock is not None:
                    for held_id, _ in held:
                        if held_id != lock:
                            fn.edges.append((held_id, lock, stmt.lineno))
                    fn.acquires.add(lock)
                    held.append((lock, _unparse(expr)))
                    pushed += 1
                else:
                    self._scan_expr(mod, ci, fn, expr, held)
            self._scan_block(mod, ci, fn, stmt.body, held)
            for _ in range(pushed):
                held.pop()
            return
        if isinstance(stmt, ast.Try):
            self._scan_block(mod, ci, fn, stmt.body, held)
            for handler in stmt.handlers:
                self._scan_block(mod, ci, fn, handler.body, held)
            self._scan_block(mod, ci, fn, stmt.orelse, held)
            # note releases that live in a finally block (for the
            # unbalanced-acquire rule)
            for sub in ast.walk(ast.Module(body=stmt.finalbody, type_ignores=[])):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "release"):
                    fn.finally_releases.add(_unparse(sub.func.value))
            self._scan_block(mod, ci, fn, stmt.finalbody, held)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_expr(mod, ci, fn, stmt.test, held)
            self._scan_block(mod, ci, fn, stmt.body, held)
            self._scan_block(mod, ci, fn, stmt.orelse, held)
            return
        if isinstance(stmt, ast.For):
            self._scan_expr(mod, ci, fn, stmt.iter, held)
            self._scan_block(mod, ci, fn, stmt.body, held)
            self._scan_block(mod, ci, fn, stmt.orelse, held)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                self._record_write_target(fn, t, held)
                # ``self.X = <nested def or method ref>`` stores a callable
            if isinstance(stmt, ast.Assign) and ci is not None:
                self._note_stored_func(mod, ci, fn, stmt)
            if stmt.value is not None:
                self._scan_expr(mod, ci, fn, stmt.value, held)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._record_write_target(fn, t, held)
            return
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self._scan_expr(mod, ci, fn, stmt.value, held)
            return
        if isinstance(stmt, ast.Expr):
            self._scan_expr(mod, ci, fn, stmt.value, held)
            return
        # generic: scan any remaining child expressions / blocks
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_expr(mod, ci, fn, child, held)
            elif isinstance(child, ast.stmt):
                self._scan_stmt(mod, ci, fn, child, held)

    def _record_write_target(self, fn: FuncNode, target: ast.expr,
                             held: List[Tuple[str, str]]) -> None:
        locks = frozenset(h for h, _ in held)
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_write_target(fn, elt, held)
            return
        if isinstance(target, ast.Starred):
            self._record_write_target(fn, target.value, held)
            return
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id in ("self", "cls")):
            fn.writes.append((target.attr, target.lineno, locks))
        elif (isinstance(target, ast.Subscript)
              and isinstance(target.value, ast.Attribute)
              and isinstance(target.value.value, ast.Name)
              and target.value.value.id in ("self", "cls")):
            fn.writes.append((target.value.attr, target.lineno, locks))

    def _note_stored_func(self, mod: ModuleInfo, ci: ClassInfo,
                          fn: FuncNode, stmt: ast.Assign) -> None:
        if not (len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Attribute)
                and isinstance(stmt.targets[0].value, ast.Name)
                and stmt.targets[0].value.id == "self"):
            return
        attr = stmt.targets[0].attr
        value = stmt.value
        if isinstance(value, ast.Name):
            nested = getattr(fn, "_locals", {}).get(value.id)
            if nested is not None:
                ci.func_attrs[attr] = nested

    # -- nested defs / context classification ------------------------------

    def _register_nested(self, mod: ModuleInfo, ci: Optional[ClassInfo],
                         parent: FuncNode, node: ast.AST,
                         name: Optional[str] = None) -> FuncNode:
        fname = name or getattr(node, "name", "<lambda>")
        child = FuncNode(f"{parent.qual}.<locals>.{fname}", mod.path,
                         mod.stem, ci, fname, node)
        self.all_funcs.append(child)
        if not hasattr(parent, "_locals"):
            parent._locals = {}
        parent._locals[fname] = child
        self._walk_function(mod, ci, child)
        return child

    def _classify_deferred(self, callee_name: Optional[str],
                           value_name: str) -> str:
        if callee_name is None:
            return f"deferred:{value_name}"
        if callee_name == "Thread":
            return f"thread:{value_name}"
        if callee_name.startswith("schedule"):
            return "timer"
        if callee_name in ("submit", "map"):
            return "pool"
        if callee_name == "execute":
            return "serialized"
        if callee_name in ("add_callback", "add_done_callback"):
            return "callback"
        return f"deferred:{callee_name}"

    def _scan_expr(self, mod: ModuleInfo, ci: Optional[ClassInfo],
                   fn: FuncNode, expr: ast.expr,
                   held: List[Tuple[str, str]]) -> None:
        if isinstance(expr, ast.Lambda):
            child = self._register_nested(mod, ci, fn, expr, name="<lambda>")
            child.inherit_from.append(fn)
            return
        if isinstance(expr, ast.Call):
            self._scan_call(mod, ci, fn, expr, held)
            return
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self._scan_expr(mod, ci, fn, child, held)

    def _scan_call(self, mod: ModuleInfo, ci: Optional[ClassInfo],
                   fn: FuncNode, call: ast.Call,
                   held: List[Tuple[str, str]]) -> None:
        func = call.func
        callee_name = _name_of(func)
        lockids = tuple(h for h, _ in held)

        # ---- callee descriptor for the interprocedural passes
        desc: Optional[tuple] = None
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                desc = ("self", func.attr)
            elif (isinstance(base, ast.Attribute)
                  and isinstance(base.value, ast.Name)
                  and base.value.id in ("self", "cls")):
                desc = ("attr", base.attr, func.attr)
        elif isinstance(func, ast.Name):
            desc = ("plain", func.id)
        if desc is not None:
            fn.calls.append((desc, call.lineno, lockids))
            if lockids:
                fn.calls_under_lock.append((desc, lockids, call.lineno))

        # ---- mutating method on self.X counts as a write to X -- unless X
        # is typed as a package class (its own analysis covers its state)
        if (isinstance(func, ast.Attribute) and func.attr in MUTATORS
                and isinstance(func.value, ast.Attribute)
                and isinstance(func.value.value, ast.Name)
                and func.value.value.id in ("self", "cls")):
            recv_attr = func.value.attr
            in_package = ci is not None and any(
                c in self.class_registry
                for c in ci.attr_classes.get(recv_attr, ())
            )
            if not in_package:
                fn.writes.append((recv_attr, call.lineno, frozenset(lockids)))

        # ---- direct blocking operations
        reason = self._blocking_reason(func, call, held)
        if reason is not None:
            fn.blocking.append((reason, call.lineno, lockids))

        # ---- manual acquire
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            recv = _unparse(func.value)
            if _is_lockish(_name_of(func.value)) or _is_lockish(recv):
                fn.manual_acquires.append((recv, call.lineno))

        # ---- deferred-callable classification for args
        for value in list(call.args) + [kw.value for kw in call.keywords]:
            target_fn = self._resolve_func_ref(mod, ci, fn, value)
            if target_fn is not None:
                kw_names = {id(kw.value): kw.arg for kw in call.keywords}
                ctx = self._classify_deferred(
                    callee_name, target_fn.name
                )
                # Thread(target=...) context gets the target's own name
                if callee_name == "Thread" and kw_names.get(id(value)) != "target":
                    ctx = "callback"
                target_fn.contexts.add(ctx)
            elif isinstance(value, ast.Lambda):
                child = self._register_nested(mod, ci, fn, value)
                child.contexts.add(
                    self._classify_deferred(callee_name, "<lambda>")
                )
            else:
                self._scan_expr(mod, ci, fn, value, held)

        # scan the receiver chain too (e.g. self._x().y())
        if isinstance(func, ast.Attribute):
            self._scan_expr(mod, ci, fn, func.value, held)

    def _resolve_func_ref(self, mod: ModuleInfo, ci: Optional[ClassInfo],
                          fn: FuncNode, value: ast.expr) -> Optional[FuncNode]:
        """A bare reference to a method / nested def passed as a value."""
        if (isinstance(value, ast.Attribute)
                and isinstance(value.value, ast.Name)
                and value.value.id in ("self", "cls") and ci is not None):
            return ci.methods.get(value.attr)
        if isinstance(value, ast.Name):
            local = getattr(fn, "_locals", {}).get(value.id)
            if local is not None:
                return local
        return None

    def _blocking_reason(self, func: ast.expr, call: ast.Call,
                         held: List[Tuple[str, str]]) -> Optional[str]:
        if isinstance(func, ast.Attribute):
            attr = func.attr
            recv = _unparse(func.value)
            if attr == "sleep":
                return "sleep()"
            if attr in SOCKET_BLOCKERS:
                return f"socket .{attr}()"
            if attr == "result":
                return ".result()"
            if attr == "wait":
                if any(recv == h_expr for _, h_expr in held):
                    return None  # cond.wait() while holding cond: legal
                return ".wait()"
            if attr == "join" and "thread" in recv.lower():
                return "thread .join()"
        elif isinstance(func, ast.Name) and func.id == "sleep":
            return "sleep()"
        return None

    # -- phase 3: context propagation --------------------------------------

    def assign_roots(self) -> None:
        for mod in self.modules:
            for ci in mod.classes.values():
                for name, meth in ci.methods.items():
                    if name == "__init__":
                        meth.contexts.add(INIT_CTX)
                    elif not name.startswith("_") or name.startswith("__"):
                        meth.contexts.add("caller")

    def propagate_contexts(self) -> None:
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for fn in self.all_funcs:
                for src in fn.inherit_from:
                    add = src.contexts - fn.contexts
                    if add:
                        fn.contexts |= add
                        changed = True
                # construction is single-threaded no matter who constructs:
                # calls made from __init__ propagate only the init context
                src_ctx = ({INIT_CTX} if fn.name == "__init__"
                           else fn.contexts)
                for desc, _line, _lk in fn.calls:
                    for callee in self._resolve_call(fn, desc):
                        add = src_ctx - callee.contexts
                        if add:
                            callee.contexts |= add
                            changed = True
        # anything still context-free is only reachable from outside the
        # package: treat as plain caller
        for fn in self.all_funcs:
            if not fn.contexts:
                fn.contexts.add("caller")

    def _resolve_call(self, fn: FuncNode, desc: tuple) -> List[FuncNode]:
        out: List[FuncNode] = []
        ci = fn.cls
        if desc[0] == "self" and ci is not None:
            m = ci.methods.get(desc[1])
            if m is not None:
                out.append(m)
            else:
                stored = ci.func_attrs.get(desc[1])
                if stored is not None:
                    out.append(stored)
                else:
                    for base in ci.bases:
                        for bci in self.class_registry.get(base, []):
                            bm = bci.methods.get(desc[1])
                            if bm is not None:
                                out.append(bm)
        elif desc[0] == "attr" and ci is not None:
            for cname in ci.attr_classes.get(desc[1], ()):
                for tci in self.class_registry.get(cname, []):
                    m = tci.methods.get(desc[2])
                    if m is not None:
                        out.append(m)
        elif desc[0] == "plain":
            name = desc[1]
            if name in self.class_registry:
                for tci in self.class_registry[name]:
                    init = tci.methods.get("__init__")
                    if init is not None:
                        out.append(init)
            else:
                local = getattr(fn, "_locals", {}).get(name)
                if local is not None:
                    out.append(local)
                else:
                    for cand in self.func_registry.get(name, []):
                        if cand.module == fn.module:
                            out.append(cand)
        return out

    # -- phase 4: interprocedural closures ---------------------------------

    def compute_locked_inheritance(self) -> None:
        """Repo convention: a ``*_locked`` method is only called with its
        class's lock already held. Credit its writes with the locks provably
        held at *every* observed call site (intersection), propagated through
        chains of ``*_locked`` helpers."""
        inh: Dict[int, Optional[frozenset]] = {
            id(fn): None for fn in self.all_funcs
            if fn.name.endswith("_locked")
        }
        for _ in range(10):
            changed = False
            for fn in self.all_funcs:
                base = inh.get(id(fn))
                if id(fn) in inh and base is None:
                    # a _locked helper whose own call sites have not been
                    # observed yet: crediting its outgoing calls now would
                    # poison callees with a premature empty intersection
                    # (the intersection only ever shrinks), making results
                    # depend on method definition order -- defer until a
                    # later round resolves its base
                    continue
                base_set = base if base is not None else frozenset()
                for desc, _line, lockids in fn.calls:
                    for callee in self._resolve_call(fn, desc):
                        if id(callee) not in inh:
                            continue
                        eff = frozenset(lockids) | base_set
                        cur = inh[id(callee)]
                        new = eff if cur is None else (cur & eff)
                        if new != cur:
                            inh[id(callee)] = new
                            changed = True
            if not changed:
                break
        self._inherited: Dict[int, frozenset] = {
            k: (v if v is not None else frozenset()) for k, v in inh.items()
        }

    def _effective_locks(self, fn: FuncNode, locks: frozenset) -> frozenset:
        return locks | self._inherited.get(id(fn), frozenset())

    def close_acquires_and_blocking(self) -> None:
        for fn in self.all_funcs:
            fn.trans_acquires = set(fn.acquires)
            if fn.blocking:
                fn.blocks_because = fn.blocking[0][0]
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for fn in self.all_funcs:
                for desc, _line, _lk in fn.calls:
                    for callee in self._resolve_call(fn, desc):
                        add = callee.trans_acquires - fn.trans_acquires
                        if add:
                            fn.trans_acquires |= add
                            changed = True
                        if callee.blocks_because and not fn.blocks_because:
                            fn.blocks_because = (
                                f"{callee.name}() -> {callee.blocks_because}"
                            )
                            changed = True

    # -- phase 5: rules ----------------------------------------------------

    def _module_of(self, fn: FuncNode) -> Optional[ModuleInfo]:
        if not hasattr(self, "_mod_by_path"):
            self._mod_by_path = {m.path: m for m in self.modules}
        return self._mod_by_path.get(fn.path)

    def rule_lock_order(self) -> None:
        edges: Dict[Tuple[str, str], Tuple[ModuleInfo, int]] = {}
        for fn in self.all_funcs:
            mod = self._module_of(fn)
            if mod is None:
                continue
            for h, a, line in fn.edges:
                edges.setdefault((h, a), (mod, line))
            for desc, lockids, line in fn.calls_under_lock:
                for callee in self._resolve_call(fn, desc):
                    for acq in callee.trans_acquires:
                        for h in lockids:
                            if h != acq:
                                edges.setdefault((h, acq), (mod, line))
        graph: Dict[str, Set[str]] = {}
        for (h, a) in edges:
            graph.setdefault(h, set()).add(a)
        # report one finding per edge that participates in a cycle
        for (h, a), (mod, line) in sorted(
            edges.items(), key=lambda kv: (str(kv[1][0].path), kv[1][1])
        ):
            if self._reaches(graph, a, h):
                self.report(
                    mod, line, "lock-order",
                    f"acquiring {a!r} while holding {h!r} closes a "
                    f"lock-order cycle ({a!r} can be held while taking "
                    f"{h!r} elsewhere): potential deadlock",
                )

    @staticmethod
    def _reaches(graph: Dict[str, Set[str]], src: str, dst: str) -> bool:
        seen: Set[str] = set()
        frontier = [src]
        while frontier:
            n = frontier.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            frontier.extend(graph.get(n, ()))
        return False

    def rule_unguarded_writes(self) -> None:
        fn_mod = {m.path: m for m in self.modules}
        for mod in self.modules:
            for ci in mod.classes.values():
                self._check_class_writes(fn_mod[mod.path], ci)

    def _class_funcs(self, ci: ClassInfo) -> List[FuncNode]:
        return [fn for fn in self.all_funcs if fn.cls is ci]

    def _check_class_writes(self, mod: ModuleInfo, ci: ClassInfo) -> None:
        if ci.class_guard is not None and ci.class_guard not in ci.lock_attrs:
            return  # class-wide serialization discipline, documented
        per_attr: Dict[str, List[Tuple[str, int, frozenset, FuncNode]]] = {}
        for fn in self._class_funcs(ci):
            eff_ctx = fn.contexts - {INIT_CTX} or {INIT_CTX}
            for attr, line, locks in fn.writes:
                locks = self._effective_locks(fn, locks)
                for ctx in eff_ctx:
                    per_attr.setdefault(attr, []).append((ctx, line, locks, fn))
        for attr, entries in sorted(per_attr.items()):
            if attr in ci.lock_attrs or attr in ci.attr_types_safe:
                continue
            guard = ci.guards.get(attr)
            if guard is not None and guard in ci.lock_attrs:
                want = f"{ci.name}.{guard}"
                for ctx, line, locks, fn in entries:
                    if ctx == INIT_CTX or fn.name == "__init__":
                        continue
                    if want not in locks:
                        self.report(
                            mod, line, "unguarded-write",
                            f"{ci.name}.{attr} is declared guarded-by "
                            f"{guard!r} but this write does not hold it",
                        )
                continue
            if guard is not None:
                continue  # declared serialization discipline (documented)
            contexts = {ctx for ctx, _, _, fn in entries
                        if ctx != INIT_CTX and fn.name != "__init__"}
            if len(contexts) < 2:
                continue
            common = None
            lines = []
            for ctx, line, locks, fn in entries:
                if ctx == INIT_CTX or fn.name == "__init__":
                    continue
                lines.append(line)
                common = locks if common is None else (common & locks)
            if common:
                continue  # every write holds a shared lock
            self.report(
                mod, min(lines), "unguarded-write",
                f"{ci.name}.{attr} is written from multiple execution "
                f"contexts ({', '.join(sorted(contexts))}) with no common "
                f"lock; guard it or declare '# guarded-by: <x>' at its "
                f"__init__ assignment",
            )

    def rule_blocking_under_lock(self) -> None:
        for fn in self.all_funcs:
            mod = self._module_of(fn)
            if mod is None:
                continue
            for reason, line, lockids in fn.blocking:
                if lockids:
                    self.report(
                        mod, line, "blocking-under-lock",
                        f"{reason} while holding {lockids[-1]!r}",
                    )
            for desc, lockids, line in fn.calls_under_lock:
                for callee in self._resolve_call(fn, desc):
                    if callee.blocks_because:
                        self.report(
                            mod, line, "blocking-under-lock",
                            f"call to {callee.name}() (which blocks: "
                            f"{callee.blocks_because}) while holding "
                            f"{lockids[-1]!r}",
                        )
                        break

    def rule_unbalanced_acquire(self) -> None:
        for fn in self.all_funcs:
            mod = self._module_of(fn)
            if mod is None:
                continue
            for recv, line in fn.manual_acquires:
                if recv not in fn.finally_releases:
                    self.report(
                        mod, line, "unbalanced-acquire",
                        f"manual {recv}.acquire() without a matching "
                        f".release() in a finally block; use 'with'",
                    )

    # -- phase 6: jit purity ------------------------------------------------

    def rule_jit_purity(self) -> None:
        for mod in self.modules:
            jitted = _find_jitted(mod.tree)
            for node, how in jitted:
                _JitPurityVisitor(self, mod, how).check(node)

    def run(self) -> List[Finding]:
        self.inventory()
        self.scan_bodies()
        self.assign_roots()
        self.propagate_contexts()
        self.compute_locked_inheritance()
        self.close_acquires_and_blocking()
        self.rule_lock_order()
        self.rule_unguarded_writes()
        self.rule_blocking_under_lock()
        self.rule_unbalanced_acquire()
        self.rule_jit_purity()
        self.findings.sort(key=lambda f: (str(f.path), f.line, f.rule))
        return self.findings


# --------------------------------------------------------------------------- #
# jit-purity
# --------------------------------------------------------------------------- #

def _is_jit_expr(expr: ast.expr) -> bool:
    """jax.jit / jit / functools.partial(jax.jit, ...) as a decorator."""
    name = _name_of(expr)
    if name == "jit":
        return True
    if isinstance(expr, ast.Call):
        fname = _name_of(expr.func)
        if fname == "jit":
            return True
        if fname == "partial" and expr.args:
            return _name_of(expr.args[0]) == "jit"
    return False


def _find_jitted(tree: ast.Module) -> List[Tuple[ast.AST, str]]:
    """Every function staged through jax.jit / pallas_call / shard_map."""
    out: List[Tuple[ast.AST, str]] = []
    defs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    seen: Set[int] = set()

    def mark(node: ast.AST, how: str) -> None:
        if id(node) not in seen:
            seen.add(id(node))
            out.append((node, how))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    mark(node, "jax.jit")
        if isinstance(node, ast.Call):
            fname = _name_of(node.func)
            if fname == "jit" and node.args:
                target = node.args[0]
                if isinstance(target, ast.Name):
                    for d in defs.get(target.id, []):
                        mark(d, "jax.jit")
            elif fname in ("pallas_call", "shard_map") and node.args:
                target = node.args[0]
                if isinstance(target, ast.Name):
                    for d in defs.get(target.id, []):
                        mark(d, fname)
    return out


class _JitPurityVisitor(ast.NodeVisitor):
    WALL_CLOCK = {"time", "monotonic", "perf_counter", "time_ns",
                  "monotonic_ns", "perf_counter_ns", "now"}
    HOST_SYNC = {"item", "asarray", "array", "frombuffer", "device_get",
                 "block_until_ready", "tolist"}

    def __init__(self, analyzer: Analyzer, mod: ModuleInfo, how: str) -> None:
        self.analyzer = analyzer
        self.mod = mod
        self.how = how

    def check(self, node: ast.AST) -> None:
        self.fname = getattr(node, "name", "<fn>")
        for stmt in node.body:
            self.visit(stmt)

    def _flag(self, node: ast.AST, what: str) -> None:
        self.analyzer.report(
            self.mod, node.lineno, "jit-purity",
            f"{what} inside {self.how}-staged {self.fname}(): traced once, "
            f"never replayed -- breaks replay determinism",
        )

    def visit_Global(self, node: ast.Global) -> None:
        self._flag(node, "global statement")

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Name) and func.id == "print":
            self._flag(node, "print()")
        if isinstance(func, ast.Attribute):
            base = func.value
            base_name = _name_of(base) if isinstance(
                base, (ast.Name, ast.Attribute)) else None
            if func.attr in self.WALL_CLOCK and base_name in (
                    "time", "datetime"):
                self._flag(node, f"wall-clock read {base_name}.{func.attr}()")
            if isinstance(base, ast.Name) and base.id == "random":
                self._flag(node, f"host RNG random.{func.attr}()")
            if (isinstance(base, ast.Attribute) and base.attr == "random"
                    and isinstance(base.value, ast.Name)
                    and base.value.id in ("np", "numpy", "onp")):
                self._flag(node, f"host RNG np.random.{func.attr}()")
            if func.attr in ("asarray", "array", "frombuffer") and isinstance(
                    base, ast.Name) and base.id in ("np", "numpy", "onp"):
                self._flag(node, f"host sync {base.id}.{func.attr}()")
            if func.attr in ("item", "block_until_ready", "tolist"):
                self._flag(node, f"host sync .{func.attr}()")
            if func.attr == "device_get":
                self._flag(node, "host sync jax.device_get()")
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Attribute):
                self._flag(node, f"attribute mutation {_unparse(t)} = ...")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.target, ast.Attribute):
            self._flag(node, f"attribute mutation {_unparse(node.target)}")
        self.generic_visit(node)


# --------------------------------------------------------------------------- #
# entry points
# --------------------------------------------------------------------------- #

def run(paths: Optional[List[str]] = None) -> List[Finding]:
    files = iter_py_files([Path(p) for p in (paths or DEFAULT_PATHS)])
    return Analyzer(files).run()


def main(argv: List[str]) -> int:
    findings = run(argv or None)
    for finding in findings:
        print(finding)
    print(f"concur: {'OK' if not findings else f'{len(findings)} findings'}")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
