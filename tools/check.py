"""Static-analysis tier runnable in this image (stdlib-only).

The reference treats static checking as part of its correctness story
(error-prone with -Werror, findbugs, checkstyle -- pom.xml:40-76,
build-common/). This repo's equivalents:

- [tool.ruff] / [tool.mypy] in pyproject.toml for environments that have
  the tools;
- this checker, which needs nothing beyond the stdlib, for `make check`
  anywhere: byte-compiles every file and enforces a focused, high-signal
  AST rule set (unused imports, mutable default arguments, bare excepts,
  `== None` comparisons, always-true tuple asserts, duplicate dict keys,
  debugger/print leftovers in library code).

Concurrency hygiene rules that belong with general code health live here too
(thread-daemon, callback-under-lock); the deep concurrency analysis (lock
graphs, write contexts, jit purity) is tools/concur.py, and the device-plane
performance analysis (recompile hazards, host syncs, dtype discipline,
donation hygiene) is tools/devlint.py. `--all` runs all three with one
merged exit code. Rule names and one-line rationales: RULE_DOCS below
(printed by `--rules`), with the full convention write-ups in
ARCHITECTURE.md "Concurrency discipline & static analysis" and
"Device-plane performance discipline".

Suppress a single line with `# noqa` or `# noqa: RULE` (rule names are
case-insensitive; shared with tools/concur.py via tools/lintlib.py).

Usage: python tools/check.py [--all|--rules] [paths...]
       (default paths: the repo's source roots)
"""

from __future__ import annotations

import ast
import importlib.util
import json
import sys
from pathlib import Path

if __package__ in (None, ""):
    sys.path.insert(0, str(Path(__file__).resolve().parent))
    from lintlib import Finding, iter_py_files, noqa_lines, suppressed
else:  # pragma: no cover - imported as a package module
    from .lintlib import Finding, iter_py_files, noqa_lines, suppressed

REPO = Path(__file__).resolve().parent.parent
DEFAULT_PATHS = ["rapid_tpu", "tests", "examples", "experiments", "tools",
                 "bench.py", "scenarios.py", "__graft_entry__.py"]

# one-line rationale per rule, both analyzers (`--rules` prints this)
RULE_DOCS = {
    # tools/check.py -- code health
    "syntax": "file must byte-compile; everything else assumes it does",
    "unused-import": "dead imports hide real dependencies and slow startup",
    "mutable-default": "def f(x=[]) shares one list across all calls",
    "bare-except": "except: swallows KeyboardInterrupt/SystemExit too",
    "none-compare": "== None matches __eq__ overrides; use 'is None'",
    "assert-tuple": "assert (x, msg) is always true -- a silent no-op test",
    "dup-dict-key": "duplicate literal keys: the first value silently loses",
    "print-in-lib": "library code must log or record, not print",
    "debugger": "breakpoint()/pdb left in committed code",
    "unknown-metric": "metric names outside the catalog fork the series",
    "unknown-span": "span/event names outside the catalog fork the trace",
    "wire-tag": "wire tags must stay unique and append-only across versions",
    "fault-catalog": "fault rules must declare a compiled/absorbed story",
    "plan-corpus": "pinned nemesis plans must stay loadable: known rule "
                   "types, sane windows/probabilities, a known harness",
    "gen-reach": "every fault Rule subclass must be reachable by the search "
                 "generator (GEN_RULES), or new faults stay untested",
    "settings-catalog": "every cataloged settings knob must be in "
                        "SETTINGS_CATALOG with bounds its default "
                        "satisfies, or operators tune blind",
    "metric-emission": "every METRIC_CATALOG name needs an emitting call "
                       "site and every emission a catalog entry, or the "
                       "catalog and the dashboards drift apart",
    "event-emission": "every EVENT_CATALOG kind needs an emitting call site "
                      "and every journal/instant emission a catalog entry, "
                      "or post-mortems grep for events that never happen",
    "signature-catalog": "every anomaly signature needs a detector that "
                         "emits it and every detector finding a catalog "
                         "row, or forensic reports cite undocumented "
                         "signatures",
    "slo-catalog": "every declared SLO must name a cataloged SLI and a "
                   "valid window pair with sane thresholds, or the burn "
                   "alerts evaluate garbage",
    # tools/check.py -- concurrency hygiene
    "thread-daemon": "a non-daemon thread outlives shutdown and hangs exit; "
                     "mark daemon=True or provably join it",
    "messaging-thread": "rapid_tpu/messaging/ runs on the reactor event "
                        "loop; new Thread constructions there (outside "
                        "reactor.py) re-grow the thread-per-message design",
    "callback-under-lock": "user callbacks invoked under a lock can re-enter "
                           "and deadlock; call them after release",
    # tools/concur.py -- concurrency correctness
    "lock-order": "a cycle in the held->acquired lock graph is a potential "
                  "deadlock; keep the hierarchy acyclic",
    "unguarded-write": "an attribute written from >=2 execution contexts "
                       "with no common lock is a data race",
    "blocking-under-lock": "blocking (socket/sleep/result/wait/join) while "
                           "holding a lock stalls every other acquirer",
    "unbalanced-acquire": "manual acquire() without release() in a finally "
                          "leaks the lock on any exception; use 'with'",
    "jit-purity": "side effects in jit/pallas/shard_map functions run once "
                  "at trace time, then never again -- silent wrong results",
    # tools/devlint.py -- device-plane performance
    "recompile-hazard": "per-call-varying statics, raw jax.jit off the "
                        "make_jit seam, or per-call jit creation recompile "
                        "in steady state",
    "host-sync": "int()/np.asarray/.item()/device_get on device state is a "
                 "blocking round trip; route through jitwatch.fetch/drain",
    "dtype-discipline": "dtype-less jnp constructions and silent widening "
                        "of narrow state fields split the compile cache",
    "donation-hygiene": "carried state through a jit without donate_argnums "
                        "doubles peak memory every dispatch",
}

# modules where `print` is the intended UI (CLIs, benchmarks, experiments)
PRINT_OK_ROOTS = ("examples", "experiments", "tools", "tests")
PRINT_OK_FILES = {"bench.py", "scenarios.py", "__graft_entry__.py"}


def _load_catalogs() -> "tuple[frozenset, tuple, frozenset, frozenset]":
    """METRIC_CATALOG / METRIC_PREFIXES / SPAN_CATALOG / EVENT_CATALOG from
    rapid_tpu/observability.py, loaded as a standalone module
    (observability.py is stdlib-only at module level; importing the
    rapid_tpu package here would pull in jax)."""
    spec = importlib.util.spec_from_file_location(
        "_rapid_observability", REPO / "rapid_tpu" / "observability.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod  # dataclass processing resolves __module__
    spec.loader.exec_module(mod)  # type: ignore[union-attr]
    return (mod.METRIC_CATALOG, mod.METRIC_PREFIXES, mod.SPAN_CATALOG,
            mod.EVENT_CATALOG)


METRIC_CATALOG, METRIC_PREFIXES, SPAN_CATALOG, EVENT_CATALOG = _load_catalogs()

# tracer/journal call sites whose literal first argument must come from the
# matching catalog: .span/.begin/.remote_span mint spans (SPAN_CATALOG),
# .event mints instants and .record journals flight-recorder entries
# (EVENT_CATALOG). A typo'd name would silently fork a trace/journal series
# exactly like a typo'd metric name.
SPAN_METHODS = ("span", "begin", "remote_span")
EVENT_METHODS = ("event", "record")


class Checker(ast.NodeVisitor):
    def __init__(self, path: Path, source: str, tree: ast.Module) -> None:
        self.path = path
        self.tree = tree
        self.findings: list[Finding] = []
        self._noqa = noqa_lines(source)
        rel = path.relative_to(REPO)
        self.print_ok = (
            rel.parts[0] in PRINT_OK_ROOTS or rel.name in PRINT_OK_FILES
        )
        # the metric-name lint applies to library code only: test fixtures
        # mint throwaway names, and observability.py defines the catalog
        self.metric_names_checked = (
            rel.parts[0] == "rapid_tpu" and rel.name != "observability.py"
        )

    def report(self, node: ast.AST, rule: str, msg: str) -> None:
        line = getattr(node, "lineno", 0)
        if suppressed(self._noqa, line, rule):
            return
        self.findings.append(Finding(self.path, line, rule, msg))

    # -- unused imports ----------------------------------------------------

    def check_unused_imports(self) -> None:
        imported: dict[str, ast.AST] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    imported[name] = node
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    imported[alias.asname or alias.name] = node

        used: set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                base = node
                while isinstance(base, ast.Attribute):
                    base = base.value
                if isinstance(base, ast.Name):
                    used.add(base.id)
        # names re-exported via __all__ count as used
        for node in self.tree.body:
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.List, ast.Tuple))
            ):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        used.add(elt.value)
        # string annotations (from __future__ import annotations) reference
        # names the walker cannot see; treat annotation strings as usage
        for node in ast.walk(self.tree):
            ann = getattr(node, "annotation", None)
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                used.update(
                    part for part in ann.value.replace("[", " ")
                    .replace("]", " ").replace(",", " ").replace(".", " ").split()
                )
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ret = node.returns
                if isinstance(ret, ast.Constant) and isinstance(ret.value, str):
                    used.update(
                        part for part in ret.value.replace("[", " ")
                        .replace("]", " ").replace(",", " ").replace(".", " ").split()
                    )
        for name, node in imported.items():
            if name not in used:
                self.report(node, "unused-import", f"'{name}' imported but unused")

    # -- node rules --------------------------------------------------------

    def visit_FunctionDef(self, node) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _check_defaults(self, node) -> None:
        for default in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.report(
                    default, "mutable-default",
                    f"mutable default argument in {node.name}()",
                )

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self.report(node, "bare-except", "bare 'except:' hides SystemExit")
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        for op, comparator in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)) and (
                isinstance(comparator, ast.Constant) and comparator.value is None
            ):
                self.report(node, "none-compare", "use 'is None' / 'is not None'")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        if isinstance(node.test, ast.Tuple) and node.test.elts:
            self.report(node, "assert-tuple", "assert on tuple is always true")
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict) -> None:
        seen: set = set()
        for key in node.keys:
            if isinstance(key, ast.Constant):
                try:
                    if key.value in seen:
                        self.report(key, "dup-dict-key",
                                    f"duplicate dict key {key.value!r}")
                    seen.add(key.value)
                except TypeError:
                    pass
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Name) and func.id == "print" and not self.print_ok:
            self.report(node, "print-in-lib",
                        "print() in library code; use logging")
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "set_trace"
        ):
            self.report(node, "debugger", "debugger breakpoint left in code")
        if (
            self.metric_names_checked
            and isinstance(func, ast.Attribute)
            and func.attr in ("incr", "observe", "set_gauge")
            and node.args
        ):
            self._check_metric_name(node, node.args[0])
        if (
            self.metric_names_checked
            and isinstance(func, ast.Attribute)
            and func.attr in SPAN_METHODS + EVENT_METHODS
            and node.args
        ):
            self._check_span_name(node, func.attr, node.args[0])
        self.generic_visit(node)

    def _check_span_name(self, call: ast.Call, method: str,
                         arg: ast.expr) -> None:
        """Literal span names must be in SPAN_CATALOG, literal event/journal
        kinds in EVENT_CATALOG. Dynamic names are skipped, same policy as the
        metric lint."""
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            return
        catalog, label = (
            (SPAN_CATALOG, "SPAN_CATALOG")
            if method in SPAN_METHODS
            else (EVENT_CATALOG, "EVENT_CATALOG")
        )
        if arg.value not in catalog:
            self.report(
                call, "unknown-span",
                f"{method}() name {arg.value!r} not in "
                f"observability.{label}",
            )

    def _check_metric_name(self, call: ast.Call, arg: ast.expr) -> None:
        """Every .incr()/.observe()/.set_gauge() call site in library code
        must use a name from observability.METRIC_CATALOG (or a METRIC_PREFIXES
        dynamic family, e.g. f"messages.{...}"). Dynamic names built from
        variables are skipped -- the lint targets the literal call sites
        where a typo would silently fork a metric series."""
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
            if name not in METRIC_CATALOG and not name.startswith(METRIC_PREFIXES):
                self.report(
                    call, "unknown-metric",
                    f"metric name {name!r} not in observability.METRIC_CATALOG",
                )
        elif isinstance(arg, ast.JoinedStr):
            head = arg.values[0] if arg.values else None
            if not (
                isinstance(head, ast.Constant)
                and isinstance(head.value, str)
                and head.value.startswith(METRIC_PREFIXES)
            ):
                self.report(
                    call, "unknown-metric",
                    "f-string metric name must start with a METRIC_PREFIXES "
                    f"prefix ({', '.join(METRIC_PREFIXES)})",
                )


def _module_literals(path: Path, wanted: set) -> dict:
    """Top-level ``NAME = <literal>`` assignments (plain or annotated) from a
    file, without importing it: {name: (value, lineno)}."""
    tree = ast.parse(path.read_text(), filename=str(path))
    out: dict = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
            node.targets[0], ast.Name
        ):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            target, value = node.target.id, node.value
        else:
            continue
        if target in wanted and value is not None:
            try:
                out[target] = (ast.literal_eval(value), node.lineno)
            except ValueError:
                pass
    return out


def check_wire_tags() -> list[Finding]:
    """Wire-numbering lint over the messaging schema tables.

    The msgpack codec's tags are _TYPES list indices and the gRPC envelope's
    oneof numbers are hand-maintained literals; a duplicate or colliding
    number would decode one message type as another with no error at the
    call site. Asserts: codec._TYPES entries are unique; every wire_schema
    message uses each field number and name once; each oneof's numbers are
    unique AND contiguous from 1 (so a new message -- e.g. the handoff
    messages after ClusterStatus -- must take the next number, never a gap
    or a reuse), EXCEPT that the request oneof may skip
    the reserved envelope-rider numbers (TRACE_CTX_FIELD_NUMBER,
    HLC_FIELD_NUMBER), which ride outside the oneof on the same envelope
    and whose numbers are therefore reserved; no oneof number collides
    with one of them outright. Msgpack-side: no dataclass field of any
    codec-carried message may start with ``__`` -- decode strips every
    ``__``-prefixed top-level key as an envelope extension, so such a
    field would silently vanish on the wire."""
    findings: list[Finding] = []
    msg_dir = REPO / "rapid_tpu" / "messaging"
    codec_path = msg_dir / "codec.py"
    schema_path = msg_dir / "wire_schema.py"
    types_path = REPO / "rapid_tpu" / "types.py"

    tree = ast.parse(codec_path.read_text(), filename=str(codec_path))
    codec_type_names: set = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if (
            any(
                isinstance(t, ast.Name) and t.id == "_TYPES"
                for t in targets
            )
            and isinstance(node.value, (ast.List, ast.Tuple))
        ):
            seen: dict = {}
            for i, elt in enumerate(node.value.elts):
                name = (
                    elt.attr if isinstance(elt, ast.Attribute)
                    else getattr(elt, "id", None)
                )
                if name is None:
                    continue
                if name in seen:
                    findings.append(Finding(
                        codec_path, elt.lineno, "wire-tags",
                        f"codec._TYPES lists {name!r} at tags {seen[name]} "
                        f"and {i}; duplicates make encoding ambiguous",
                    ))
                seen[name] = i
            codec_type_names = set(seen)
            break
    else:
        findings.append(Finding(
            codec_path, 0, "wire-tags", "codec._TYPES not found"
        ))

    # msgpack reserved-key collision: the codec encodes each message as a
    # dict keyed by dataclass field names and decode() strips every
    # "__"-prefixed top-level key (envelope extensions like "__tc"), so a
    # codec-carried dataclass field named "__anything" would be silently
    # dropped by every decoder
    types_tree = ast.parse(types_path.read_text(), filename=str(types_path))
    for node in types_tree.body:
        if not (isinstance(node, ast.ClassDef)
                and node.name in codec_type_names):
            continue
        for stmt in node.body:
            if (
                isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
                and stmt.target.id.startswith("__")
            ):
                findings.append(Finding(
                    types_path, stmt.lineno, "wire-tags",
                    f"{node.name}.{stmt.target.id} collides with the "
                    "codec's reserved '__' envelope-key namespace: decoders "
                    "strip it, so the field never survives the wire",
                ))

    wanted = {"_MESSAGES", "_REQUEST_ONEOF", "_RESPONSE_ONEOF",
              "TRACE_CTX_FIELD_NUMBER", "HLC_FIELD_NUMBER"}
    lits = _module_literals(schema_path, wanted)
    for name in sorted(wanted - lits.keys()):
        findings.append(Finding(
            schema_path, 0, "wire-tags",
            f"wire_schema.{name} not found or not a pure literal",
        ))

    messages = lits.get("_MESSAGES", ({}, 0))[0]
    if messages:
        line = lits["_MESSAGES"][1]
        for msg_name, fields in messages.items():
            numbers = [number for _, _, number, _ in fields]
            names = [field_name for field_name, _, _, _ in fields]
            for number in sorted({n for n in numbers if numbers.count(n) > 1}):
                findings.append(Finding(
                    schema_path, line, "wire-tags",
                    f"{msg_name} uses field number {number} more than once",
                ))
            for field_name in sorted({n for n in names if names.count(n) > 1}):
                findings.append(Finding(
                    schema_path, line, "wire-tags",
                    f"{msg_name} declares field {field_name!r} more than once",
                ))
            for number in numbers:
                if number < 1:
                    findings.append(Finding(
                        schema_path, line, "wire-tags",
                        f"{msg_name} uses invalid field number {number}",
                    ))

    # numbers reserved for the envelope riders (traceCtx, hlc): they sit on
    # RapidRequest outside the oneof, so the oneof must skip them, never
    # reuse them. Each new rider appends its NAME here and its number at
    # the top of the envelope's free space, exactly like a proto
    # `reserved` declaration.
    reserved = {
        name: lits[name][0]
        for name in ("TRACE_CTX_FIELD_NUMBER", "HLC_FIELD_NUMBER")
        if name in lits
    }
    reserved_numbers = set(reserved.values())
    for oneof_name in ("_REQUEST_ONEOF", "_RESPONSE_ONEOF"):
        if oneof_name not in lits:
            continue
        entries, line = lits[oneof_name]
        numbers = [number for _, _, number in entries]
        if len(set(numbers)) != len(numbers):
            findings.append(Finding(
                schema_path, line, "wire-tags",
                f"{oneof_name} reuses a field number: {sorted(numbers)}",
            ))
        # contiguity from 1, with one documented exception: the request
        # oneof skips every reserved envelope-rider number (they live
        # outside the oneof on the same envelope, so reserved, not free)
        expected: list = []
        candidate = 1
        while len(expected) < len(numbers):
            if not (
                oneof_name == "_REQUEST_ONEOF"
                and candidate in reserved_numbers
            ):
                expected.append(candidate)
            candidate += 1
        if sorted(numbers) != expected:
            findings.append(Finding(
                schema_path, line, "wire-tags",
                f"{oneof_name} numbers {sorted(numbers)} are not contiguous "
                "from 1 (modulo the reserved envelope-rider numbers "
                f"{sorted(reserved_numbers)}); new messages must take the "
                "next free number",
            ))
        for rider, number in sorted(reserved.items()):
            if number in numbers:
                findings.append(Finding(
                    schema_path, line, "wire-tags",
                    f"{oneof_name} number {number} collides with "
                    f"{rider} (rides outside the oneof)",
                ))
    if len(reserved_numbers) != len(reserved):
        findings.append(Finding(
            schema_path, 0, "wire-tags",
            "two envelope riders share one reserved field number: "
            f"{sorted(reserved.items())}",
        ))
        if messages:
            for _, type_name, _ in entries:
                if type_name not in messages:
                    findings.append(Finding(
                        schema_path, line, "wire-tags",
                        f"{oneof_name} references unknown message "
                        f"{type_name!r}",
                    ))
    return findings


def check_fault_rules() -> list[Finding]:
    """Fault-rule catalog lint over rapid_tpu/faults.py.

    Every Rule subclass must have a device-plane story: an entry in
    RULE_CATALOG saying whether _device_rules compiles it onto the fault
    arrays ("compiled") or the round model absorbs it ("absorbed"). A rule
    class added without a catalog entry would silently skip the device
    plane's three-way parity contract; a stale entry would document a rule
    that no longer exists. (The companion constraint -- every fd.* /
    nemesis_* metric the fault plane emits is in METRIC_CATALOG -- is
    enforced by the unknown-metric rule on the same files.)"""
    findings: list[Finding] = []
    path = REPO / "rapid_tpu" / "faults.py"
    rule_classes = _rule_subclasses(path)

    lits = _module_literals(path, {"RULE_CATALOG"})
    if "RULE_CATALOG" not in lits:
        findings.append(Finding(
            path, 0, "fault-catalog",
            "RULE_CATALOG not found or not a pure literal",
        ))
        return findings
    catalog, line = lits["RULE_CATALOG"]

    for name, lineno in sorted(rule_classes.items()):
        if name not in catalog:
            findings.append(Finding(
                path, lineno, "fault-catalog",
                f"Rule subclass {name!r} missing from RULE_CATALOG: does "
                "_device_rules compile or absorb it?",
            ))
    for name, story in catalog.items():
        if name not in rule_classes:
            findings.append(Finding(
                path, line, "fault-catalog",
                f"RULE_CATALOG lists {name!r} but no such Rule subclass "
                "exists",
            ))
        if story not in ("compiled", "absorbed"):
            findings.append(Finding(
                path, line, "fault-catalog",
                f"RULE_CATALOG[{name!r}] must be 'compiled' or 'absorbed', "
                f"got {story!r}",
            ))
    return findings


def _rule_subclasses(path: Path) -> "dict[str, int]":
    """Transitive Rule subclasses defined in a faults module, by AST walk
    (no import): {class name: lineno}."""
    tree = ast.parse(path.read_text(), filename=str(path))
    rule_classes: dict[str, int] = {}
    known = {"Rule"}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        bases = {b.id for b in node.bases if isinstance(b, ast.Name)}
        if bases & known:
            known.add(node.name)
            rule_classes[node.name] = node.lineno
    return rule_classes


def check_generator_reach() -> list[Finding]:
    """Generator-reachability lint (the GEN_RULES sync discipline).

    The nemesis search can only find bugs in faults it can emit:
    rapid_tpu/search/generator.py keeps GEN_RULES, the literal tuple of
    Rule subclasses its sampler draws from, and this lint pins it against
    the Rule subclasses actually defined in rapid_tpu/faults.py -- the
    same two-sided freshness contract RULE_CATALOG has. A new fault rule
    that never enters GEN_RULES would silently stay outside every hunt;
    a GEN_RULES entry with no backing class would crash the sampler."""
    findings: list[Finding] = []
    gen_path = REPO / "rapid_tpu" / "search" / "generator.py"
    rule_classes = _rule_subclasses(REPO / "rapid_tpu" / "faults.py")

    lits = _module_literals(gen_path, {"GEN_RULES"})
    if "GEN_RULES" not in lits:
        findings.append(Finding(
            gen_path, 0, "gen-reach",
            "GEN_RULES not found or not a pure literal",
        ))
        return findings
    gen_rules, line = lits["GEN_RULES"]

    for name in sorted(set(rule_classes) - set(gen_rules)):
        findings.append(Finding(
            gen_path, line, "gen-reach",
            f"Rule subclass {name!r} missing from GEN_RULES: the nemesis "
            "search can never emit it, so it ships untested",
        ))
    for name in sorted(set(gen_rules) - set(rule_classes)):
        findings.append(Finding(
            gen_path, line, "gen-reach",
            f"GEN_RULES lists {name!r} but no such Rule subclass exists "
            "in rapid_tpu/faults.py",
        ))
    return findings


# SETTINGS_CATALOG namespaces -> the frozen dataclass each one documents.
# A new cataloged settings group registers here; a key outside every
# registered namespace is a finding (the group ships without a dataclass).
SETTINGS_GROUPS = {
    "adaptive_fd": "AdaptiveFdSettings",
    "profiling": "ProfilingSettings",
    "durability": "DurabilitySettings",
    "slo": "SLOSettings",
    "forensics": "ForensicsSettings",
    "hierarchy": "HierarchySettings",
}


def check_settings_catalog() -> list[Finding]:
    """Settings-catalog lint (the knob discipline).

    rapid_tpu/settings.py keeps SETTINGS_CATALOG, the pure-literal table of
    every ``<group>.<knob>`` with its bounds and one-line doc -- the table
    __post_init__ validates against and statusz/docs cite. Two-sided
    freshness, same contract as RULE_CATALOG/GEN_RULES: every field of each
    SETTINGS_GROUPS dataclass must have a catalog entry whose bounds are
    sane (min <= max) and admit the field's default; every catalog key must
    name a real field of its group's dataclass. All by AST walk --
    importing settings would pull in the package."""
    findings: list[Finding] = []
    path = REPO / "rapid_tpu" / "settings.py"

    lits = _module_literals(path, {"SETTINGS_CATALOG"})
    if "SETTINGS_CATALOG" not in lits:
        findings.append(Finding(
            path, 0, "settings-catalog",
            "SETTINGS_CATALOG not found or not a pure literal",
        ))
        return findings
    catalog, cat_line = lits["SETTINGS_CATALOG"]

    # each group dataclass's fields with literal defaults, by AST
    by_class: dict = {cls: {} for cls in SETTINGS_GROUPS.values()}
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in tree.body:
        if not isinstance(node, ast.ClassDef) or node.name not in by_class:
            continue
        for stmt in node.body:
            if (
                isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
                and stmt.value is not None
            ):
                try:
                    by_class[node.name][stmt.target.id] = (
                        ast.literal_eval(stmt.value), stmt.lineno
                    )
                except ValueError:
                    pass

    for group, cls in sorted(SETTINGS_GROUPS.items()):
        fields = by_class[cls]
        if not fields:
            findings.append(Finding(
                path, 0, "settings-catalog",
                f"{cls} not found or has no literal-defaulted fields",
            ))
            continue
        for name, (default, lineno) in sorted(fields.items()):
            key = f"{group}.{name}"
            entry = catalog.get(key)
            if entry is None:
                findings.append(Finding(
                    path, lineno, "settings-catalog",
                    f"{cls}.{name} missing from SETTINGS_CATALOG: "
                    "the knob ships without bounds or doc",
                ))
                continue
            if not ({"min", "max", "doc"} <= set(entry)):
                findings.append(Finding(
                    path, cat_line, "settings-catalog",
                    f"SETTINGS_CATALOG[{key!r}] must carry min/max/doc",
                ))
                continue
            lo, hi = entry["min"], entry["max"]
            if lo > hi:
                findings.append(Finding(
                    path, cat_line, "settings-catalog",
                    f"SETTINGS_CATALOG[{key!r}] bounds inverted: {lo} > {hi}",
                ))
            default_n = float(default) if isinstance(default, bool) else default
            if not (lo <= default_n <= hi):
                findings.append(Finding(
                    path, lineno, "settings-catalog",
                    f"{cls}.{name} default {default!r} outside "
                    f"its own catalog bounds [{lo}, {hi}]",
                ))
    for key in sorted(catalog):
        group = key.split(".", 1)[0]
        cls = SETTINGS_GROUPS.get(group)
        if cls is None:
            findings.append(Finding(
                path, cat_line, "settings-catalog",
                f"SETTINGS_CATALOG key {key!r} outside the namespaces this "
                f"catalog covers ({', '.join(sorted(SETTINGS_GROUPS))})",
            ))
            continue
        if key.split(".", 1)[1] not in by_class[cls]:
            findings.append(Finding(
                path, cat_line, "settings-catalog",
                f"SETTINGS_CATALOG lists {key!r} but {cls} "
                "has no such field",
            ))
    return findings


def check_metric_emission() -> list[Finding]:
    """Catalog-emission lint (the two-sided metric-name discipline).

    The per-file ``unknown-metric`` rule covers one direction at each call
    site: a literal emission must use a cataloged name. This check closes
    the loop repo-wide, the same shape as the settings-catalog lint: every
    METRIC_CATALOG name must have at least one emitting call site
    (.incr/.observe/.set_gauge) somewhere in rapid_tpu/ -- a cataloged name
    nobody emits is a stale doc operators will grep dashboards for in vain
    -- and every literal emission must be cataloged or belong to a
    METRIC_PREFIXES dynamic family. Unlike the per-file rule this scan
    includes observability.py itself (StableViewTimer and MetricsHistory
    emit there) and scenarios.py (the nemesis harness emits its
    zone-detection histogram from outside the package)."""
    findings: list[Finding] = []
    obs_path = REPO / "rapid_tpu" / "observability.py"
    emitted: dict = {}  # name -> (path, lineno) of first literal emission
    fstring_heads: list = []  # literal heads of f-string emissions

    for path in iter_py_files([REPO / "rapid_tpu", REPO / "scenarios.py"]):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError:
            continue  # the syntax rule already owns this finding
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("incr", "observe", "set_gauge")
                and node.args
            ):
                continue
            # a conditional pick between literals counts for each branch
            # (faults.py: "nemesis_reordered" if ... else "nemesis_delayed")
            args = [node.args[0]]
            if isinstance(node.args[0], ast.IfExp):
                args = [node.args[0].body, node.args[0].orelse]
            for arg in args:
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    emitted.setdefault(arg.value, (path, node.lineno))
                elif isinstance(arg, ast.JoinedStr) and arg.values and isinstance(
                    arg.values[0], ast.Constant
                ):
                    fstring_heads.append(str(arg.values[0].value))

    for name in sorted(METRIC_CATALOG):
        if name in emitted:
            continue
        if any(name.startswith(head) for head in fstring_heads):
            continue  # covered by a dynamic family emission
        findings.append(Finding(
            obs_path, 0, "metric-emission",
            f"METRIC_CATALOG lists {name!r} but no call site in rapid_tpu/ "
            "emits it",
        ))
    for name, (path, lineno) in sorted(emitted.items()):
        if name not in METRIC_CATALOG and not name.startswith(METRIC_PREFIXES):
            findings.append(Finding(
                path, lineno, "metric-emission",
                f"emitted metric {name!r} is not in "
                "observability.METRIC_CATALOG",
            ))
    return findings


def check_event_emission() -> list[Finding]:
    """Catalog-emission lint for journal/instant events (the two-sided
    EVENT_CATALOG discipline, mirror of check_metric_emission).

    The per-file ``unknown-span`` rule covers one direction at each call
    site: a literal .event()/.record() kind must be cataloged. This check
    closes the loop repo-wide: every EVENT_CATALOG kind must have at least
    one emitting call site somewhere in rapid_tpu/ or scenarios.py -- a
    cataloged kind nobody records is a stale doc a post-mortem will grep
    bundles for in vain -- and every literal emission must be cataloged.
    Conditional picks between literals (slo/burn.py's
    ``"slo_alert_fired" if kind == "fired" else "slo_alert_cleared"``)
    count for each branch, same as the metric scan."""
    findings: list[Finding] = []
    obs_path = REPO / "rapid_tpu" / "observability.py"
    emitted: dict = {}  # kind -> (path, lineno) of first literal emission

    for path in iter_py_files([REPO / "rapid_tpu", REPO / "scenarios.py"]):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError:
            continue  # the syntax rule already owns this finding
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in EVENT_METHODS
                and node.args
            ):
                continue
            args = [node.args[0]]
            if isinstance(node.args[0], ast.IfExp):
                args = [node.args[0].body, node.args[0].orelse]
            for arg in args:
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    emitted.setdefault(arg.value, (path, node.lineno))

    for kind in sorted(EVENT_CATALOG):
        if kind not in emitted:
            findings.append(Finding(
                obs_path, 0, "event-emission",
                f"EVENT_CATALOG lists {kind!r} but no .event()/.record() "
                "call site in rapid_tpu/ emits it",
            ))
    for kind, (path, lineno) in sorted(emitted.items()):
        if kind not in EVENT_CATALOG:
            findings.append(Finding(
                path, lineno, "event-emission",
                f"recorded event kind {kind!r} is not in "
                "observability.EVENT_CATALOG",
            ))
    return findings


def check_signature_catalog() -> list[Finding]:
    """Anomaly-signature catalog lint over rapid_tpu/forensics/timeline.py.

    SIGNATURE_CATALOG is the closed set of names forensic findings may
    carry (tools/forensics.py exits 3 on any of them, operators route
    pages by them). Two-sided freshness, same contract as RULE_CATALOG:
    every catalog row needs a detector that emits it (a ``_finding(...)``
    call with that literal name), every emitted name a catalog row with a
    non-empty doc -- else reports cite signatures nobody documented, or
    the catalog documents detectors that no longer exist."""
    findings: list[Finding] = []
    path = REPO / "rapid_tpu" / "forensics" / "timeline.py"

    lits = _module_literals(path, {"SIGNATURE_CATALOG"})
    if "SIGNATURE_CATALOG" not in lits:
        findings.append(Finding(
            path, 0, "signature-catalog",
            "SIGNATURE_CATALOG not found or not a pure literal",
        ))
        return findings
    catalog, cat_line = lits["SIGNATURE_CATALOG"]

    emitted: dict = {}  # signature -> lineno of first _finding() literal
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "_finding"
            and node.args
        ):
            continue
        args = [node.args[0]]
        if isinstance(node.args[0], ast.IfExp):
            args = [node.args[0].body, node.args[0].orelse]
        for arg in args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                emitted.setdefault(arg.value, node.lineno)

    for name, spec in sorted(catalog.items()):
        if not (isinstance(spec, dict) and str(spec.get("doc", "")).strip()):
            findings.append(Finding(
                path, cat_line, "signature-catalog",
                f"SIGNATURE_CATALOG[{name!r}] must carry a non-empty doc",
            ))
        if name not in emitted:
            findings.append(Finding(
                path, cat_line, "signature-catalog",
                f"SIGNATURE_CATALOG lists {name!r} but no detector emits "
                "it (_finding call with that literal name)",
            ))
    for name, lineno in sorted(emitted.items()):
        if name not in catalog:
            findings.append(Finding(
                path, lineno, "signature-catalog",
                f"detector emits signature {name!r} missing from "
                "SIGNATURE_CATALOG",
            ))
    return findings


def check_slo_catalog() -> list[Finding]:
    """SLO-target catalog lint over rapid_tpu/slo/burn.py.

    SLI_CATALOG / BURN_WINDOWS / SLO_CATALOG are pure module literals so
    this check reads them by AST, never importing the package. Every
    declared SLO must name a cataloged SLI, carry an objective strictly
    inside (0, 1) (an objective of 1.0 leaves zero error budget and the
    burn-rate division blows up), and reference only declared window
    pairs; every window pair must have 0 < short_s < long_s and a positive
    burn threshold; every fast-availability SLO must declare a positive
    latency_threshold_ms (the predicate is meaningless without one)."""
    findings: list[Finding] = []
    path = REPO / "rapid_tpu" / "slo" / "burn.py"
    wanted = {"SLI_CATALOG", "BURN_WINDOWS", "SLO_CATALOG"}
    lits = _module_literals(path, wanted)
    for name in sorted(wanted - set(lits)):
        findings.append(Finding(
            path, 0, "slo-catalog",
            f"{name} not found or not a pure literal",
        ))
    if len(lits) != len(wanted):
        return findings
    slis, sli_line = lits["SLI_CATALOG"]
    windows, win_line = lits["BURN_WINDOWS"]
    slos, slo_line = lits["SLO_CATALOG"]

    for pair, spec in sorted(windows.items()):
        short_s, long_s = spec.get("short_s", 0), spec.get("long_s", 0)
        if not (0 < short_s < long_s):
            findings.append(Finding(
                path, win_line, "slo-catalog",
                f"BURN_WINDOWS[{pair!r}] needs 0 < short_s < long_s, "
                f"got ({short_s}, {long_s})",
            ))
        if spec.get("burn", 0) <= 0:
            findings.append(Finding(
                path, win_line, "slo-catalog",
                f"BURN_WINDOWS[{pair!r}] burn threshold must be positive",
            ))
    for name, spec in sorted(slos.items()):
        sli = spec.get("sli")
        if sli not in slis:
            findings.append(Finding(
                path, slo_line, "slo-catalog",
                f"SLO_CATALOG[{name!r}] names SLI {sli!r}, not in "
                "SLI_CATALOG",
            ))
        objective = spec.get("objective", 0)
        if not (0.0 < objective < 1.0):
            findings.append(Finding(
                path, slo_line, "slo-catalog",
                f"SLO_CATALOG[{name!r}] objective {objective!r} must be "
                "strictly inside (0, 1)",
            ))
        declared = spec.get("windows", ())
        if not declared:
            findings.append(Finding(
                path, slo_line, "slo-catalog",
                f"SLO_CATALOG[{name!r}] declares no window pairs",
            ))
        for pair in declared:
            if pair not in windows:
                findings.append(Finding(
                    path, slo_line, "slo-catalog",
                    f"SLO_CATALOG[{name!r}] references window pair "
                    f"{pair!r}, not in BURN_WINDOWS",
                ))
        if sli == "fast-availability" and not (
            spec.get("latency_threshold_ms", 0) > 0
        ):
            findings.append(Finding(
                path, slo_line, "slo-catalog",
                f"SLO_CATALOG[{name!r}] is a fast-availability SLO but "
                "declares no positive latency_threshold_ms",
            ))
    return findings


def check_plan_corpus() -> list[Finding]:
    """Pinned-plan corpus lint over scenarios/corpus/*.json.

    Each corpus file is the shrunk witness of a violation the nemesis
    search once found, auto-registered by scenarios.py as a regression
    scenario -- so a malformed pin fails silently at the worst moment (the
    regression stops running). Stdlib-only checks: the JSON parses, the
    harness is known, the plan carries an int seed and non-empty rules,
    every rule type is a RULE_CATALOG class, windows are sane
    [start, end|null] pairs, and probabilities sit in (0, 1]."""
    findings: list[Finding] = []
    corpus = sorted((REPO / "scenarios" / "corpus").glob("*.json"))
    catalog = set(_rule_subclasses(REPO / "rapid_tpu" / "faults.py"))

    def bad(path: Path, msg: str) -> None:
        findings.append(Finding(path, 1, "plan-corpus", msg))

    for path in corpus:
        try:
            spec = json.loads(path.read_text())
        except json.JSONDecodeError as exc:
            bad(path, f"not valid JSON: {exc}")
            continue
        if not isinstance(spec, dict):
            bad(path, "top level must be a probe-spec object")
            continue
        if spec.get("harness") not in ("engine", "sim"):
            bad(path, f"unknown harness {spec.get('harness')!r}")
        plan = spec.get("plan")
        if not isinstance(plan, dict):
            bad(path, "missing 'plan' object (FaultPlan.to_json dict)")
            continue
        if not isinstance(plan.get("seed"), int):
            bad(path, "plan.seed must be an int (determinism anchor)")
        rules = plan.get("rules")
        if not isinstance(rules, list) or not rules:
            bad(path, "plan.rules must be a non-empty list (an empty pin "
                      "witnesses nothing)")
            continue
        for i, rule in enumerate(rules):
            if not isinstance(rule, dict):
                bad(path, f"rules[{i}] is not an object")
                continue
            kind = rule.get("type")
            if kind not in catalog:
                bad(path, f"rules[{i}].type {kind!r} is not a Rule subclass "
                          "in rapid_tpu/faults.py")
            for window in rule.get("windows") or []:
                if (
                    not isinstance(window, list) or len(window) != 2
                    or not isinstance(window[0], int) or window[0] < 0
                    or not (
                        window[1] is None
                        or (isinstance(window[1], int)
                            and window[1] > window[0])
                    )
                ):
                    bad(path, f"rules[{i}] window {window!r} is not a sane "
                              "[start_ms, end_ms|null] pair")
            prob = rule.get("probability")
            if prob is not None and not (
                isinstance(prob, (int, float)) and 0 < prob <= 1
            ):
                bad(path, f"rules[{i}].probability {prob!r} outside (0, 1]")
    return findings


# ---------------------------------------------------------------------------
# concurrency hygiene (library code + analyzer fixtures only: tests, CLIs
# and experiments legitimately make short-lived foreground threads and
# invoke callables however they like)
# ---------------------------------------------------------------------------

CALLBACK_NAMES = {
    "callback", "callbacks", "cb", "fn", "func", "handler", "handlers",
    "subscriber", "subscribers", "listener", "listeners", "notifier",
    "hook", "hooks",
}
_LOCKISH = ("lock", "mutex", "cond")


def _hygiene_target(path: Path) -> bool:
    parts = set(path.parts)
    return "rapid_tpu" in parts or "fixtures" in parts


class _HygieneVisitor(ast.NodeVisitor):
    """thread-daemon + callback-under-lock, tracked through `with` bodies."""

    def __init__(self, path: Path, noqa: "dict[int, set[str]]") -> None:
        self.path = path
        self.noqa = noqa
        self.findings: list[Finding] = []
        self._locks_held = 0

    def _report(self, node: ast.AST, rule: str, msg: str) -> None:
        if not suppressed(self.noqa, node.lineno, rule):
            self.findings.append(Finding(self.path, node.lineno, rule, msg))

    @staticmethod
    def _terminal(expr: ast.expr) -> "str | None":
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Attribute):
            return expr.attr
        return None

    def visit_With(self, node: ast.With) -> None:
        lockish = sum(
            1 for item in node.items
            if (name := self._terminal(item.context_expr)) is not None
            and any(t in name.lower() for t in _LOCKISH)
        )
        self._locks_held += lockish
        self.generic_visit(node)
        self._locks_held -= lockish

    def visit_Call(self, node: ast.Call) -> None:
        name = self._terminal(node.func)
        if name == "Thread":
            daemon = next(
                (kw.value for kw in node.keywords if kw.arg == "daemon"), None
            )
            if not (isinstance(daemon, ast.Constant) and daemon.value is True):
                self._report(
                    node, "thread-daemon",
                    "threading.Thread in library code must be daemon=True "
                    "(or join it on shutdown and suppress this line)",
                )
            if (
                "messaging" in self.path.parts
                and self.path.name != "reactor.py"
            ):
                self._report(
                    node, "messaging-thread",
                    "thread construction in rapid_tpu/messaging/: socket "
                    "I/O belongs on the reactor (messaging/reactor.py); a "
                    "deliberately-owned worker needs an explicit waiver",
                )
        if self._locks_held and name is not None:
            if name in CALLBACK_NAMES or name.startswith("on_"):
                self._report(
                    node, "callback-under-lock",
                    f"calling {name}() while holding a lock: a callback "
                    f"that re-enters this object deadlocks; snapshot under "
                    f"the lock, call after release",
                )
        self.generic_visit(node)


def check_file(path: Path) -> list[Finding]:
    if not path.is_absolute():
        path = REPO / path
    source = path.read_text()
    try:
        # compile() rather than py_compile: Python 3.12 refuses non-regular
        # cfile targets, and we never want the .pyc anyway
        compile(source, str(path), "exec")
    except SyntaxError as exc:
        return [Finding(path, exc.lineno or 0, "syntax", str(exc))]
    tree = ast.parse(source, filename=str(path))
    checker = Checker(path, source, tree)
    checker.check_unused_imports()
    checker.visit(tree)
    findings = checker.findings
    if _hygiene_target(path):
        hygiene = _HygieneVisitor(path, noqa_lines(source))
        hygiene.visit(tree)
        findings.extend(hygiene.findings)
    return findings


def run(paths: "list[str] | None" = None) -> list[Finding]:
    """Importable entry point (mirrors concur.run)."""
    files = iter_py_files([Path(p) for p in (paths or DEFAULT_PATHS)])
    findings: list[Finding] = []
    for f in files:
        findings.extend(check_file(f))
    findings.extend(check_wire_tags())
    findings.extend(check_fault_rules())
    findings.extend(check_generator_reach())
    findings.extend(check_settings_catalog())
    findings.extend(check_metric_emission())
    findings.extend(check_event_emission())
    findings.extend(check_signature_catalog())
    findings.extend(check_slo_catalog())
    findings.extend(check_plan_corpus())
    return findings


def main(argv: list[str]) -> int:
    if "--rules" in argv:
        width = max(len(r) for r in RULE_DOCS)
        for rule, why in RULE_DOCS.items():
            print(f"{rule:<{width}}  {why}")
        return 0
    run_all = "--all" in argv
    paths = [a for a in argv if not a.startswith("--")]
    findings = run(paths or None)
    if run_all:
        if __package__ in (None, ""):
            import concur
            import devlint
        else:  # pragma: no cover - imported as a package module
            from . import concur, devlint
        findings.extend(concur.run())  # concur's own default: rapid_tpu
        findings.extend(devlint.run())  # devlint's own default: device plane
    for finding in findings:
        print(finding)
    label = "check+concur+devlint" if run_all else "check"
    print(f"{label}: {'OK' if not findings else f'{len(findings)} findings'}")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
