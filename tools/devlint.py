"""Device-plane performance analyzer (stdlib-only, AST-based).

The static half of the device-plane performance suite (the runtime half is
rapid_tpu/runtime/jitwatch.py). It scans the modules that own jit dispatch
and device-resident state -- the sim engine/driver/classic/pallas/bridge,
the placement and handoff device kernels, and the sharded engine -- and
reports the patterns that silently destroy steady-state throughput:

- ``recompile-hazard``: raw ``jax.jit`` that bypasses the ``make_jit`` seam
  (compiles invisible to jitwatch); jit wrappers created inside a function
  body (a fresh executable per call unless the caller caches); static
  parameters with shape/count-like names (``n``, ``rounds``, ``batch``...)
  whose per-call-varying values mint one executable per distinct value;
  unhashable (list/dict/set) values reaching a static slot; and loop bodies
  that feed the loop variable into a static slot of a jitted callee.
- ``host-sync``: undeclared host<->device round trips -- ``.item()`` /
  ``.tolist()``, ``int()``/``float()``/``bool()`` or ``np.asarray`` on
  device-resident state, raw ``jax.device_get`` / ``block_until_ready``
  outside the jitwatch ``fetch``/``drain`` helpers, and python control flow
  (``int()``/``if``) on traced parameters inside jitted bodies.
- ``dtype-discipline``: ``jnp`` array constructions with no explicit dtype
  (x64-flag-dependent, weak-type cache splits); arithmetic that silently
  widens the pinned narrow state fields (``fd_fail``/``fd_hist``/``fd_seen``:
  float constants, true division).
- ``donation-hygiene``: ``X = f(..., X, ...)`` state-update calls where
  ``f`` is a jitted entry with no ``donate_argnums`` -- the carried state
  doubles its peak memory every dispatch.

Conventions the analyzer understands (see ARCHITECTURE.md "Device-plane
performance discipline"); a tag on line L covers findings on L..L+3:

- ``# devlint: sync-point`` -- this host sync is deliberate and accounted
  (cold path, cached, or billed to setup); suppresses ``host-sync``.
- ``# devlint: no-donate`` -- the input state is deliberately kept alive
  (shared with other readers); suppresses ``donation-hygiene``.
- ``# devlint: jit-cached`` -- the jit wrapper created here is cached by
  the caller (one per key, not per call); suppresses ``recompile-hazard``.
- ``# devlint: static-shape`` -- the static value is drawn from a bounded
  set (compile classes are flat); suppresses ``recompile-hazard``.

Suppress single findings with ``# noqa: RULE`` (shared with tools/check.py).

Usage: python tools/devlint.py [paths...]   (default: the device plane)
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

if __package__ in (None, ""):
    sys.path.insert(0, str(Path(__file__).resolve().parent))
    from lintlib import Finding, iter_py_files, noqa_lines, parse, suppressed
else:  # pragma: no cover - imported as a package module
    from .lintlib import Finding, iter_py_files, noqa_lines, parse, suppressed

DEVICE_PLANE = [
    "rapid_tpu/sim/engine.py",
    "rapid_tpu/sim/driver.py",
    "rapid_tpu/sim/classic.py",
    "rapid_tpu/sim/pallas_kernels.py",
    "rapid_tpu/sim/bridge.py",
    "rapid_tpu/placement/device.py",
    "rapid_tpu/handoff/device.py",
    "rapid_tpu/shard/engine.py",
]

# ``# devlint: <tag>`` -> the rule it suppresses
TAG_RULES = {
    "sync-point": "host-sync",
    "no-donate": "donation-hygiene",
    "jit-cached": "recompile-hazard",
    "static-shape": "recompile-hazard",
}
TAG_WINDOW = 3  # a tag on line L covers findings on L..L+TAG_WINDOW

# static parameter name tokens that smell like per-call-varying shapes/counts
SHAPEY_TOKENS = {"n", "rounds", "rows", "batch", "size", "length", "steps",
                 "count"}

# the pinned narrow state fields (engine state catalog: uint8/int32)
NARROW_FIELDS = {"fd_fail", "fd_hist", "fd_seen"}

# jnp constructors -> positional index of their dtype slot
DTYPE_SLOT = {"zeros": 1, "ones": 1, "empty": 1, "full": 2, "arange": 3,
              "array": 1}

HOST_CASTS = {"int", "float", "bool"}


def _name_of(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _base_name(expr: ast.expr) -> Optional[str]:
    """Root name of an attribute chain ('jnp.zeros' -> 'jnp')."""
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _unparse(expr: ast.expr) -> str:
    try:
        return ast.unparse(expr)
    except Exception:  # noqa: BLE001 - best-effort label only
        return "<expr>"


def devlint_tags(source: str) -> Dict[int, Set[str]]:
    """line -> declared ``# devlint:`` tags on that line."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), 1):
        if "# devlint:" not in line:
            continue
        _, _, tail = line.partition("# devlint:")
        tags = {t.strip().lower() for t in tail.split("#")[0].split(",")}
        tags = {t for t in tags if t in TAG_RULES}
        if tags:
            out[i] = tags
    return out


def _tagged(tags: Dict[int, Set[str]], line: int, rule: str) -> bool:
    for tag_line in range(max(1, line - TAG_WINDOW), line + 1):
        for tag in tags.get(tag_line, ()):
            if TAG_RULES[tag] == rule:
                return True
    return False


def _is_jit_name(expr: ast.expr) -> bool:
    """jax.jit / jit (the raw, seam-bypassing form)."""
    return _name_of(expr) == "jit"


def _int_tuple(node: Optional[ast.expr]) -> Tuple[int, ...]:
    """Literal int / tuple-of-int value of a static_argnums-style operand."""
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


def _str_tuple(node: Optional[ast.expr]) -> Tuple[str, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(elt.value for elt in node.elts
                     if isinstance(elt, ast.Constant)
                     and isinstance(elt.value, str))
    return ()


class JitEntry:
    """One jitted callable: where it was created and what the analyzer could
    resolve about its static/donated slots."""

    def __init__(self, name: str, call: ast.Call,
                 fn: Optional[ast.AST]) -> None:
        self.name = name                  # bare python name it is bound to
        self.call = call                  # the make_jit/jax.jit call node
        self.fn = fn                      # wrapped FunctionDef, if resolved
        kw = {k.arg: k.value for k in call.keywords}
        self.static_nums = _int_tuple(kw.get("static_argnums"))
        self.static_names: Tuple[str, ...] = _str_tuple(
            kw.get("static_argnames"))
        self.donates = bool(_int_tuple(kw.get("donate_argnums")))
        self.params: List[str] = []
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.params = [a.arg for a in fn.args.args]

    def static_params(self) -> List[Tuple[object, str]]:
        """(slot, param-name) for every resolvable static slot."""
        out: List[Tuple[object, str]] = []
        for i in self.static_nums:
            if i < len(self.params):
                out.append((i, self.params[i]))
        for name in self.static_names:
            out.append((name, name))
        return out

    def traced_params(self) -> Set[str]:
        statics = {p for _, p in self.static_params()}
        return set(self.params) - statics


def _shapey(param: str) -> bool:
    return bool(SHAPEY_TOKENS & set(param.lower().split("_")))


def _contains(expr: ast.expr, pred) -> bool:
    return any(pred(sub) for sub in ast.walk(expr))


def _device_rooted(expr: ast.expr) -> bool:
    """Heuristic: the expression reads device-resident state (the engine
    state pytree or a ``*_dev`` cached array)."""
    def devy(sub: ast.AST) -> bool:
        if isinstance(sub, ast.Attribute):
            return sub.attr == "state" or sub.attr.endswith("_dev")
        if isinstance(sub, ast.Name):
            return sub.id == "state" or sub.id.endswith("_dev")
        return False
    return _contains(expr, devy)


def _goes_through_seam(expr: ast.expr) -> bool:
    """True if the expression routes through jitwatch's audited helpers."""
    def seam(sub: ast.AST) -> bool:
        return (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and _base_name(sub.func) == "jitwatch"
                and sub.func.attr in ("fetch", "drain", "host_transfer"))
    return _contains(expr, seam)


class Module:
    def __init__(self, path: Path, source: str, tree: ast.Module) -> None:
        self.path = path
        self.source = source
        self.tree = tree
        self.noqa = noqa_lines(source)
        self.tags = devlint_tags(source)
        self.defs: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, node)


class Analyzer:
    def __init__(self, files: List[Path]) -> None:
        self.modules: List[Module] = []
        self.findings: List[Finding] = []
        # bare name -> JitEntry, across every scanned file (the driver calls
        # entries the engine defines; one registry covers the import)
        self.registry: Dict[str, JitEntry] = {}
        for f in files:
            try:
                source, tree = parse(f)
            except SyntaxError:
                continue  # tools/check.py owns syntax reporting
            self.modules.append(Module(f, source, tree))

    def report(self, mod: Module, line: int, rule: str, msg: str) -> None:
        if suppressed(mod.noqa, line, rule) or _tagged(mod.tags, line, rule):
            return
        self.findings.append(Finding(mod.path, line, rule, msg))

    # -- phase 1: jit inventory --------------------------------------------

    def inventory(self) -> None:
        for mod in self.modules:
            # NAME = make_jit("class", fn, ...) at any nesting depth
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Assign):
                    continue
                value = node.value
                if not (isinstance(value, ast.Call)
                        and _name_of(value.func) in ("make_jit", "jit")):
                    continue
                fn_ref = None
                # make_jit("class", fn, ...): fn is the 2nd positional;
                # raw jit(fn, ...): fn is the 1st
                pos = 1 if _name_of(value.func) == "make_jit" else 0
                if len(value.args) > pos and isinstance(value.args[pos],
                                                        ast.Name):
                    fn_ref = mod.defs.get(value.args[pos].id)
                for t in node.targets:
                    name = _name_of(t)
                    if name:
                        self.registry[name] = JitEntry(name, value, fn_ref)
            # decorator form: @functools.partial(make_jit, "class", ...)
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for dec in node.decorator_list:
                    if (isinstance(dec, ast.Call)
                            and _name_of(dec.func) == "partial"
                            and dec.args
                            and _name_of(dec.args[0]) in ("make_jit", "jit")):
                        self.registry[node.name] = JitEntry(
                            node.name, dec, node)

    # -- rule: recompile-hazard --------------------------------------------

    def rule_recompile(self) -> None:
        for mod in self.modules:
            self._raw_jit_uses(mod)
            self._nested_jit_creation(mod)
            self._loop_varying_statics(mod)
        for entry in self.registry.values():
            self._shapey_statics(entry)
            self._unhashable_static_defaults(entry)

    def _raw_jit_uses(self, mod: Module) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec
                    if (isinstance(dec, ast.Call)
                            and _name_of(dec.func) == "partial" and dec.args):
                        target = dec.args[0]
                    elif isinstance(dec, ast.Call):
                        target = dec.func
                    if _is_jit_name(target):
                        self.report(
                            mod, node.lineno, "recompile-hazard",
                            f"raw jax.jit on {node.name}() bypasses the "
                            f"make_jit seam (rapid_tpu/runtime/jitwatch.py): "
                            f"its compiles are invisible to the recompile "
                            f"budget",
                        )
            elif (isinstance(node, ast.Call) and _is_jit_name(node.func)
                  and isinstance(node.func, ast.Attribute)):
                self.report(
                    mod, node.lineno, "recompile-hazard",
                    "raw jax.jit call bypasses the make_jit seam "
                    "(rapid_tpu/runtime/jitwatch.py)",
                )

    def _nested_jit_creation(self, mod: Module) -> None:
        for outer in ast.walk(mod.tree):
            if not isinstance(outer, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(outer):
                if (isinstance(node, ast.Call)
                        and _name_of(node.func) in ("make_jit", "jit")
                        and node is not outer):
                    self.report(
                        mod, node.lineno, "recompile-hazard",
                        f"jit wrapper created inside {outer.name}(): a fresh "
                        f"executable per call unless the caller caches it "
                        f"(tag '# devlint: jit-cached' if it does)",
                    )
                    break  # one finding per enclosing function is enough

    def _shapey_statics(self, entry: JitEntry) -> None:
        mod = self._module_of(entry.call)
        if mod is None:
            return
        for slot, param in entry.static_params():
            if _shapey(param):
                self.report(
                    mod, entry.call.lineno, "recompile-hazard",
                    f"static parameter {param!r} of {entry.name} looks "
                    f"shape/count-like: per-call-varying values mint one "
                    f"executable each (tag '# devlint: static-shape' if the "
                    f"value set is bounded)",
                )

    def _unhashable_static_defaults(self, entry: JitEntry) -> None:
        mod = self._module_of(entry.call)
        if mod is None or not isinstance(
                entry.fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        args = entry.fn.args
        defaults = args.defaults
        offset = len(args.args) - len(defaults)
        statics = {p for _, p in entry.static_params()}
        for i, default in enumerate(defaults):
            param = args.args[offset + i].arg
            if param in statics and isinstance(
                    default, (ast.List, ast.Dict, ast.Set)):
                self.report(
                    mod, default.lineno, "recompile-hazard",
                    f"static parameter {param!r} of {entry.name} defaults to "
                    f"an unhashable {type(default).__name__.lower()}: every "
                    f"call raises or re-traces; use a tuple",
                )

    def _loop_varying_statics(self, mod: Module) -> None:
        for loop in ast.walk(mod.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            loop_vars: Set[str] = set()
            if isinstance(loop, ast.For):
                for sub in ast.walk(loop.target):
                    if isinstance(sub, ast.Name):
                        loop_vars.add(sub.id)
            if not loop_vars:
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                entry = self.registry.get(_name_of(node.func) or "")
                if entry is None:
                    continue
                static_idx = {s for s, _ in entry.static_params()
                              if isinstance(s, int)} | set(entry.static_nums)
                for i, arg in enumerate(node.args):
                    if i in static_idx and _contains(
                            arg, lambda s: isinstance(s, ast.Name)
                            and s.id in loop_vars):
                        self.report(
                            mod, node.lineno, "recompile-hazard",
                            f"static argument {i} of {entry.name} varies "
                            f"with the loop variable: one executable per "
                            f"distinct value (tag '# devlint: static-shape' "
                            f"if the value set is bounded)",
                        )
                # unhashable literals reaching a static slot
                for i, arg in enumerate(node.args):
                    if i in static_idx and isinstance(
                            arg, (ast.List, ast.Dict, ast.Set)):
                        self.report(
                            mod, node.lineno, "recompile-hazard",
                            f"unhashable literal at static argument {i} of "
                            f"{entry.name}: jit statics must be hashable",
                        )

    # -- rule: host-sync ----------------------------------------------------

    def rule_host_sync(self) -> None:
        for mod in self.modules:
            jitted_defs = {id(e.fn) for e in self.registry.values()
                           if e.fn is not None}
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    self._sync_call(mod, node)
            for entry in self.registry.values():
                if entry.fn is not None and id(entry.fn) in jitted_defs:
                    if self._module_of(entry.call) is mod:
                        self._traced_misuse(mod, entry)

    def _sync_call(self, mod: Module, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in ("item", "tolist"):
                if _device_rooted(func.value):
                    self.report(
                        mod, node.lineno, "host-sync",
                        f".{func.attr}() on device state blocks on the "
                        f"device queue; route it through jitwatch.fetch() "
                        f"or tag '# devlint: sync-point'",
                    )
                return
            if (func.attr in ("asarray", "array")
                    and _base_name(func) in ("np", "numpy", "onp")):
                if node.args and _device_rooted(node.args[0]) \
                        and not _goes_through_seam(node.args[0]):
                    self.report(
                        mod, node.lineno, "host-sync",
                        f"np.{func.attr}() on device state is an implicit "
                        f"device->host copy; route it through "
                        f"jitwatch.fetch() or tag '# devlint: sync-point'",
                    )
                return
            if func.attr == "device_get" or func.attr == "block_until_ready":
                if _base_name(func) == "jitwatch":
                    return
                self.report(
                    mod, node.lineno, "host-sync",
                    f"raw {func.attr}(): un-annotated sync point; use "
                    f"jitwatch.fetch()/drain() or tag "
                    f"'# devlint: sync-point'",
                )
                return
        if (isinstance(func, ast.Name) and func.id in HOST_CASTS
                and node.args):
            arg = node.args[0]
            if _device_rooted(arg) and not _goes_through_seam(arg):
                self.report(
                    mod, node.lineno, "host-sync",
                    f"{func.id}() on device state forces a blocking "
                    f"device->host transfer; route it through "
                    f"jitwatch.fetch() or tag '# devlint: sync-point'",
                )

    def _traced_misuse(self, mod: Module, entry: JitEntry) -> None:
        traced = entry.traced_params()
        if not traced:
            return
        for node in ast.walk(entry.fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in HOST_CASTS and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in traced):
                self.report(
                    mod, node.lineno, "host-sync",
                    f"{node.func.id}() on traced parameter "
                    f"{node.args[0].id!r} inside jitted {entry.name}: "
                    f"fails under jit (or silently bakes a constant); use "
                    f"lax ops on the traced value",
                )
            if (isinstance(node, ast.If)
                    and isinstance(node.test, ast.Name)
                    and node.test.id in traced):
                self.report(
                    mod, node.lineno, "host-sync",
                    f"python branch on traced parameter {node.test.id!r} "
                    f"inside jitted {entry.name}: use lax.cond / jnp.where",
                )

    # -- rule: dtype-discipline --------------------------------------------

    def rule_dtype(self) -> None:
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    self._dtype_construction(mod, node)
                elif isinstance(node, ast.BinOp):
                    self._narrow_widening(mod, node)

    def _dtype_construction(self, mod: Module, node: ast.Call) -> None:
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and _base_name(func) == "jnp"
                and func.attr in DTYPE_SLOT):
            return
        if any(kw.arg == "dtype" for kw in node.keywords):
            return
        if len(node.args) > DTYPE_SLOT[func.attr]:
            return  # dtype passed positionally
        self.report(
            mod, node.lineno, "dtype-discipline",
            f"jnp.{func.attr}() without an explicit dtype: the result "
            f"depends on the x64 flag and weak-type promotion (a silent "
            f"cache split); pin it",
        )

    def _narrow_widening(self, mod: Module, node: ast.BinOp) -> None:
        def narrow(sub: ast.AST) -> bool:
            return (isinstance(sub, (ast.Attribute, ast.Name))
                    and _name_of(sub) in NARROW_FIELDS)

        sides = [node.left, node.right]
        if not any(_contains(s, narrow) for s in sides):
            return
        # .astype on either side is an explicit, audited widen
        if any(_contains(s, lambda n: isinstance(n, ast.Call)
                         and isinstance(n.func, ast.Attribute)
                         and n.func.attr == "astype") for s in sides):
            return
        field = next(_name_of(sub) for s in sides for sub in ast.walk(s)
                     if narrow(sub))
        if isinstance(node.op, ast.Div):
            self.report(
                mod, node.lineno, "dtype-discipline",
                f"true division on narrow state field {field!r} silently "
                f"widens the pinned dtype to float; use // or an explicit "
                f".astype()",
            )
            return
        for s in sides:
            if _contains(s, lambda n: isinstance(n, ast.Constant)
                         and isinstance(n.value, float)):
                self.report(
                    mod, node.lineno, "dtype-discipline",
                    f"float constant in arithmetic on narrow state field "
                    f"{field!r} silently widens the pinned dtype; use an "
                    f"explicit .astype()",
                )
                return

    # -- rule: donation-hygiene --------------------------------------------

    def rule_donation(self) -> None:
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                entry = self.registry.get(_name_of(node.value.func) or "")
                if entry is None or entry.donates:
                    continue
                arg_reprs = {_unparse(a) for a in node.value.args}
                targets: List[ast.expr] = []
                for t in node.targets:
                    targets.extend(t.elts if isinstance(
                        t, (ast.Tuple, ast.List)) else [t])
                for t in targets:
                    if _unparse(t) in arg_reprs:
                        self.report(
                            mod, node.lineno, "donation-hygiene",
                            f"{_unparse(t)} is carried through jitted "
                            f"{entry.name} with no donate_argnums: the old "
                            f"buffers stay live across the call (peak memory "
                            f"doubles); donate, or tag "
                            f"'# devlint: no-donate' if the input is shared",
                        )
                        break

    # -- driver -------------------------------------------------------------

    def _module_of(self, node: ast.AST) -> Optional[Module]:
        if not hasattr(self, "_node_mod"):
            self._node_mod: Dict[int, Module] = {}
            for mod in self.modules:
                for sub in ast.walk(mod.tree):
                    self._node_mod[id(sub)] = mod
        return self._node_mod.get(id(node))

    def run(self) -> List[Finding]:
        self.inventory()
        self.rule_recompile()
        self.rule_host_sync()
        self.rule_dtype()
        self.rule_donation()
        # dedup (a node can be reached by more than one walk) + stable order
        seen: Set[str] = set()
        unique: List[Finding] = []
        for f in sorted(self.findings,
                        key=lambda f: (str(f.path), f.line, f.rule, f.msg)):
            if str(f) not in seen:
                seen.add(str(f))
                unique.append(f)
        self.findings = unique
        return self.findings


# --------------------------------------------------------------------------- #
# entry points
# --------------------------------------------------------------------------- #

def run(paths: Optional[List[str]] = None) -> List[Finding]:
    files = iter_py_files([Path(p) for p in (paths or DEVICE_PLANE)])
    return Analyzer(files).run()


def main(argv: List[str]) -> int:
    findings = run(argv or None)
    for finding in findings:
        print(finding)
    print(f"devlint: {'OK' if not findings else f'{len(findings)} findings'}")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
