#!/usr/bin/env python
"""rapid-top: poll the cluster-status introspection RPC of live agents.

Sends a ``ClusterStatusRequest`` to one or more members over the framed-TCP
transport and renders each answer: configuration id, view size, cut-detector
watermark occupancy, consensus round state, a compact metrics digest, and the
tail of the node's flight-recorder journal. Because the request is answered
on the protocol executor, the numbers are a consistent snapshot of that
node's protocol state, and disagreement in ``config`` across members is
itself the finding.

    python tools/statusz.py 127.0.0.1:1234 127.0.0.1:1235
    python tools/statusz.py --json --journal 10 127.0.0.1:1234
    python tools/statusz.py --history 32 127.0.0.1:1234 127.0.0.1:1235
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # runnable as a script from anywhere in the tree
    sys.path.insert(0, _REPO)

from rapid_tpu import Endpoint, Settings  # noqa: E402
from rapid_tpu.messaging.tcp import TcpClientServer  # noqa: E402
from rapid_tpu.profiling import cluster_timeseries, merge_by_series  # noqa: E402
from rapid_tpu.types import ClusterStatusRequest, ClusterStatusResponse  # noqa: E402


def fetch_status(
    client: TcpClientServer, target: Endpoint, timeout_s: float = 5.0,
    include_history: int = 0,
) -> ClusterStatusResponse:
    reply = client.send_message(
        target,
        ClusterStatusRequest(
            sender=client.address, include_history=include_history
        ),
    ).result(timeout_s)
    if not isinstance(reply, ClusterStatusResponse):
        raise RuntimeError(
            f"{target}: unexpected reply {type(reply).__name__}"
        )
    return reply


def render(status: ClusterStatusResponse, journal_lines: int = 5) -> str:
    lines = [
        f"{status.sender}  config={status.configuration_id}"
        f"  members={status.membership_size}",
        f"  cut-detector: tracked={status.reports_tracked}"
        f" pre-proposal={status.pre_proposal_size}"
        f" proposal={status.proposal_size}"
        f" in-progress={status.updates_in_progress}",
        f"  consensus: decided={status.consensus_decided}"
        f" votes={status.consensus_votes}",
    ]
    # hierarchy digest: which cell this member sits in, its parent
    # configuration, and the composed global view it has adopted
    # (hierarchy on <=> global_cells is non-empty)
    if status.global_cells:
        lines.append(
            f"  hierarchy: cell={status.cell_id}"
            f" cell-size={status.cell_size}"
            f" parent-config={status.parent_configuration_id}"
            f" cells={len(status.global_cells)}"
            f" members={sum(status.global_sizes)}"
            f" fingerprint={status.global_fingerprint}"
        )
    if status.placement_partitions:
        lines.append(
            f"  placement: version={status.placement_version}"
            f" partitions={status.placement_partitions}"
            f" owned={status.placement_owned}"
        )
    if (
        status.handoff_in_flight
        or status.handoff_completed
        or status.handoff_failed
        or status.handoff_partitions
    ):
        lines.append(
            f"  handoff: in-flight={status.handoff_in_flight}"
            f" completed={status.handoff_completed}"
            f" failed={status.handoff_failed}"
            f" stored={len(status.handoff_partitions)}"
        )
    if status.serving_gets or status.serving_puts or status.serving_partitions:
        lines.append(
            f"  serving: gets={status.serving_gets}"
            f" puts={status.serving_puts}"
            f" acks={status.serving_put_acks}"
            f" leads={sum(1 for lead in status.serving_leaders if lead == str(status.sender))}"
            f"/{len(status.serving_partitions)}"
        )
    # durability digest: restart health -- how much log a crash would
    # replay (zero right after a checkpoint) and which snapshot anchors it
    if status.durability_segments or status.durability_snapshot_version:
        lines.append(
            f"  durability: segments={status.durability_segments}"
            f" snapshot={status.durability_snapshot_version}"
            f" replayed={status.durability_replayed}"
        )
    # SLO digest: one line per (SLO, window pair) alert -- burn rate in
    # budget multiples, FIRING flag, and the attributed churn episode's
    # trace id when the plane correlated one against the journal
    if status.slo_names:
        alerts = " ".join(
            "{name}={burn:.2f}x{firing}{trace}".format(
                name=name, burn=burn_milli / 1000.0,
                firing=" FIRING" if firing else "",
                trace=f"(episode {trace})" if trace else "",
            )
            for name, burn_milli, firing, trace in zip(
                status.slo_names, status.slo_burn_milli,
                status.slo_firing, status.slo_attributed_trace,
            )
        )
        lines.append(f"  slo: {alerts}")
    # failure-detector digest: the node's worst monitored edges (already
    # sorted suspicion desc, RTT desc by the service), the gray-failure
    # signature an operator checks before any eviction shows up
    if status.fd_subjects:
        worst = list(
            zip(status.fd_subjects, status.fd_rtt_micros,
                status.fd_suspicion_milli)
        )[:3]
        edges = " ".join(
            f"{subject}(rtt={rtt_us / 1000.0:.1f}ms"
            f" susp={susp / 1000.0:.2f})"
            for subject, rtt_us, susp in worst
        )
        lines.append(f"  fd-edges: monitored={len(status.fd_subjects)} {edges}")
    if status.fd_tiers:
        tiers = " ".join(
            f"{tier}(interval={interval}ms threshold={threshold}"
            f" flush={flush}ms)"
            for tier, interval, threshold, flush in zip(
                status.fd_tiers, status.fd_tier_interval_ms,
                status.fd_tier_threshold, status.fd_tier_flush_ms,
            )
        )
        lines.append(f"  fd-tiers: {tiers}")
    # transport summary: per-peer outbound queue depths (the backpressure
    # signature of a slow-reading peer) get a first-class line above the
    # raw metric digest they also appear in
    depths = [
        (name[len("msg.queue_depth{peer="):-1], value)
        for name, value in zip(status.metric_names, status.metric_values)
        if name.startswith("msg.queue_depth{peer=")
    ]
    if depths:
        total = sum(v for _, v in depths)
        deepest = max(depths, key=lambda kv: kv[1])
        lines.append(
            f"  transport: peers={len(depths)}"
            f" queued-bytes={total:.0f}"
            f" deepest={deepest[0]}:{deepest[1]:.0f}"
        )
    for name, value in zip(status.metric_names, status.metric_values):
        lines.append(f"  metric {name} = {value}")
    tail = status.journal[-journal_lines:] if journal_lines else ()
    for raw in tail:
        try:
            entry = json.loads(raw)
            lines.append(
                "  journal [{seq}] {kind} @{virtual_ms}ms {detail}".format(
                    seq=entry.get("seq"), kind=entry.get("kind"),
                    virtual_ms=entry.get("virtual_ms"),
                    detail=entry.get("detail", {}),
                )
            )
        except (ValueError, TypeError):
            lines.append(f"  journal {raw}")
    return "\n".join(lines)


def to_json(status: ClusterStatusResponse) -> dict:
    return {
        "node": str(status.sender),
        "configuration_id": status.configuration_id,
        "membership_size": status.membership_size,
        "reports_tracked": status.reports_tracked,
        "pre_proposal_size": status.pre_proposal_size,
        "proposal_size": status.proposal_size,
        "updates_in_progress": status.updates_in_progress,
        "consensus_decided": status.consensus_decided,
        "consensus_votes": status.consensus_votes,
        "hierarchy": {
            "cell_id": status.cell_id,
            "cell_size": status.cell_size,
            "parent_configuration_id": status.parent_configuration_id,
            "global_fingerprint": status.global_fingerprint,
            "cells": {
                str(cell): {"epoch": epoch, "size": size, "leader": leader}
                for cell, epoch, size, leader in zip(
                    status.global_cells, status.global_epochs,
                    status.global_sizes, status.global_leaders,
                )
            },
        } if status.global_cells else None,
        "placement_version": status.placement_version,
        "placement_partitions": status.placement_partitions,
        "placement_owned": status.placement_owned,
        "handoff_in_flight": status.handoff_in_flight,
        "handoff_completed": status.handoff_completed,
        "handoff_failed": status.handoff_failed,
        "handoff_partitions": {
            str(p): fp
            for p, fp in zip(
                status.handoff_partitions, status.handoff_fingerprints
            )
        },
        "durability_segments": status.durability_segments,
        "durability_snapshot_version": status.durability_snapshot_version,
        "durability_replayed": status.durability_replayed,
        "serving_gets": status.serving_gets,
        "serving_puts": status.serving_puts,
        "serving_put_acks": status.serving_put_acks,
        "serving_leaders": {
            str(p): leader
            for p, leader in zip(
                status.serving_partitions, status.serving_leaders
            )
        },
        "fd_edges": {
            subject: {
                "rtt_ms": rtt_us / 1000.0,
                "suspicion": susp / 1000.0,
            }
            for subject, rtt_us, susp in zip(
                status.fd_subjects, status.fd_rtt_micros,
                status.fd_suspicion_milli,
            )
        },
        "fd_tiers": {
            tier: {
                "interval_ms": interval,
                "threshold": threshold,
                "flush_ms": flush,
            }
            for tier, interval, threshold, flush in zip(
                status.fd_tiers, status.fd_tier_interval_ms,
                status.fd_tier_threshold, status.fd_tier_flush_ms,
            )
        },
        "slo_alerts": {
            name: {
                "burn": burn_milli / 1000.0,
                "firing": bool(firing),
                "attributed_trace": trace,
            }
            for name, burn_milli, firing, trace in zip(
                status.slo_names, status.slo_burn_milli,
                status.slo_firing, status.slo_attributed_trace,
            )
        },
        "metrics": dict(zip(status.metric_names, status.metric_values)),
        "journal": [json.loads(line) for line in status.journal],
        "history": [json.loads(line) for line in status.history],
    }


def render_timeseries(statuses: List[ClusterStatusResponse],
                      max_series: int = 12) -> str:
    """The cluster-wide timeseries view assembled from every scraped
    member's history ring: one line per (series, node) with span, point
    count and last value -- the operator's "what moved, where" summary."""
    by_series = merge_by_series(cluster_timeseries(statuses))
    lines = ["cluster timeseries:"]
    if not by_series:
        lines.append("  (no history scraped -- profiling off or old peers)")
        return "\n".join(lines)
    for name in sorted(by_series)[:max_series]:
        for node, points in sorted(by_series[name].items()):
            first_ts, _ = points[0]
            last_ts, last = points[-1]
            lines.append(
                f"  {name} @{node}: n={len(points)}"
                f" span={last_ts - first_ts:.1f}s last={last:g}"
            )
    if len(by_series) > max_series:
        lines.append(f"  ... and {len(by_series) - max_series} more series")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="poll rapid-tpu agents' cluster-status RPC"
    )
    parser.add_argument("targets", nargs="+", help="host:port of live agents")
    parser.add_argument("--timeout", type=float, default=5.0)
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit one JSON object per target")
    parser.add_argument("--journal", type=int, default=5,
                        help="journal tail lines to show (text mode)")
    parser.add_argument("--history", type=int, default=0,
                        help="metric history snapshots to scrape per node; "
                        "also renders the assembled cluster timeseries")
    args = parser.parse_args(argv)
    # client half only: no start() means no listening socket is ever bound
    client = TcpClientServer(Endpoint(b"127.0.0.1", 0), Settings())
    rc = 0
    # cell id (None = flat member) -> configuration ids seen there. In
    # hierarchical mode each cell is its own Rapid cluster, so members of
    # *different* cells legitimately carry different (cell-local) config
    # ids -- disagreement only means trouble within one cell.
    configs: dict = {}
    placements = set()
    # composed global-view fingerprints from hierarchy-enabled members
    hier_fps = set()
    # partition id -> set of content fingerprints reported by its holders
    fingerprints: dict = {}
    # partition id -> set of serving leaders reported by its replicas
    leaders: dict = {}
    statuses: List[ClusterStatusResponse] = []
    try:
        for raw in args.targets:
            target = Endpoint.from_string(raw)
            try:
                # only the history-bearing form passes the extra argument:
                # the plain poll keeps the pre-profiling 3-arg call shape
                # (monkeypatched in the handoff/serving statusz tests)
                if args.history:
                    status = fetch_status(
                        client, target, args.timeout,
                        include_history=args.history,
                    )
                else:
                    status = fetch_status(client, target, args.timeout)
            except Exception as exc:  # noqa: BLE001 -- report and keep polling
                print(f"{raw}: unreachable ({exc})", file=sys.stderr)
                rc = 1
                continue
            statuses.append(status)
            cell_key = status.cell_id if status.global_cells else None
            configs.setdefault(cell_key, set()).add(status.configuration_id)
            if status.placement_partitions:
                placements.add(status.placement_version)
            if status.global_cells:
                hier_fps.add(status.global_fingerprint)
            for part, fp in zip(
                status.handoff_partitions, status.handoff_fingerprints
            ):
                fingerprints.setdefault(part, set()).add(fp)
            for part, leader in zip(
                status.serving_partitions, status.serving_leaders
            ):
                leaders.setdefault(part, set()).add(leader)
            if args.as_json:
                print(json.dumps(to_json(status), sort_keys=True))
            else:
                print(render(status, journal_lines=args.journal))
        if args.history and not args.as_json and statuses:
            print(render_timeseries(statuses))
    finally:
        client.shutdown()
    for cell_key in sorted(configs, key=lambda k: (k is not None, k or 0)):
        ids = configs[cell_key]
        if len(ids) <= 1:
            continue
        scope = ("configuration id" if cell_key is None
                 else f"cell {cell_key} configuration id")
        print(
            f"WARNING: members disagree on {scope}: {sorted(ids)}",
            file=sys.stderr,
        )
        rc = max(rc, 2)
    # the composed global view folds every cell's (epoch, size, leader,
    # membership) into one integer, so fingerprint disagreement among
    # hierarchy-enabled members is the cross-cell analogue of config-id
    # disagreement: somebody has not adopted the parent decision
    if len(hier_fps) > 1:
        print(
            "WARNING: members disagree on the composed global view "
            f"fingerprint: {sorted(hier_fps)}",
            file=sys.stderr,
        )
        rc = max(rc, 2)
    # the placement map is a pure function of the configuration, so version
    # disagreement among placement-enabled members is the same class of
    # finding as config-id disagreement (split-brain / drifted map function)
    if len(placements) > 1:
        print(
            "WARNING: members disagree on placement map version: "
            f"{sorted(placements)}",
            file=sys.stderr,
        )
        rc = max(rc, 2)
    # replicas of a partition must hold byte-identical content once handoff
    # has drained; divergent fingerprints mean a corrupt or torn transfer
    # survived verification somewhere, which is the same severity of finding
    # as a split-brain configuration
    torn = sorted(p for p, fps in fingerprints.items() if len(fps) > 1)
    if torn:
        print(
            "WARNING: replicas disagree on partition content fingerprints: "
            f"partitions {torn}",
            file=sys.stderr,
        )
        rc = max(rc, 2)
    # the serving leader is a pure function of the placement row (first
    # live replica in placement order), so two replicas of one partition
    # naming different leaders is a split-brain write path: both would
    # accept quorum writes for the same keys
    split = sorted(p for p, who in leaders.items() if len(who) > 1)
    if split:
        print(
            "WARNING: replicas disagree on the serving leader: "
            f"partitions {split}",
            file=sys.stderr,
        )
        rc = max(rc, 2)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
