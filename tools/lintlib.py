"""Shared plumbing for the repo's stdlib-only static analyzers.

Both analyzers -- tools/check.py (general code health + catalog lints) and
tools/concur.py (concurrency correctness) -- report through one Finding type,
honor the same ``# noqa`` / ``# noqa: RULE`` suppression syntax (rule names
case-insensitive, comma-separated), and scan the same file universe. Keeping
that here means a suppression or a path exclusion behaves identically no
matter which tool surfaced the finding, and `python tools/check.py --all`
can merge both reports into one exit code.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Set

REPO = Path(__file__).resolve().parent.parent

# directories whose .py files are deliberately bad examples (analyzer
# regression fixtures) or generated -- never part of a default scan
EXCLUDED_DIR_NAMES = {"fixtures", "__pycache__", ".git"}


class Finding:
    def __init__(self, path: Path, line: int, rule: str, msg: str) -> None:
        self.path, self.line, self.rule, self.msg = Path(path), line, rule, msg

    def __str__(self) -> str:
        try:
            rel = self.path.relative_to(REPO)
        except ValueError:
            rel = self.path
        return f"{rel}:{self.line}: {self.rule} {self.msg}"

    def __repr__(self) -> str:
        return f"Finding({self})"


def noqa_lines(source: str) -> Dict[int, Set[str]]:
    """line -> suppressed rule names, lowercased ('*' = suppress all)."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), 1):
        if "# noqa" not in line:
            continue
        _, _, tail = line.partition("# noqa")
        tail = tail.strip()
        if tail.startswith(":"):
            out[i] = {r.strip().lower() for r in tail[1:].split(",")}
        else:
            out[i] = {"*"}
    return out


def suppressed(noqa: Dict[int, Set[str]], line: int, rule: str) -> bool:
    rules = noqa.get(line, set())
    return "*" in rules or rule.lower() in rules


def iter_py_files(roots: Iterable[Path]) -> List[Path]:
    """Every .py file under the given roots, fixtures/caches excluded,
    sorted for deterministic reports."""
    files: List[Path] = []
    for root in roots:
        root = (REPO / root) if not root.is_absolute() else root
        if root.is_dir():
            for f in sorted(root.rglob("*.py")):
                if not EXCLUDED_DIR_NAMES & set(f.parts):
                    files.append(f)
        elif root.exists():
            files.append(root)
    return files


def parse(path: Path) -> "tuple[str, ast.Module]":
    source = path.read_text()
    return source, ast.parse(source, filename=str(path))
