"""Coverage-guided nemesis search CLI ("Jepsen in a box", ROADMAP item 4).

Drives a budgeted :class:`rapid_tpu.search.hunt.Hunter` run: sample or
mutate FaultPlans, execute each as a probe on the chosen harness, check
invariants (linearizability, view agreement, config parity, fingerprint
agreement), bias generation toward unvisited coverage signals, shrink
the first witness of each violation kind, and print a corpus/coverage
report. Everything is deterministic per --seed.

    python tools/hunt.py --budget 200                  # engine harness
    python tools/hunt.py --harness sim --budget 20     # simulator replay
    python tools/hunt.py --unguided                    # coverage bias off
    python tools/hunt.py --pin scenarios/corpus        # write shrunk plans
    python tools/hunt.py --json                        # machine-readable

Pinned plans land as scenarios/corpus/*.json, which scenarios.py
auto-registers into the battery as regression scenarios.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="coverage-guided nemesis search over FaultPlans"
    )
    parser.add_argument("--seed", type=int, default=0,
                        help="search seed (same seed -> same hunt)")
    parser.add_argument("--budget", type=int, default=200,
                        help="number of probes to run")
    parser.add_argument("--harness", choices=("engine", "sim"),
                        default="engine",
                        help="engine: real ServingEngines on the virtual-"
                             "time fabric; sim: device-plane replay on the "
                             "Simulator (slower, needs jax)")
    parser.add_argument("--unguided", action="store_true",
                        help="disable the coverage-bias corpus (baseline "
                             "random search)")
    parser.add_argument("--no-shrink", action="store_true",
                        help="report violations without minimizing them")
    parser.add_argument("--shrink-budget", type=int, default=200,
                        help="probe budget per shrink")
    parser.add_argument("--pin", metavar="DIR",
                        help="write each shrunk violation to DIR as a "
                             "corpus JSON (scenarios.py auto-registers "
                             "scenarios/corpus/*.json)")
    parser.add_argument("--json", action="store_true",
                        help="emit the report as JSON instead of text")
    parser.add_argument("--forensics", action="store_true",
                        help="run probes with the HLC forensics mirror and "
                             "pin each shrunk witness WITH its evidence "
                             "bundle (--pin writes a .bundle.json sidecar "
                             "readable by tools/forensics.py report)")
    args = parser.parse_args(argv)

    from rapid_tpu.search.hunt import Hunter, pin_to_file

    hunter = Hunter(
        seed=args.seed, budget=args.budget, harness=args.harness,
        guided=not args.unguided, shrink=not args.no_shrink,
        shrink_budget=args.shrink_budget, forensics=args.forensics,
    )
    report = hunter.run()

    written = []
    if args.pin:
        pin_dir = Path(args.pin)
        pin_dir.mkdir(parents=True, exist_ok=True)
        for i, pin in enumerate(report.pinned):
            kinds = "-".join(pin["kinds"])
            name = f"hunt-s{args.seed}-{args.harness}-{kinds}-{i}"
            path = pin_dir / f"{name}.json"
            pin_to_file(
                pin, str(path), name,
                f"shrunk by tools/hunt.py --seed {args.seed} "
                f"--budget {args.budget} --harness {args.harness}",
            )
            written.append(str(path))

    if args.json:
        out = report.to_json()
        out["pinned_files"] = written
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        print(report.report_text())
        for path in written:
            print(f"  wrote {path}")
    return 1 if report.violations else 0


if __name__ == "__main__":
    sys.exit(main())
