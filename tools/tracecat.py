#!/usr/bin/env python
"""Merge per-node Chrome traces into one cluster-wide timeline.

Each rapid_tpu process exports its own ``chrome_trace`` JSON with timestamps
relative to that process's first span -- loading two of them side by side in
Perfetto puts both nodes at t=0 and the causal order is lost. This tool
merges N per-node trace files into a single file:

- every input's processes are re-numbered to unique pids and renamed
  ``<label>/<plane>`` so each node keeps its own process rows;
- wall-clock rows are offset-aligned across inputs using the virtual-time
  track the exporter dual-emits: a span that appears on both its wall row
  and the ``virtual-time (ms)`` row (matched by ``args.span_id``) yields one
  ``virtual_ts - wall_ts`` sample, and the per-input mean of those samples
  shifts that input's wall rows onto the shared virtual axis. Inputs with no
  virtual samples are left at their own zero;
- the per-input virtual-time processes are merged into ONE shared
  ``virtual-time (ms)`` process (the axis is cluster-global by construction);
- ``--trace-id`` keeps only the spans of one distributed trace, so a single
  churn episode -- fd_signal on the observer through view_change on every
  member -- can be read end to end.

Stdlib only; usable as a library (``merge_traces``) or a CLI:

    python tools/tracecat.py node1.json node2.json -o merged.json
    python tools/tracecat.py --trace-id 42 node*.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

VIRTUAL_PROCESS_NAME = "virtual-time (ms)"


def _virtual_pid(events: List[dict]) -> Optional[int]:
    for ev in events:
        if (
            ev.get("ph") == "M"
            and ev.get("name") == "process_name"
            and ev.get("args", {}).get("name") == VIRTUAL_PROCESS_NAME
        ):
            return ev.get("pid")
    return None


def _wall_offset_us(events: List[dict], virtual_pid: Optional[int]) -> float:
    """Mean (virtual_ts - wall_ts) over dual-emitted spans: the shift that
    maps this input's wall rows onto the shared virtual axis."""
    if virtual_pid is None:
        return 0.0
    virtual_ts: Dict[int, int] = {}
    for ev in events:
        if ev.get("ph") == "X" and ev.get("pid") == virtual_pid:
            span_id = ev.get("args", {}).get("span_id")
            if span_id is not None:
                virtual_ts.setdefault(span_id, ev["ts"])
    samples = [
        virtual_ts[ev["args"]["span_id"]] - ev["ts"]
        for ev in events
        if ev.get("ph") == "X"
        and ev.get("pid") != virtual_pid
        and ev.get("args", {}).get("span_id") in virtual_ts
    ]
    if not samples:
        return 0.0
    return sum(samples) / len(samples)


def merge_traces(
    traces: List[dict],
    labels: Optional[List[str]] = None,
    trace_id: Optional[int] = None,
) -> dict:
    """Merge chrome_trace dicts (one per node) into a single timeline."""
    if labels is None:
        labels = [f"node{i}" for i in range(len(traces))]
    assert len(labels) == len(traces)
    merged: List[dict] = []
    merged_virtual_pid = 1  # pid 1 is the shared virtual axis
    next_pid = 2
    next_virtual_tid = 1
    merged.append({
        "ph": "M", "pid": merged_virtual_pid, "name": "process_name",
        "args": {"name": VIRTUAL_PROCESS_NAME},
    })
    for label, trace in zip(labels, traces):
        events = trace.get("traceEvents", [])
        virtual_pid = _virtual_pid(events)
        offset = _wall_offset_us(events, virtual_pid)
        pid_map: Dict[int, int] = {}
        virtual_tid_map: Dict[int, int] = {}
        for ev in events:
            pid = ev.get("pid")
            is_virtual = virtual_pid is not None and pid == virtual_pid
            out = dict(ev)
            if "args" in ev:
                out["args"] = dict(ev["args"])
            if is_virtual:
                out["pid"] = merged_virtual_pid
                tid = ev.get("tid")
                if tid is not None:
                    if tid not in virtual_tid_map:
                        virtual_tid_map[tid] = next_virtual_tid
                        next_virtual_tid += 1
                    out["tid"] = virtual_tid_map[tid]
            else:
                if pid not in pid_map:
                    pid_map[pid] = next_pid
                    next_pid += 1
                out["pid"] = pid_map[pid]
            if ev.get("ph") == "M":
                if is_virtual and ev.get("name") == "process_name":
                    continue  # the shared axis is already declared once
                if ev.get("name") == "process_name":
                    out["args"]["name"] = f"{label}/{ev['args']['name']}"
                elif is_virtual and ev.get("name") == "thread_name":
                    out["args"]["name"] = f"{label}/{ev['args']['name']}"
                merged.append(out)
                continue
            if trace_id is not None and (
                ev.get("args", {}).get("trace_id") != trace_id
            ):
                continue
            if not is_virtual:
                out["ts"] = int(round(ev["ts"] + offset))
            merged.append(out)
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="merge per-node Chrome traces into one timeline"
    )
    parser.add_argument("traces", nargs="+", help="per-node chrome_trace JSON files")
    parser.add_argument("-o", "--output", default=None,
                        help="output path (default: stdout)")
    parser.add_argument("--trace-id", type=int, default=None,
                        help="keep only spans of this distributed trace")
    args = parser.parse_args(argv)
    loaded: List[dict] = []
    labels: List[str] = []
    for path in args.traces:
        with open(path) as fh:
            loaded.append(json.load(fh))
        stem = path.rsplit("/", 1)[-1]
        labels.append(stem[:-5] if stem.endswith(".json") else stem)
    merged = merge_traces(loaded, labels=labels, trace_id=args.trace_id)
    text = json.dumps(merged)
    if args.output is None:
        sys.stdout.write(text + "\n")
    else:
        with open(args.output, "w") as fh:
            fh.write(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
