#!/usr/bin/env python
"""forensics: merge evidence bundles into a causal timeline and judge it.

Two subcommands over the forensics plane's artifacts (the bundle files
``Cluster.capture_bundle()`` / ``agent --bundle-out`` write):

``report`` -- merge one or more bundles into a single HLC-ordered cluster
timeline, run the anomaly-signature detectors over it
(SIGNATURE_CATALOG: view divergence, stuck handoff, deposed-leader
writes, alert-storm -> burn chains), and render the operator report.
``--json`` emits the machine form instead; ``--trace-out`` additionally
writes a Chrome-trace (chrome://tracing / Perfetto) file with every
journal event as an instant on the HLC axis, one track per node. Exit 3
when any signature is detected, 0 on a clean timeline -- the CI-shaped
contract, matching perfscope.

``verify`` -- recompute a bundle's manifest fingerprint (rc 3 on
mismatch), so a bundle quoted in an incident review can be authenticated.

    python tools/forensics.py report bundle.json
    python tools/forensics.py report n1.json n2.json --json --trace-out t.json
    python tools/forensics.py verify bundle.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # runnable as a script from anywhere in the tree
    sys.path.insert(0, _REPO)

from rapid_tpu.forensics.bundle import load_bundle, verify_bundle  # noqa: E402
from rapid_tpu.forensics.timeline import (  # noqa: E402
    DEFAULT_DIVERGENCE_GRACE_MS,
    DEFAULT_STORM_MIN_EVENTS,
    detect_signatures,
    merge_timeline,
    report_text,
    timeline_chrome_trace,
)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="merge forensic evidence bundles and detect anomaly "
        "signatures on the HLC-ordered cluster timeline"
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_report = sub.add_parser(
        "report", help="HLC-ordered timeline + signature verdicts "
        "(rc 3 when any signature is detected)"
    )
    p_report.add_argument("bundles", nargs="+",
                          help="evidence bundle JSON file(s)")
    p_report.add_argument("--json", action="store_true", dest="as_json",
                          help="emit the machine-readable report")
    p_report.add_argument("--trace-out", default=None,
                          help="also write a Chrome trace on the HLC axis")
    p_report.add_argument("--grace-ms", type=int,
                          default=DEFAULT_DIVERGENCE_GRACE_MS,
                          help="view-divergence propagation grace window "
                          f"(default {DEFAULT_DIVERGENCE_GRACE_MS})")
    p_report.add_argument("--storm-min", type=int,
                          default=DEFAULT_STORM_MIN_EVENTS,
                          help="alert events inside an episode that count "
                          f"as a storm (default {DEFAULT_STORM_MIN_EVENTS})")

    p_verify = sub.add_parser(
        "verify", help="recompute a bundle's manifest fingerprint"
    )
    p_verify.add_argument("bundle")

    args = parser.parse_args(argv)

    if args.cmd == "verify":
        try:
            bundle = load_bundle(args.bundle)
        except (OSError, ValueError) as exc:
            print(f"{args.bundle}: {exc}", file=sys.stderr)
            return 2
        if verify_bundle(bundle):
            print(f"{args.bundle}: fingerprint ok "
                  f"({bundle['manifest']['fingerprint'][:12]})")
            return 0
        print(f"{args.bundle}: FINGERPRINT MISMATCH", file=sys.stderr)
        return 3

    # report
    bundles = []
    for path in args.bundles:
        try:
            bundles.append(load_bundle(path))
        except (OSError, ValueError) as exc:
            print(f"{path}: {exc}", file=sys.stderr)
            return 2
    events = merge_timeline(bundles)
    if not events:
        print("no journal events in the given bundle(s)", file=sys.stderr)
        return 2
    findings = detect_signatures(
        events, grace_ms=args.grace_ms, storm_min_events=args.storm_min,
    )
    if args.trace_out:
        with open(args.trace_out, "w") as fh:
            json.dump(timeline_chrome_trace(events), fh)
        print(f"wrote Chrome trace to {args.trace_out}", file=sys.stderr)
    if args.as_json:
        print(json.dumps({
            "events": [e.to_journal_entry() for e in events],
            "findings": findings,
            "bundles": [
                {"trigger": b.get("trigger"),
                 "captured_by": b.get("captured_by"),
                 "manifest": b.get("manifest")}
                for b in bundles
            ],
        }, sort_keys=True, default=str))
    else:
        print(report_text(events, findings, bundles))
    for finding in findings:
        print(f"SIGNATURE: {finding['signature']}", file=sys.stderr)
    return 3 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
