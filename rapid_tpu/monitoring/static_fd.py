"""Deterministic failure detector for tests and simulations.

Reference: StaticFailureDetector (test fixture, StaticFailureDetector.java:25-62)
-- consults a shared mutable blacklist, so tests fail arbitrary node sets
instantly and deterministically.
"""

from __future__ import annotations

from typing import Callable, Set

from ..types import Endpoint
from .base import IEdgeFailureDetectorFactory


class StaticFailureDetector:
    def __init__(
        self, subject: Endpoint, blacklist: Set[Endpoint], notifier: Callable[[], None]
    ) -> None:
        self._subject = subject
        self._blacklist = blacklist
        self._notifier = notifier
        self._notified = False

    def __call__(self) -> None:
        if not self._notified and self._subject in self._blacklist:
            self._notified = True
            self._notifier()


class StaticFailureDetectorFactory(IEdgeFailureDetectorFactory):
    def __init__(self, blacklist: Set[Endpoint]) -> None:
        self.blacklist = blacklist  # shared & mutable on purpose

    def create_instance(
        self, subject: Endpoint, notifier: Callable[[], None]
    ) -> Callable[[], None]:
        return StaticFailureDetector(subject, self.blacklist, notifier)

    def fail_nodes(self, nodes: Set[Endpoint]) -> None:
        self.blacklist.update(nodes)
