"""The edge failure-detector plugin seam.

Reference: monitoring/IEdgeFailureDetectorFactory.java:31-33. The membership
service schedules the returned runnable once per FD interval for each of the
node's subjects (MembershipService.java:686-696); the detector invokes
``notifier`` to declare the edge to its subject faulty.
"""

from __future__ import annotations

from typing import Callable

from ..types import Endpoint


class IEdgeFailureDetectorFactory:
    def create_instance(
        self, subject: Endpoint, notifier: Callable[[], None]
    ) -> Callable[[], None]:
        """Return a runnable executed every failure_detector_interval_ms."""
        raise NotImplementedError
