"""Adaptive gray-aware failure detection.

Closes the telemetry loop PR 6 opened: the static PingPong detector already
measures per-edge RTT EWMAs (the observable that separates a gray node from a
dead one), but only acts once probes hard-fail ``failure_threshold`` times.
This layer scores each monitored edge by RTT *outlierness relative to its
topology tier* and converts sustained outliers into alerts before the hard
path fires, while per-tier controllers adapt probe intervals, failure
thresholds, and the alert-batching flush window.

Scoring (phi-accrual-flavored, over the existing EWMA + jitter variance):

* Every answered probe yields a robust z-score
  ``z = (rtt - median_tier) / max(spread_tier, min_spread_ms)`` against the
  smoothed RTTs of the observer's other edges in the same tier (median /
  median-absolute-deviation, so one gray peer cannot poison the baseline).
  With fewer than two warmed-up tier peers the edge falls back to its own
  history: ``z = (rtt - srtt) / max(4 * rtt_var, min_spread_ms)``.
* ``z > outlier_z`` sustains an *outlier streak*; a missed probe sustains a
  *miss streak* (a gray node past the probe timeout answers nothing, so
  misses against an established healthy history are the strongest signal);
  any answered probe resets the miss streak.
* ``suspicion = max(miss_streak, outlier_streak) / gray_confirm`` once
  ``warmup_probes`` samples exist, else 0.0 -- a fresh edge (or a node that
  was dead on arrival) can never be gray-suspected; it takes the static
  hard-failure path unchanged.

Safety:

* A suspicion >= 1 alert rides the *existing* DOWN-alert path; the
  cut detector's H/L aggregation is untouched, so almost-everywhere
  agreement still gates eviction -- one paranoid observer cannot cut a
  healthy node.
* Clock skew cannot masquerade as outlierness: all of an observer's edges
  are measured with the same injectable probe clock, so a skewed rate
  scales numerator and tier spread together and an offset cancels in the
  subtraction (tests/test_adaptive_fd.py pins both directions).

Controllers (all outputs clamped to the AdaptiveFdSettings floors/ceilings):

* probe interval: RTT-proportional, ``max(floor, 8 * median_tier_rtt)`` --
  LAN tiers probe faster than the static default, WAN tiers slower (fewer
  false positives); any suspect edge drags its tier to the floor.
* failure threshold: detection-time-budget-constant,
  ``default_threshold * default_interval / adapted_interval`` -- faster
  probing does not lower the hard path's tolerated outage time.
* alert flush window: drops to the floor while a gray alert is pending so
  the cut detector hears about a gray node promptly, else the static
  window clamped to [floor, ceiling].
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from ..messaging.base import IMessagingClient
from ..observability import Metrics
from ..settings import Settings
from ..types import Endpoint
from .base import IEdgeFailureDetectorFactory
from .pingpong import EdgeRegistryMixin, PingPongFailureDetector

# Edge-tier labels, widest separating boundary between observer and subject
# (matches sim/topology.py LatencyTopology semantics). "default" is used
# when no tier resolver is configured: every edge shares one peer group.
TIER_RACK = "rack"
TIER_ZONE = "zone"
TIER_REGION = "region"
TIER_WAN = "wan"
TIER_DEFAULT = "default"


def topology_tier_resolver(
    topology, self_index: int, index_of: Callable[[Endpoint], Optional[int]]
) -> Callable[[Endpoint], str]:
    """Tier resolver for a sim/topology.py LatencyTopology: maps a subject
    endpoint to the widest tier separating it from the observer at
    ``self_index``. ``index_of`` maps endpoints to topology indices (None ->
    TIER_DEFAULT, e.g. a peer outside the modeled topology)."""

    def tier_of(subject: Endpoint) -> str:
        j = index_of(subject)
        if j is None:
            return TIER_DEFAULT
        if topology.region_of(self_index) != topology.region_of(j):
            return TIER_WAN
        if topology.zone_of(self_index) != topology.zone_of(j):
            return TIER_REGION
        if topology.rack_of(self_index) != topology.rack_of(j):
            return TIER_ZONE
        return TIER_RACK

    return tier_of


def _median(values) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return float(ordered[mid])
    return (ordered[mid - 1] + ordered[mid]) / 2.0


class AdaptivePingPongFailureDetector(PingPongFailureDetector):
    """PingPong detector with tier-relative gray suspicion on top of the
    unchanged cumulative hard-failure path."""

    def __init__(
        self,
        address: Endpoint,
        subject: Endpoint,
        client: IMessagingClient,
        notifier: Callable[[], None],
        factory: "AdaptivePingPongFactory",
        failure_threshold: int,
        metrics: Optional[Metrics] = None,
        clock: Optional[Callable[[], int]] = None,
    ) -> None:
        super().__init__(
            address, subject, client, notifier,
            failure_threshold=failure_threshold, metrics=metrics, clock=clock,
        )
        self._factory = factory
        self._adaptive = factory.settings.adaptive_fd
        self._miss_streak = 0
        self._outlier_streak = 0

    # -- scoring ----------------------------------------------------------

    def _warmed_up(self) -> bool:
        return self._sample_count >= self._adaptive.warmup_probes

    def _record_sample(self, rtt: float) -> None:
        self._miss_streak = 0
        if not self._warmed_up():
            self._metrics.observe("fd.suspicion", 0.0)
            return
        z = self._z_score(rtt)
        if z is not None and z > self._adaptive.outlier_z:
            self._outlier_streak += 1
        else:
            self._outlier_streak = 0
        self._metrics.observe("fd.suspicion", self.suspicion())

    def _record_failure(self) -> None:
        super()._record_failure()
        if self._warmed_up():
            self._miss_streak += 1
            self._metrics.observe("fd.suspicion", self.suspicion())

    def _z_score(self, rtt: float) -> Optional[float]:
        floor = self._adaptive.min_spread_ms
        stats = self._factory.tier_stats(self._subject)
        if stats is not None:
            median, spread = stats
            return (rtt - median) / max(spread, floor)
        srtt, var = self._rtt_ms, self._rtt_var_ms
        if srtt is None or var is None:
            return None
        return (rtt - srtt) / max(4.0 * var, floor)

    def suspicion(self) -> float:
        if not self._warmed_up():
            return 0.0
        streak = max(self._miss_streak, self._outlier_streak)
        return streak / self._adaptive.gray_confirm

    # -- alerting ---------------------------------------------------------

    def has_failed(self) -> bool:
        return super().has_failed() or self.suspicion() >= 1.0

    def __call__(self) -> None:
        if (
            not self._notified
            and not super().has_failed()
            and self.suspicion() >= 1.0
        ):
            # gray path fired first: the alert the base tick is about to
            # send exists only because of suspicion, not the hard counter
            self._metrics.incr("fd.gray_alerts")
        super().__call__()


class _TierController:
    """Derived per-tier parameters; pure function of the tier's current
    peer statistics and the static defaults (recomputed on demand)."""

    __slots__ = ("tier", "interval_ms", "threshold", "flush_ms")

    def __init__(self, tier: str, interval_ms: int, threshold: int,
                 flush_ms: int) -> None:
        self.tier = tier
        self.interval_ms = interval_ms
        self.threshold = threshold
        self.flush_ms = flush_ms


class AdaptivePingPongFactory(EdgeRegistryMixin, IEdgeFailureDetectorFactory):
    """Creates AdaptivePingPongFailureDetectors and serves the adapted
    per-tier parameters the service consults (probe interval per subject,
    alert flush window, statusz digests). RTT history carries across
    configuration changes for still-monitored subjects so warmup does not
    restart on every view change."""

    def __init__(
        self,
        address: Endpoint,
        client: IMessagingClient,
        settings: Settings,
        metrics: Optional[Metrics] = None,
        clock: Optional[Callable[[], int]] = None,
        tier_of: Optional[Callable[[Endpoint], str]] = None,
    ) -> None:
        self._address = address
        self._client = client
        self.settings = settings
        self._metrics = metrics
        self._clock = clock
        self._tier_of = tier_of if tier_of is not None else (
            lambda _subject: TIER_DEFAULT
        )
        self._edges: Dict[Endpoint, AdaptivePingPongFailureDetector] = {}

    # -- detector creation ------------------------------------------------

    def create_instance(
        self, subject: Endpoint, notifier: Callable[[], None]
    ) -> Callable[[], None]:
        detector = AdaptivePingPongFailureDetector(
            self._address, subject, self._client, notifier,
            factory=self,
            failure_threshold=self.adapted_threshold(subject),
            metrics=self._metrics, clock=self._clock,
        )
        previous = self._edges.get(subject)
        if previous is not None:
            # carry the RTT history (not the failure/streak state) so a
            # subject monitored across view changes keeps its warmup
            detector._rtt_ms = previous._rtt_ms
            detector._rtt_var_ms = previous._rtt_var_ms
            detector._seed_window = list(previous._seed_window)
            detector._sample_count = previous._sample_count
        self._register_edge(subject, detector)
        return detector

    # -- tier statistics --------------------------------------------------

    def tier_of(self, subject: Endpoint) -> str:
        return self._tier_of(subject)

    def tier_stats(self, subject: Endpoint) -> Optional[Tuple[float, float]]:
        """(median, spread) of the smoothed RTTs of the observer's *other*
        warmed-up edges in ``subject``'s tier; None below two peers."""
        tier = self._tier_of(subject)
        srtts = [
            det.rtt_ms()
            for peer, det in self._edges.items()
            if peer != subject
            and self._tier_of(peer) == tier
            and det.rtt_ms() is not None
            and det.sample_count() >= self.settings.adaptive_fd.warmup_probes
        ]
        if len(srtts) < 2:
            return None
        median = _median(srtts)
        spread = _median([abs(x - median) for x in srtts])
        return median, spread

    def _tier_median(self, tier: str) -> Optional[float]:
        srtts = [
            det.rtt_ms()
            for peer, det in self._edges.items()
            if self._tier_of(peer) == tier
            and det.rtt_ms() is not None
            and det.sample_count() >= self.settings.adaptive_fd.warmup_probes
        ]
        return _median(srtts) if len(srtts) >= 2 else None

    def _tier_suspect(self, tier: str) -> bool:
        return any(
            det.suspicion() > 0.0
            for peer, det in self._edges.items()
            if self._tier_of(peer) == tier
        )

    # -- controllers ------------------------------------------------------

    def interval_ms_for(self, subject: Endpoint,
                        default_ms: Optional[int] = None) -> int:
        """Adapted probe interval for ``subject``: RTT-proportional per
        tier, floored while the tier holds a suspect edge."""
        st = self.settings.adaptive_fd
        if default_ms is None:
            default_ms = self.settings.failure_detector_interval_ms
        tier = self._tier_of(subject)
        if self._tier_suspect(tier):
            out = st.interval_floor_ms
        else:
            median = self._tier_median(tier)
            out = default_ms if median is None else int(
                max(st.interval_floor_ms, 8.0 * median)
            )
        out = max(st.interval_floor_ms, min(st.interval_ceiling_ms, out))
        if self._metrics is not None:
            self._metrics.observe("fd.adapted_interval_ms", out)
        return out

    def adapted_threshold(self, subject: Endpoint) -> int:
        """Hard-failure threshold keeping the detection time budget
        (threshold x interval) at the static product, clamped."""
        st = self.settings.adaptive_fd
        default_threshold = self.settings.fd_failure_threshold
        default_interval = self.settings.failure_detector_interval_ms
        interval = self._interval_no_metrics(subject, default_interval)
        budget = default_threshold * default_interval
        threshold = int(round(budget / max(interval, 1)))
        return max(st.threshold_floor, min(st.threshold_ceiling, threshold))

    def _interval_no_metrics(self, subject: Endpoint, default_ms: int) -> int:
        st = self.settings.adaptive_fd
        tier = self._tier_of(subject)
        if self._tier_suspect(tier):
            out = st.interval_floor_ms
        else:
            median = self._tier_median(tier)
            out = default_ms if median is None else int(
                max(st.interval_floor_ms, 8.0 * median)
            )
        return max(st.interval_floor_ms, min(st.interval_ceiling_ms, out))

    def flush_window_ms(self, default_ms: Optional[int] = None) -> int:
        """Adapted alert-batching flush window: the floor while any edge
        holds a ripe gray suspicion (deliver the alert promptly), else the
        static window clamped to the adaptive band."""
        st = self.settings.adaptive_fd
        if default_ms is None:
            default_ms = self.settings.batching_window_ms
        if any(det.suspicion() >= 1.0 for det in self._edges.values()):
            return st.flush_floor_ms
        return max(st.flush_floor_ms, min(st.flush_ceiling_ms, default_ms))

    # -- observability ----------------------------------------------------

    def edge_digest(self):
        rows = [
            (str(subject), det.rtt_ms(), det.suspicion())
            for subject, det in self._edges.items()
        ]
        rows.sort(key=lambda r: (-r[2], -(r[1] or 0.0), r[0]))
        return tuple(rows)

    def tier_params(self) -> Tuple[Tuple[str, int, int, int], ...]:
        """((tier, interval_ms, threshold, flush_ms), ...) for every tier
        with a monitored edge, sorted by tier name."""
        by_tier: Dict[str, Endpoint] = {}
        for subject in self._edges:
            by_tier.setdefault(self._tier_of(subject), subject)
        flush = self.flush_window_ms()
        return tuple(
            (
                tier,
                self._interval_no_metrics(
                    subject, self.settings.failure_detector_interval_ms
                ),
                self.adapted_threshold(subject),
                flush,
            )
            for tier, subject in sorted(by_tier.items())
        )
