"""Default ping-pong edge failure detector.

Reference: PingPongFailureDetector.java. Per tick: if the *cumulative* failed
probe count has reached FAILURE_THRESHOLD=10, notify once; otherwise send a
best-effort probe. A success does NOT reset the counter (the reference's
handleProbeOnSuccess only logs, :116-118) -- preserved for parity; see
WindowedPingPongFailureDetector for the paper's "40% of last 10" policy.
A subject answering BOOTSTRAPPING is tolerated BOOTSTRAP_COUNT_THRESHOLD=30
times before counting as failure (:44,100-106).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Deque, Optional

from ..messaging.base import IMessagingClient
from ..observability import Metrics, global_metrics
from ..runtime.futures import Promise
from ..types import Endpoint, NodeStatus, ProbeMessage, ProbeResponse
from .base import IEdgeFailureDetectorFactory

FAILURE_THRESHOLD = 10
BOOTSTRAP_COUNT_THRESHOLD = 30


class PingPongFailureDetector:
    def __init__(
        self,
        address: Endpoint,
        subject: Endpoint,
        client: IMessagingClient,
        notifier: Callable[[], None],
        failure_threshold: int = FAILURE_THRESHOLD,
        metrics: Optional[Metrics] = None,
    ) -> None:
        self._address = address
        self._subject = subject
        self._client = client
        self._notifier = notifier
        self._failure_threshold = failure_threshold
        self._metrics = metrics if metrics is not None else global_metrics()
        self._failure_count = 0
        self._bootstrap_response_count = 0
        self._notified = False
        self._probe = ProbeMessage(sender=address)

    def has_failed(self) -> bool:
        return self._failure_count >= self._failure_threshold

    def __call__(self) -> None:
        if self.has_failed() and not self._notified:
            self._notified = True
            self._notifier()
        else:
            self._metrics.incr("fd.probes")
            self._client.send_message_best_effort(
                self._subject, self._probe
            ).add_callback(self._on_probe_done)

    def _record_failure(self) -> None:
        self._failure_count += 1
        self._metrics.incr("fd.probe_failures")

    def _on_probe_done(self, promise: Promise) -> None:
        if promise.exception() is not None:
            self._record_failure()
            return
        response = promise.peek()
        if not isinstance(response, ProbeResponse):
            self._record_failure()
            return
        if response.status == NodeStatus.BOOTSTRAPPING:
            self._bootstrap_response_count += 1
            if self._bootstrap_response_count > BOOTSTRAP_COUNT_THRESHOLD:
                self._record_failure()


class PingPongFailureDetectorFactory(IEdgeFailureDetectorFactory):
    def __init__(self, address: Endpoint, client: IMessagingClient,
                 failure_threshold: int = FAILURE_THRESHOLD,
                 metrics: Optional[Metrics] = None) -> None:
        self._address = address
        self._client = client
        self._failure_threshold = failure_threshold
        self._metrics = metrics

    def create_instance(
        self, subject: Endpoint, notifier: Callable[[], None]
    ) -> Callable[[], None]:
        return PingPongFailureDetector(
            self._address, subject, self._client, notifier,
            self._failure_threshold, metrics=self._metrics,
        )


class WindowedPingPongFailureDetector(PingPongFailureDetector):
    """The paper's policy (atc-2018 §6): mark the edge faulty when >= 40% of
    the last ``window`` probes failed. Offered as an option; the reference
    code's cumulative counter remains the parity default."""

    def __init__(self, address, subject, client, notifier,
                 window: int = 10, threshold: float = 0.4,
                 metrics: Optional[Metrics] = None) -> None:
        super().__init__(address, subject, client, notifier, metrics=metrics)
        self._window: Deque[bool] = deque(maxlen=window)
        self._threshold = threshold

    def has_failed(self) -> bool:
        window = self._window
        if len(window) < window.maxlen:  # type: ignore[arg-type]
            return False
        return sum(window) >= self._threshold * window.maxlen  # type: ignore[operator]

    def _on_probe_done(self, promise: Promise) -> None:
        # only genuine failures enter the window: BOOTSTRAPPING replies within
        # the 30-reply tolerance are not failures (they increment
        # failure_count only past the tolerance, matching the cumulative
        # policy), else the windowed policy would flap on joining subjects
        before = self._failure_count
        super()._on_probe_done(promise)
        self._window.append(self._failure_count > before)


class WindowedPingPongFailureDetectorFactory(IEdgeFailureDetectorFactory):
    def __init__(self, address: Endpoint, client: IMessagingClient,
                 window: int = 10, threshold: float = 0.4,
                 metrics: Optional[Metrics] = None) -> None:
        self._address = address
        self._client = client
        self._window = window
        self._threshold = threshold
        self._metrics = metrics

    def create_instance(self, subject, notifier):
        return WindowedPingPongFailureDetector(
            self._address, subject, self._client, notifier,
            self._window, self._threshold, metrics=self._metrics,
        )
