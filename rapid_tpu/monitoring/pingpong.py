"""Default ping-pong edge failure detector.

Reference: PingPongFailureDetector.java. Per tick: if the *cumulative* failed
probe count has reached FAILURE_THRESHOLD=10, notify once; otherwise send a
best-effort probe. A success does NOT reset the counter (the reference's
handleProbeOnSuccess only logs, :116-118) -- preserved for parity; see
WindowedPingPongFailureDetector for the paper's "40% of last 10" policy.
A subject answering BOOTSTRAPPING is tolerated BOOTSTRAP_COUNT_THRESHOLD=30
times before counting as failure (:44,100-106).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, Optional

from ..messaging.base import IMessagingClient
from ..observability import Metrics, global_metrics
from ..runtime.futures import Promise
from ..types import Endpoint, NodeStatus, ProbeMessage, ProbeResponse
from .base import IEdgeFailureDetectorFactory

FAILURE_THRESHOLD = 10
BOOTSTRAP_COUNT_THRESHOLD = 30

# EWMA smoothing for the per-edge RTT estimate (TCP SRTT's classic alpha)
_RTT_ALPHA = 0.125


def _wall_ms() -> int:
    return int(time.monotonic() * 1000)


class PingPongFailureDetector:
    def __init__(
        self,
        address: Endpoint,
        subject: Endpoint,
        client: IMessagingClient,
        notifier: Callable[[], None],
        failure_threshold: int = FAILURE_THRESHOLD,
        metrics: Optional[Metrics] = None,
        clock: Optional[Callable[[], int]] = None,
    ) -> None:
        self._address = address
        self._subject = subject
        self._client = client
        self._notifier = notifier
        self._failure_threshold = failure_threshold
        self._metrics = metrics if metrics is not None else global_metrics()
        # ``clock``: ms source for RTT measurement -- the node's scheduler
        # clock when available (virtual-time determinism; also the seam a
        # ClockSkewRule drifts), else the wall clock
        self._clock = clock if clock is not None else _wall_ms
        self._failure_count = 0
        self._bootstrap_response_count = 0
        self._notified = False
        self._probe = ProbeMessage(sender=address)
        self._rtt_ms: Optional[float] = None  # per-edge EWMA estimate

    def has_failed(self) -> bool:
        return self._failure_count >= self._failure_threshold

    def rtt_ms(self) -> Optional[float]:
        """Smoothed probe round-trip estimate for this edge (None until the
        first answered probe). The observable that separates a gray node
        from a dead one: a SlowNodeRule victim inside the timeout shows an
        inflated estimate here long before any eviction."""
        return self._rtt_ms

    def __call__(self) -> None:
        if self.has_failed() and not self._notified:
            self._notified = True
            self._notifier()
        else:
            self._metrics.incr("fd.probes")
            sent_ms = self._clock()
            self._client.send_message_best_effort(
                self._subject, self._probe
            ).add_callback(lambda p: self._on_probe_result(p, sent_ms))

    def _on_probe_result(self, promise: Promise, sent_ms: int) -> None:
        if promise.exception() is None and isinstance(
            promise.peek(), ProbeResponse
        ):
            rtt = max(0, self._clock() - sent_ms)
            self._metrics.observe("fd.rtt_ms", rtt)
            self._rtt_ms = (
                float(rtt) if self._rtt_ms is None
                else (1 - _RTT_ALPHA) * self._rtt_ms + _RTT_ALPHA * rtt
            )
        self._on_probe_done(promise)

    def _record_failure(self) -> None:
        self._failure_count += 1
        self._metrics.incr("fd.probe_failures")

    def _on_probe_done(self, promise: Promise) -> None:
        if promise.exception() is not None:
            self._record_failure()
            return
        response = promise.peek()
        if not isinstance(response, ProbeResponse):
            self._record_failure()
            return
        if response.status == NodeStatus.BOOTSTRAPPING:
            self._bootstrap_response_count += 1
            if self._bootstrap_response_count > BOOTSTRAP_COUNT_THRESHOLD:
                self._record_failure()


class PingPongFailureDetectorFactory(IEdgeFailureDetectorFactory):
    def __init__(self, address: Endpoint, client: IMessagingClient,
                 failure_threshold: int = FAILURE_THRESHOLD,
                 metrics: Optional[Metrics] = None,
                 clock: Optional[Callable[[], int]] = None) -> None:
        self._address = address
        self._client = client
        self._failure_threshold = failure_threshold
        self._metrics = metrics
        self._clock = clock

    def create_instance(
        self, subject: Endpoint, notifier: Callable[[], None]
    ) -> Callable[[], None]:
        return PingPongFailureDetector(
            self._address, subject, self._client, notifier,
            self._failure_threshold, metrics=self._metrics,
            clock=self._clock,
        )


class WindowedPingPongFailureDetector(PingPongFailureDetector):
    """The paper's policy (atc-2018 §6): mark the edge faulty when >= 40% of
    the last ``window`` probes failed. Offered as an option; the reference
    code's cumulative counter remains the parity default."""

    def __init__(self, address, subject, client, notifier,
                 window: int = 10, threshold: float = 0.4,
                 metrics: Optional[Metrics] = None,
                 clock: Optional[Callable[[], int]] = None) -> None:
        super().__init__(address, subject, client, notifier, metrics=metrics,
                         clock=clock)
        self._window: Deque[bool] = deque(maxlen=window)
        self._threshold = threshold

    def has_failed(self) -> bool:
        window = self._window
        if len(window) < window.maxlen:  # type: ignore[arg-type]
            return False
        return sum(window) >= self._threshold * window.maxlen  # type: ignore[operator]

    def _on_probe_done(self, promise: Promise) -> None:
        # only genuine failures enter the window: BOOTSTRAPPING replies within
        # the 30-reply tolerance are not failures (they increment
        # failure_count only past the tolerance, matching the cumulative
        # policy), else the windowed policy would flap on joining subjects
        before = self._failure_count
        super()._on_probe_done(promise)
        self._window.append(self._failure_count > before)


class WindowedPingPongFailureDetectorFactory(IEdgeFailureDetectorFactory):
    def __init__(self, address: Endpoint, client: IMessagingClient,
                 window: int = 10, threshold: float = 0.4,
                 metrics: Optional[Metrics] = None,
                 clock: Optional[Callable[[], int]] = None) -> None:
        self._address = address
        self._client = client
        self._window = window
        self._threshold = threshold
        self._metrics = metrics
        self._clock = clock

    def create_instance(self, subject, notifier):
        return WindowedPingPongFailureDetector(
            self._address, subject, self._client, notifier,
            self._window, self._threshold, metrics=self._metrics,
            clock=self._clock,
        )
