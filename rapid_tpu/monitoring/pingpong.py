"""Default ping-pong edge failure detector.

Reference: PingPongFailureDetector.java. Per tick: if the *cumulative* failed
probe count has reached FAILURE_THRESHOLD=10, notify once; otherwise send a
best-effort probe. A success does NOT reset the counter (the reference's
handleProbeOnSuccess only logs, :116-118) -- preserved for parity; see
WindowedPingPongFailureDetector for the paper's "40% of last 10" policy.
A subject answering BOOTSTRAPPING is tolerated BOOTSTRAP_COUNT_THRESHOLD=30
times before counting as failure (:44,100-106).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, Optional

from ..messaging.base import IMessagingClient
from ..observability import Metrics, global_metrics
from ..runtime.futures import Promise
from ..types import Endpoint, NodeStatus, ProbeMessage, ProbeResponse
from .base import IEdgeFailureDetectorFactory

FAILURE_THRESHOLD = 10
BOOTSTRAP_COUNT_THRESHOLD = 30

# EWMA smoothing for the per-edge RTT estimate (TCP SRTT's classic alpha)
_RTT_ALPHA = 0.125
# EWMA smoothing for the RTT deviation estimate (TCP RTTVAR's classic beta)
_RTT_BETA = 0.25
# The deviation estimate is seeded from the spread of the first
# RTT_SEED_SAMPLES samples rather than TCP's single-sample R/2 point
# estimate: one slow first probe on a fresh WAN edge would otherwise pin an
# inflated variance (or, worse, a tiny one that flags normal jitter as
# outlier) for many EWMA half-lives. Until the seed window fills,
# rtt_var_ms() is None and suspicion scoring stays inactive.
RTT_SEED_SAMPLES = 4


def _wall_ms() -> int:
    return int(time.monotonic() * 1000)


class PingPongFailureDetector:
    def __init__(
        self,
        address: Endpoint,
        subject: Endpoint,
        client: IMessagingClient,
        notifier: Callable[[], None],
        failure_threshold: int = FAILURE_THRESHOLD,
        metrics: Optional[Metrics] = None,
        clock: Optional[Callable[[], int]] = None,
    ) -> None:
        self._address = address
        self._subject = subject
        self._client = client
        self._notifier = notifier
        self._failure_threshold = failure_threshold
        self._metrics = metrics if metrics is not None else global_metrics()
        # ``clock``: ms source for RTT measurement -- the node's scheduler
        # clock when available (virtual-time determinism; also the seam a
        # ClockSkewRule drifts), else the wall clock
        self._clock = clock if clock is not None else _wall_ms
        self._failure_count = 0
        self._bootstrap_response_count = 0
        self._notified = False
        self._probe = ProbeMessage(sender=address)
        self._rtt_ms: Optional[float] = None  # per-edge EWMA estimate
        self._rtt_var_ms: Optional[float] = None  # EWMA |deviation| estimate
        self._seed_window: list = []  # first RTT_SEED_SAMPLES raw samples
        self._sample_count = 0

    def has_failed(self) -> bool:
        return self._failure_count >= self._failure_threshold

    def rtt_ms(self) -> Optional[float]:
        """Smoothed probe round-trip estimate for this edge (None until the
        first answered probe). The observable that separates a gray node
        from a dead one: a SlowNodeRule victim inside the timeout shows an
        inflated estimate here long before any eviction."""
        return self._rtt_ms

    def rtt_var_ms(self) -> Optional[float]:
        """Smoothed mean-absolute-deviation of the probe RTT, None until
        RTT_SEED_SAMPLES answered probes seeded it (cold-start guard)."""
        return self._rtt_var_ms

    def sample_count(self) -> int:
        """Answered probes observed on this edge (RTT samples)."""
        return self._sample_count

    def suspicion(self) -> float:
        """Gray-failure suspicion score in [0, inf): 0 means healthy, >= 1
        means the edge warrants an alert. The static detector never
        suspects (alerts only via the hard failure_threshold); the adaptive
        subclass overrides this with the tier-relative outlier score."""
        return 0.0

    def __call__(self) -> None:
        if self.has_failed() and not self._notified:
            self._notified = True
            self._notifier()
        else:
            self._metrics.incr("fd.probes")
            sent_ms = self._clock()
            self._client.send_message_best_effort(
                self._subject, self._probe
            ).add_callback(lambda p: self._on_probe_result(p, sent_ms))

    def _on_probe_result(self, promise: Promise, sent_ms: int) -> None:
        if promise.exception() is None and isinstance(
            promise.peek(), ProbeResponse
        ):
            rtt = max(0, self._clock() - sent_ms)
            self._metrics.observe("fd.rtt_ms", rtt)
            srtt_before = self._rtt_ms
            self._rtt_ms = (
                float(rtt) if self._rtt_ms is None
                else (1 - _RTT_ALPHA) * self._rtt_ms + _RTT_ALPHA * rtt
            )
            self._update_variance(float(rtt), srtt_before)
            self._sample_count += 1
            self._record_sample(float(rtt))
        self._on_probe_done(promise)

    def _update_variance(self, rtt: float, srtt_before: Optional[float]) -> None:
        if self._rtt_var_ms is None:
            self._seed_window.append(rtt)
            if len(self._seed_window) >= RTT_SEED_SAMPLES:
                mean = sum(self._seed_window) / len(self._seed_window)
                self._rtt_var_ms = sum(
                    abs(x - mean) for x in self._seed_window
                ) / len(self._seed_window)
                self._seed_window = []
            return
        deviation = abs(rtt - (srtt_before if srtt_before is not None else rtt))
        self._rtt_var_ms = (
            (1 - _RTT_BETA) * self._rtt_var_ms + _RTT_BETA * deviation
        )

    def _record_sample(self, rtt: float) -> None:
        """Per-answered-probe hook for subclasses (adaptive scoring)."""

    def _record_failure(self) -> None:
        self._failure_count += 1
        self._metrics.incr("fd.probe_failures")

    def _on_probe_done(self, promise: Promise) -> None:
        if promise.exception() is not None:
            self._record_failure()
            return
        response = promise.peek()
        if not isinstance(response, ProbeResponse):
            self._record_failure()
            return
        if response.status == NodeStatus.BOOTSTRAPPING:
            self._bootstrap_response_count += 1
            if self._bootstrap_response_count > BOOTSTRAP_COUNT_THRESHOLD:
                self._record_failure()


class EdgeRegistryMixin:
    """Tracks the live detector per monitored subject so the service can
    expose per-edge RTT EWMAs and suspicion scores through cluster_status
    (and statusz can render a worst-edges digest)."""

    _edges: dict

    def _register_edge(self, subject: Endpoint, detector) -> None:
        if not hasattr(self, "_edges"):
            self._edges = {}
        self._edges[subject] = detector

    def begin_configuration(self, subjects) -> None:
        """Drop edges no longer monitored (called by the service before it
        recreates detectors for a new configuration)."""
        keep = set(subjects)
        edges = getattr(self, "_edges", {})
        for gone in [s for s in edges if s not in keep]:
            del edges[gone]

    def edge_digest(self):
        """((subject_str, rtt_ms|None, suspicion), ...) sorted worst-first:
        by suspicion desc, then smoothed RTT desc, then subject."""
        edges = getattr(self, "_edges", {})
        rows = [
            (str(subject), det.rtt_ms(), det.suspicion())
            for subject, det in edges.items()
        ]
        rows.sort(key=lambda r: (-r[2], -(r[1] or 0.0), r[0]))
        return tuple(rows)


class PingPongFailureDetectorFactory(EdgeRegistryMixin,
                                     IEdgeFailureDetectorFactory):
    def __init__(self, address: Endpoint, client: IMessagingClient,
                 failure_threshold: int = FAILURE_THRESHOLD,
                 metrics: Optional[Metrics] = None,
                 clock: Optional[Callable[[], int]] = None) -> None:
        self._address = address
        self._client = client
        self._failure_threshold = failure_threshold
        self._metrics = metrics
        self._clock = clock
        self._edges = {}

    def create_instance(
        self, subject: Endpoint, notifier: Callable[[], None]
    ) -> Callable[[], None]:
        detector = PingPongFailureDetector(
            self._address, subject, self._client, notifier,
            self._failure_threshold, metrics=self._metrics,
            clock=self._clock,
        )
        self._register_edge(subject, detector)
        return detector


class WindowedPingPongFailureDetector(PingPongFailureDetector):
    """The paper's policy (atc-2018 §6): mark the edge faulty when >= 40% of
    the last ``window`` probes failed. Offered as an option; the reference
    code's cumulative counter remains the parity default."""

    def __init__(self, address, subject, client, notifier,
                 window: int = 10, threshold: float = 0.4,
                 metrics: Optional[Metrics] = None,
                 clock: Optional[Callable[[], int]] = None) -> None:
        super().__init__(address, subject, client, notifier, metrics=metrics,
                         clock=clock)
        self._window: Deque[bool] = deque(maxlen=window)
        self._threshold = threshold

    def has_failed(self) -> bool:
        window = self._window
        if len(window) < window.maxlen:  # type: ignore[arg-type]
            return False
        return sum(window) >= self._threshold * window.maxlen  # type: ignore[operator]

    def _on_probe_done(self, promise: Promise) -> None:
        # only genuine failures enter the window: BOOTSTRAPPING replies within
        # the 30-reply tolerance are not failures (they increment
        # failure_count only past the tolerance, matching the cumulative
        # policy), else the windowed policy would flap on joining subjects
        before = self._failure_count
        super()._on_probe_done(promise)
        self._window.append(self._failure_count > before)


class WindowedPingPongFailureDetectorFactory(EdgeRegistryMixin,
                                             IEdgeFailureDetectorFactory):
    def __init__(self, address: Endpoint, client: IMessagingClient,
                 window: int = 10, threshold: float = 0.4,
                 metrics: Optional[Metrics] = None,
                 clock: Optional[Callable[[], int]] = None) -> None:
        self._address = address
        self._client = client
        self._window = window
        self._threshold = threshold
        self._metrics = metrics
        self._clock = clock
        self._edges = {}

    def create_instance(self, subject, notifier):
        detector = WindowedPingPongFailureDetector(
            self._address, subject, self._client, notifier,
            self._window, self._threshold, metrics=self._metrics,
            clock=self._clock,
        )
        self._register_edge(subject, detector)
        return detector
