"""Core wire/data types for the rapid-tpu membership protocol.

These are the Python equivalents of the reference protobuf schema
(/root/reference/rapid/src/main/proto/rapid.proto:13-206). In-process we pass
immutable dataclasses directly; the byte-level wire codec lives in
rapid_tpu.messaging.codec. There is no RapidRequest/RapidResponse envelope
class -- Python dispatch is by message type (the reference needs the `oneof`
envelope only because of protobuf/gRPC, rapid.proto:21-45).
"""

from __future__ import annotations

import enum
import uuid as _uuid
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


class EdgeStatus(enum.IntEnum):
    """rapid.proto:96-99 (EdgeStatus UP/DOWN)."""

    UP = 0
    DOWN = 1


class JoinStatusCode(enum.IntEnum):
    """rapid.proto:64-72."""

    HOSTNAME_ALREADY_IN_RING = 0
    UUID_ALREADY_IN_RING = 1
    SAFE_TO_JOIN = 2
    CONFIG_CHANGED = 3
    MEMBERSHIP_REJECTED = 4


class NodeStatus(enum.IntEnum):
    """rapid.proto:197-200 (probe responses)."""

    OK = 0
    BOOTSTRAPPING = 1


@dataclass(frozen=True, order=True)
class Endpoint:
    """A process address: rapid.proto:13-17 (Endpoint{bytes hostname, int32 port})."""

    hostname: bytes
    port: int

    def __str__(self) -> str:
        return f"{self.hostname.decode('utf-8', 'replace')}:{self.port}"

    @staticmethod
    def from_parts(hostname: str, port: int) -> "Endpoint":
        if not 0 <= port <= 65535:
            raise ValueError(f"invalid port: {port}")
        return Endpoint(hostname.encode("utf-8"), port)

    @staticmethod
    def from_string(host_string: str) -> "Endpoint":
        """Parse 'host:port' (Utils.hostFromString, Utils.java:64-69)."""
        host, sep, port = host_string.rpartition(":")
        if not sep or not host:
            raise ValueError(f"invalid host:port string: {host_string!r}")
        return Endpoint.from_parts(host, int(port))


@dataclass(frozen=True, order=True)
class NodeId:
    """128-bit logical node identifier; rapid.proto:50-54 (NodeId{int64 high, low}).

    Ordering matches the reference NodeIdComparator (MembershipView.java:465-491):
    signed compare on `high`, then `low` -- both stored as Java-style signed 64-bit.
    """

    high: int
    low: int

    @staticmethod
    def from_uuid(u: _uuid.UUID) -> "NodeId":
        def _signed(x: int) -> int:
            return x - (1 << 64) if x >= (1 << 63) else x

        return NodeId(_signed(u.int >> 64), _signed(u.int & ((1 << 64) - 1)))

    @staticmethod
    def random(rng=None) -> "NodeId":
        if rng is None:
            return NodeId.from_uuid(_uuid.uuid4())
        return NodeId.from_uuid(_uuid.UUID(int=rng.getrandbits(128), version=4))


# Application metadata tags: rapid.proto:56-58. Keys are strings, values bytes.
Metadata = Dict[str, bytes]


def freeze_metadata(metadata: Optional[Metadata]) -> Tuple[Tuple[str, bytes], ...]:
    if not metadata:
        return ()
    return tuple(sorted(metadata.items()))


# ---------------------------------------------------------------------------
# Protocol messages (rapid.proto:60-206)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PreJoinMessage:
    """Join protocol phase 1, joiner -> seed (rapid.proto:60-63)."""

    sender: Endpoint
    node_id: NodeId


@dataclass(frozen=True)
class JoinMessage:
    """Join protocol phase 2, joiner -> observer (rapid.proto:85-92)."""

    sender: Endpoint
    node_id: NodeId
    ring_numbers: Tuple[int, ...]
    configuration_id: int
    metadata: Tuple[Tuple[str, bytes], ...] = ()


@dataclass(frozen=True)
class JoinResponse:
    """Response for both join phases (rapid.proto:74-83)."""

    sender: Endpoint
    status_code: JoinStatusCode
    configuration_id: int
    endpoints: Tuple[Endpoint, ...] = ()
    identifiers: Tuple[NodeId, ...] = ()
    metadata: Tuple[Tuple[Endpoint, Tuple[Tuple[str, bytes], ...]], ...] = ()


@dataclass(frozen=True)
class AlertMessage:
    """An edge-status report by an observer (rapid.proto:101-110)."""

    edge_src: Endpoint
    edge_dst: Endpoint
    edge_status: EdgeStatus
    configuration_id: int
    ring_numbers: Tuple[int, ...]
    node_id: Optional[NodeId] = None  # set for UP alerts about joiners
    metadata: Tuple[Tuple[str, bytes], ...] = ()


@dataclass(frozen=True)
class BatchedAlertMessage:
    """Batched alerts flushed by the AlertBatcher (rapid.proto:112-115)."""

    sender: Endpoint
    messages: Tuple[AlertMessage, ...]


@dataclass(frozen=True)
class ProbeMessage:
    """Edge failure-detector probe (rapid.proto:186-190)."""

    sender: Endpoint


@dataclass(frozen=True)
class ProbeResponse:
    """rapid.proto:202-205."""

    status: NodeStatus = NodeStatus.OK


@dataclass(frozen=True, order=True)
class Rank:
    """Paxos rank = (round, nodeIndex); rapid.proto:133-137.

    Total order: round first, then node index (Paxos.compareRanks,
    Paxos.java:331-337) -- dataclass order matches.
    """

    round: int
    node_index: int


@dataclass(frozen=True)
class FastRoundPhase2bMessage:
    """Fast-round vote broadcast (rapid.proto:139-144)."""

    sender: Endpoint
    configuration_id: int
    endpoints: Tuple[Endpoint, ...]


@dataclass(frozen=True)
class Phase1aMessage:
    sender: Endpoint
    configuration_id: int
    rank: Rank


@dataclass(frozen=True)
class Phase1bMessage:
    sender: Endpoint
    configuration_id: int
    rnd: Rank
    vrnd: Rank
    vval: Tuple[Endpoint, ...]


@dataclass(frozen=True)
class Phase2aMessage:
    sender: Endpoint
    configuration_id: int
    rnd: Rank
    vval: Tuple[Endpoint, ...]


@dataclass(frozen=True)
class Phase2bMessage:
    sender: Endpoint
    configuration_id: int
    rnd: Rank
    endpoints: Tuple[Endpoint, ...]


@dataclass(frozen=True)
class LeaveMessage:
    """Graceful-leave intent (rapid.proto:182-184)."""

    sender: Endpoint


@dataclass(frozen=True)
class Response:
    """Empty acknowledgement (rapid.proto:47-48)."""


@dataclass(frozen=True)
class ConsensusResponse:
    """Empty consensus acknowledgement (rapid.proto:146-147)."""


@dataclass(frozen=True)
class FastRoundVoteBatch:
    """Transport-level fan-in of identical-value fast-round votes: one frame
    standing for one ``FastRoundPhase2bMessage`` per listed sender, all
    carrying the same ``(configuration_id, endpoints)`` value. Pure
    compression -- the receiver tallies each (sender, value) exactly as it
    would the individual message, with the same per-sender dedup -- so a
    swarm's quorum of votes (~3N/4 messages at protocol level) crosses the
    wire in O(1) frames instead of thousands. Native-codec transports only
    (rapid.proto has no such message)."""

    senders: Tuple["Endpoint", ...]
    configuration_id: int
    endpoints: Tuple["Endpoint", ...]


@dataclass(frozen=True)
class MessageBatch:
    """Transport-level batch envelope: one frame carrying several otherwise
    independent requests to the same peer, flushed by a broadcaster's
    coalescing window (messaging/unicast.py / messaging/gossip.py with
    ``Settings.broadcast_flush_window_ms > 0``). Unlike FastRoundVoteBatch
    (identical-value votes only) the inner messages are heterogeneous: a
    churn wave's alerts, votes, and gossip ride one frame per peer. The
    receiver dispatches each inner message exactly as if it had arrived
    alone (one protocol task for the whole batch) and acks the envelope;
    inner responses are dropped -- batched sends are fire-and-forget
    broadcasts. Carried by both the native codec (tag 25) and the gRPC
    transport (oneof field 17); peers that never batch interop unchanged."""

    sender: "Endpoint"
    messages: Tuple[object, ...] = ()  # inner RapidMessage requests


@dataclass(frozen=True)
class GossipEnvelope:
    """Epidemic-relay wrapper around any protocol message.

    The gossip dissemination alternative the reference's broadcaster seam
    explicitly anticipates but never implements (IBroadcaster.java:24-26).
    ``gossip_id`` dedups relays cluster-wide; ``ttl`` bounds propagation
    depth. Carried by the native codec transports (tcp / in-process /
    native-tcp); the JVM-wire-compatible gRPC transport cannot carry it
    (rapid.proto has no such message).

    ``kind`` selects the anti-entropy sub-protocol frame (push-pull gossip
    mode, messaging/gossip.py): PAYLOAD carries the message itself; IHAVE
    advertises the id without the payload (tiny); PULL asks the advertiser
    to send the payload. Pre-push-pull frames carry no ``kind`` field and
    decode to PAYLOAD (0), so the wire stays backward compatible."""

    KIND_PAYLOAD = 0
    KIND_IHAVE = 1
    KIND_PULL = 2

    sender: "Endpoint"
    gossip_id: NodeId
    ttl: int
    payload: object = None  # any RapidMessage (None for IHAVE/PULL frames)
    kind: int = 0


@dataclass(frozen=True)
class ClusterStatusRequest:
    """Introspection RPC: ask any member for its view of the cluster.

    Not in rapid.proto's reference surface -- an extension message carried
    by every transport (the proto schema grows matching messages in
    messaging/wire_schema.py). Answered synchronously from protocol state,
    so it works while consensus is in flight and through the nemesis.

    ``include_history`` asks for up to that many metric history-ring
    snapshots in the response (0 = none, the default, which keeps the
    answer small and matches pre-profiling peers' frames)."""

    sender: Endpoint
    include_history: int = 0


@dataclass(frozen=True)
class ClusterStatusResponse:
    """One member's introspection snapshot.

    Cut-detector occupancy mirrors the K/H/L watermark machinery:
    ``reports_tracked`` = subjects with at least one report,
    ``pre_proposal_size`` = subjects past L but below H, ``proposal_size``
    = subjects past H awaiting a stable cut, ``updates_in_progress`` =
    subjects between the watermarks blocking the cut. ``metric_names`` /
    ``metric_values`` are a parallel-array counter digest (flat rendered
    names, see Metrics.snapshot); ``journal`` is the flight recorder's tail
    as JSON lines."""

    sender: Endpoint
    configuration_id: int
    membership_size: int
    reports_tracked: int = 0
    pre_proposal_size: int = 0
    proposal_size: int = 0
    updates_in_progress: int = 0
    consensus_decided: bool = False
    consensus_votes: int = 0
    metric_names: Tuple[str, ...] = ()
    metric_values: Tuple[int, ...] = ()
    journal: Tuple[str, ...] = ()
    # placement plane (0/absent when placement is not enabled): the map
    # fingerprint every member must agree on, the map geometry, and how
    # many partitions this member holds a replica of
    placement_version: int = 0
    placement_partitions: int = 0
    placement_owned: int = 0
    # handoff plane (0/absent when handoff is not enabled): session counts
    # plus a parallel (partition id, content fingerprint) digest of the local
    # partition store, so an operator tool can cross-check replicas holding
    # the same partition for byte-level divergence
    handoff_in_flight: int = 0
    handoff_completed: int = 0
    handoff_failed: int = 0
    handoff_partitions: Tuple[int, ...] = ()
    handoff_fingerprints: Tuple[int, ...] = ()
    # serving plane (0/absent when serving is not enabled): request counters
    # plus a parallel (partition id, leader "host:port") digest over the
    # partitions this member holds a replica of, so an operator tool can
    # cross-check that every replica of a partition agrees on its leader
    serving_gets: int = 0
    serving_puts: int = 0
    serving_put_acks: int = 0
    serving_partitions: Tuple[int, ...] = ()
    serving_leaders: Tuple[str, ...] = ()
    # failure-detector plane: parallel per-edge arrays (worst edge first --
    # suspicion desc, then RTT desc) and, when adaptive FD is on, parallel
    # per-tier arrays of the derived controller parameters. RTT in
    # microseconds and suspicion in thousandths because the wire schema
    # carries no float scalar.
    fd_subjects: Tuple[str, ...] = ()
    fd_rtt_micros: Tuple[int, ...] = ()
    fd_suspicion_milli: Tuple[int, ...] = ()
    fd_tiers: Tuple[str, ...] = ()
    fd_tier_interval_ms: Tuple[int, ...] = ()
    fd_tier_threshold: Tuple[int, ...] = ()
    fd_tier_flush_ms: Tuple[int, ...] = ()
    # profiling plane (empty unless profiling is enabled AND the request
    # set include_history): the node's metric history-ring tail as
    # sorted-key JSON lines (MetricsHistory.to_wire), the carriage a
    # scraper folds into a cluster-wide timeseries (profiling/scrape.py)
    history: Tuple[str, ...] = ()
    # durability plane (0/absent when durability is not enabled): live WAL
    # segment count, last snapshot version, and how many log records the
    # most recent recovery replayed -- the restart-health digest statusz
    # renders next to the handoff fingerprint cross-check
    durability_segments: int = 0
    durability_snapshot_version: int = 0
    durability_replayed: int = 0
    # SLO plane (empty unless slo is enabled): parallel per-alert arrays --
    # "slo:window" alert names, the current short-window burn rate in
    # thousandths, firing flags, and the attributed churn episode's trace
    # id (0 = unattributed) -- enough for an operator tool to render
    # "p99 burning, attributed to view-change episode <trace-id>"
    slo_names: Tuple[str, ...] = ()
    slo_burn_milli: Tuple[int, ...] = ()
    slo_firing: Tuple[int, ...] = ()
    slo_attributed_trace: Tuple[int, ...] = ()
    # forensics plane (0/absent when forensics is not enabled): journal
    # truncation accounting (entries the flight recorder dropped on
    # overflow, and the ring's capacity) plus the node's current hybrid
    # logical clock -- the coordinates evidence bundles merge timelines on
    journal_dropped: int = 0
    journal_capacity: int = 0
    hlc_physical_ms: int = 0
    hlc_logical: int = 0
    hlc_incarnation: int = 0
    # hierarchy plane (0/absent when hierarchy is not enabled; plane-on is
    # signalled by a non-empty global_cells, which always carries at least
    # the member's own cell): this member's cell, its cell-local
    # membership size, the parent (leader-set) configuration id, the
    # composed global fingerprint, and the parallel per-cell rows of the
    # composed global view -- the single-integer agreement surfaces
    # statusz cross-checks
    cell_id: int = 0
    cell_size: int = 0
    parent_configuration_id: int = 0
    global_fingerprint: int = 0
    global_cells: Tuple[int, ...] = ()
    global_epochs: Tuple[int, ...] = ()
    global_sizes: Tuple[int, ...] = ()
    global_leaders: Tuple[str, ...] = ()


@dataclass(frozen=True)
class CellDigestMessage:
    """Hierarchy plane, leader-to-leader: one cell's row of the composed
    global view, announced by that cell's rank-0 leader after every
    intra-cell view change (hierarchy/plane.py).

    ``configuration_id`` is the cell's local Rapid configuration id -- its
    epoch in the composed view, so stale/reordered digests are rejected
    deterministically. ``fingerprint`` is the fold over the cell's sorted
    member hashes (hierarchy/parent.py cell_fingerprint): two leaders
    disagreeing about who is in a cell compose differently even at equal
    sizes. ``parent_round`` is the sender's parent-round counter, the
    liveness stamp whole-cell eviction ages against. Carried by the native
    codec (tag 26) and the gRPC transport (oneof field 19); pre-hierarchy
    peers never see one (the plane is off by default)."""

    sender: Endpoint
    cell: int = 0
    configuration_id: int = 0
    membership_size: int = 0
    leader: str = ""
    fingerprint: int = 0
    parent_round: int = 0


@dataclass(frozen=True)
class GlobalViewMessage:
    """Hierarchy plane, leader-to-cell: the composed global view a leader
    fans back into its own cell after the composition moves, as parallel
    per-cell arrays (the ClusterStatusResponse digest shape).

    ``parent_configuration_id`` / ``global_fingerprint`` are the two
    single-integer agreement surfaces: the fold over the sorted leader-set
    hashes, and the fold over the per-cell row hashes
    (hierarchy/parent.py). Carried by the native codec (tag 27) and the
    gRPC transport (oneof field 20); intra-cell only, so it never crosses
    a cell boundary by construction."""

    sender: Endpoint
    parent_configuration_id: int = 0
    global_fingerprint: int = 0
    cells: Tuple[int, ...] = ()
    epochs: Tuple[int, ...] = ()
    sizes: Tuple[int, ...] = ()
    leaders: Tuple[str, ...] = ()
    fingerprints: Tuple[int, ...] = ()
    # the sending leader's monotonic parent-round counter: epochs are
    # configuration-id hashes (unordered), so receivers gate reordered
    # frames from the same leader by this stamp instead
    parent_round: int = 0


@dataclass(frozen=True)
class HandoffRequest:
    """Pull one chunk of a partition during a handoff session.

    Sent by the NEW owner (recipient) to a surviving OLD replica (source).
    Pull-based so the recipient controls pacing/backpressure and resume:
    after a transport failure it simply re-requests from the last offset it
    has not yet received -- the source keeps no per-session state. Not in
    rapid.proto's reference surface; carried as a rapid-tpu extension on
    every transport (msgpack tag 19, request oneof 12)."""

    sender: Endpoint
    session_id: int
    partition: int
    offset: int
    length: int
    map_version: int = 0


@dataclass(frozen=True)
class HandoffChunk:
    """One chunk of partition content, answering a HandoffRequest.

    ``total_size`` and ``fingerprint`` describe the FULL partition content
    at the source (signed xxh64), repeated on every chunk so the recipient
    can verify assembly regardless of which chunk arrives last and detect a
    source whose content changed mid-session. ``status`` 0 = OK, 1 = the
    source no longer holds the partition (recipient fails over). Msgpack
    tag 20, response oneof 6."""

    STATUS_OK = 0
    STATUS_NOT_FOUND = 1

    sender: Endpoint
    session_id: int
    partition: int
    offset: int
    data: bytes = b""
    total_size: int = 0
    fingerprint: int = 0
    status: int = 0


@dataclass(frozen=True)
class HandoffAck:
    """Verified-completion notice, recipient -> source (answered with the
    empty Response). Lets the source release the partition if the new map
    no longer assigns it a replica. Msgpack tag 21, request oneof 13."""

    sender: Endpoint
    session_id: int
    partition: int
    fingerprint: int = 0
    map_version: int = 0


@dataclass(frozen=True)
class Get:
    """Serving-plane read for one key, answered with a PutAck.

    Routed by the client to the partition leader (first live replica in
    placement order). ``quorum`` != 0 asks a replica to answer from its
    local store regardless of leadership -- the read-your-writes fallback
    fans a quorum Get to every replica and takes the max-version answer
    among a majority, which must intersect any acked write's quorum.
    ``map_version`` is the placement version the client routed against, so
    a stale-map request can be redirected. Not in rapid.proto's reference
    surface; a rapid-tpu extension (msgpack tag 22, request oneof 14)."""

    sender: Endpoint
    key: bytes
    quorum: int = 0
    map_version: int = 0


@dataclass(frozen=True)
class Put:
    """Serving-plane write for one key, answered with a PutAck.

    A client Put (``replicate`` == 0) goes to the partition leader, which
    assigns the key's next monotonic version, applies locally, and fans
    replication Puts (``replicate`` != 0, ``version`` set) to the other
    replicas; it acks the client once a majority of the replica row
    (itself included) has applied. Replicas apply a replicated Put only if
    its version is newer than what they hold, so duplicated or reordered
    replication is idempotent. ``request_id`` echoes back in the ack for
    client-side correlation. Msgpack tag 23, request oneof 16 (15 stays
    reserved for the traceCtx envelope field)."""

    sender: Endpoint
    key: bytes
    value: bytes = b""
    request_id: int = 0
    replicate: int = 0
    version: int = 0
    map_version: int = 0


@dataclass(frozen=True)
class PutAck:
    """The serving plane's unified reply to both Get and Put.

    ``status`` OK carries the value+version for Gets and the assigned
    version for Puts; NOT_LEADER carries a ``leader`` hint so the client
    can re-route after churn; NOT_FOUND is a miss on an OK read path;
    RETRY means the leader could not assemble a write quorum before its
    deadline (the write may or may not survive -- the client must re-issue
    with the same key to learn which). Msgpack tag 24, response oneof 7."""

    STATUS_OK = 0
    STATUS_NOT_LEADER = 1
    STATUS_NOT_FOUND = 2
    STATUS_RETRY = 3

    sender: Endpoint
    status: int = 0
    key: bytes = b""
    value: bytes = b""
    version: int = 0
    request_id: int = 0
    leader: Optional[Endpoint] = None
    map_version: int = 0


# Any protocol request/response, for type annotations.
RapidMessage = object

CONSENSUS_MESSAGE_TYPES = (
    FastRoundPhase2bMessage,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
)
