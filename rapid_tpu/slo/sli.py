"""Streaming SLI computation for the serving path.

Three primitives, all pure host-side data structures:

* :func:`histogram_quantile` -- latency percentiles off the same mergeable
  fixed-bucket histograms the serving plane already records
  (``SERVING_LATENCY_BUCKETS_MS``), Prometheus ``histogram_quantile``
  semantics: the answer is the smallest bucket upper edge covering the
  requested rank, so merged histograms from many nodes quantile exactly
  like one node's.
* :class:`SliTracker` -- fixed-width time-bucket ring of SLI aggregates
  (good/total per named predicate, offered arrivals, a latency histogram
  per bucket). Any trailing window is an exact sum of whole buckets, which
  is what makes the burn-rate arithmetic in burn.py pinnable at window
  edges: a window of ``d`` ms ending at ``now`` covers every bucket that
  overlaps the half-open interval ``(now - d, now]``.
* :class:`OpenLoopGenerator` -- an arrival-rate-driven load model
  (ROADMAP item 3(d)): inter-arrival times are seeded exponential draws
  *independent of completions*, keys are zipfian over the working set, and
  each arrival is stamped with one of millions of simulated client ids.
  Because arrivals never wait for the server, latency measured from the
  scheduled arrival includes queueing delay -- the coordinated-omission
  fix the closed-loop driver could not provide.

Everything here is stdlib-only so tools can import it without JAX.
"""

from __future__ import annotations

import bisect
import random
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Sequence, Tuple

from ..observability import SERVING_LATENCY_BUCKETS_MS


def histogram_quantile(
    buckets: Sequence[float], counts: Sequence[int], q: float,
) -> float:
    """The smallest bucket upper edge whose cumulative count reaches rank
    ``q * total`` (inclusive ``le`` edges, Prometheus convention).
    ``counts`` has one slot per edge plus the +Inf overflow slot. Returns
    0.0 on an empty histogram and ``inf`` when the rank lands in the
    overflow bucket."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = q * total
    cumulative = 0
    for edge, count in zip(buckets, counts):
        cumulative += count
        if cumulative >= rank:
            return float(edge)
    return float("inf")


@dataclass
class WindowStats:
    """Exact aggregate of one trailing window: per-predicate good counts,
    total scored requests, offered arrivals, and the merged latency
    histogram counts (parallel to ``latency_buckets`` plus +Inf)."""

    total: int = 0
    offered: int = 0
    good: Dict[str, int] = field(default_factory=dict)
    latency_buckets: Tuple[float, ...] = SERVING_LATENCY_BUCKETS_MS
    latency_counts: List[int] = field(default_factory=list)

    def availability(self, predicate: str) -> float:
        """good/total ratio for one named good-event predicate (1.0 on an
        empty window: no traffic consumes no error budget)."""
        if self.total <= 0:
            return 1.0
        return self.good.get(predicate, 0) / self.total

    def error_rate(self, predicate: str) -> float:
        return 1.0 - self.availability(predicate)

    def quantile(self, q: float) -> float:
        return histogram_quantile(self.latency_buckets, self.latency_counts, q)

    def goodput_ratio(self, predicate: str = "availability") -> float:
        """Completed-good over offered arrivals (1.0 when nothing was
        offered). Under overload this drops below availability: arrivals
        that never completed in the window count against it."""
        if self.offered <= 0:
            return 1.0
        return min(1.0, self.good.get(predicate, 0) / self.offered)


class _Bucket:
    __slots__ = ("start_ms", "total", "offered", "good", "latency_counts")

    def __init__(self, start_ms: int, predicates: Tuple[str, ...],
                 n_latency_slots: int) -> None:
        self.start_ms = start_ms
        self.total = 0
        self.offered = 0
        self.good = {p: 0 for p in predicates}
        self.latency_counts = [0] * n_latency_slots


class SliTracker:
    """Fixed-width time-bucket ring of SLI aggregates.

    ``predicates`` names the good-event predicates tracked per request (the
    caller evaluates them -- the tracker only counts). Buckets materialize
    lazily on first record so idle time costs nothing; the ring holds at
    most ``max_buckets`` buckets, evicting the oldest. Time must not run
    backwards across record calls (both planes feed a monotonic clock)."""

    def __init__(self, bucket_ms: int = 1000, max_buckets: int = 4096,
                 predicates: Sequence[str] = ("availability",),
                 latency_buckets: Tuple[float, ...] = SERVING_LATENCY_BUCKETS_MS,
                 ) -> None:
        assert bucket_ms >= 1
        assert max_buckets >= 2
        self.bucket_ms = int(bucket_ms)
        self.max_buckets = int(max_buckets)
        self.predicates = tuple(predicates)
        self.latency_buckets = tuple(latency_buckets)
        self._n_latency_slots = len(self.latency_buckets) + 1
        # fed from one execution context per owner (the service's protocol
        # executor, or the bench/sim driving thread) -- see SloPlane
        self._buckets: List[_Bucket] = []  # guarded-by: protocol-executor

    def _bucket_for(self, now_ms: int) -> _Bucket:
        start = (int(now_ms) // self.bucket_ms) * self.bucket_ms
        if self._buckets and self._buckets[-1].start_ms >= start:
            return self._buckets[-1]
        b = _Bucket(start, self.predicates, self._n_latency_slots)
        self._buckets.append(b)
        if len(self._buckets) > self.max_buckets:
            del self._buckets[: len(self._buckets) - self.max_buckets]
        return b

    def record(self, now_ms: int, latency_ms: float,
               good: Iterable[str] = ()) -> None:
        """Score one completed request at ``now_ms``: ``good`` is the set of
        predicate names the request satisfied."""
        b = self._bucket_for(now_ms)
        b.total += 1
        for name in good:
            if name in b.good:
                b.good[name] += 1
        i = bisect.bisect_left(self.latency_buckets, latency_ms)
        b.latency_counts[min(i, self._n_latency_slots - 1)] += 1

    def record_offered(self, now_ms: int, n: int = 1) -> None:
        """Count ``n`` open-loop arrivals offered at ``now_ms`` (whether or
        not they ever complete -- that asymmetry IS the goodput signal)."""
        self._bucket_for(now_ms).offered += n

    def window(self, now_ms: int, duration_ms: int) -> WindowStats:
        """Exact aggregate over every bucket overlapping
        ``(now_ms - duration_ms, now_ms]``."""
        cutoff = int(now_ms) - int(duration_ms)
        stats = WindowStats(
            latency_buckets=self.latency_buckets,
            latency_counts=[0] * self._n_latency_slots,
            good={p: 0 for p in self.predicates},
        )
        for b in reversed(self._buckets):
            if b.start_ms + self.bucket_ms <= cutoff:
                break
            if b.start_ms > now_ms:
                continue
            stats.total += b.total
            stats.offered += b.offered
            for name, count in b.good.items():
                stats.good[name] += count
            for i, c in enumerate(b.latency_counts):
                stats.latency_counts[i] += c
        return stats

    def span_ms(self) -> int:
        """Virtual time covered by the live ring (0 when empty)."""
        if not self._buckets:
            return 0
        return (
            self._buckets[-1].start_ms + self.bucket_ms
            - self._buckets[0].start_ms
        )


@dataclass(frozen=True)
class Arrival:
    """One open-loop client request, scheduled independently of every
    completion. ``at_ms`` is the arrival offset on the virtual clock."""

    at_ms: int
    op: str  # "get" | "put"
    key: bytes
    value: bytes
    client: int


class OpenLoopGenerator:
    """Arrival-rate-driven load: seeded exponential inter-arrivals, zipfian
    key popularity, and per-arrival simulated client ids drawn from a
    population of ``clients`` (millions by default). Deterministic per
    ``seed``: two generators with equal constructor arguments emit
    identical arrival streams.

    The zipf CDF is precomputed once over the working set (weight of key
    rank ``r`` is ``(r + 1) ** -zipf_s``), so each draw is one uniform
    variate plus a bisect -- cheap enough for millions of arrivals."""

    def __init__(self, rate_per_s: float, keys: Sequence[bytes],
                 put_fraction: float = 0.2, seed: int = 0,
                 zipf_s: float = 1.1, clients: int = 1_000_000) -> None:
        assert rate_per_s > 0
        assert keys
        assert 0.0 <= put_fraction <= 1.0
        self.rate_per_s = float(rate_per_s)
        self.keys = tuple(keys)
        self.put_fraction = float(put_fraction)
        self.clients = int(clients)
        self._rng = random.Random(seed)
        self._t_ms = 0.0
        self._seq = 0
        cdf: List[float] = []
        acc = 0.0
        for rank in range(len(self.keys)):
            acc += (rank + 1) ** -float(zipf_s)
            cdf.append(acc)
        self._cdf = [w / acc for w in cdf]

    def _pick_key(self) -> bytes:
        return self.keys[bisect.bisect_left(self._cdf, self._rng.random())]

    def next_arrival(self) -> Arrival:
        self._t_ms += self._rng.expovariate(self.rate_per_s) * 1000.0
        self._seq += 1
        op = "put" if self._rng.random() < self.put_fraction else "get"
        client = self._rng.randrange(self.clients)
        key = self._pick_key()
        value = b""
        if op == "put":
            value = b"v%d-c%d" % (self._seq, client)
        return Arrival(
            at_ms=int(self._t_ms), op=op, key=key, value=value, client=client,
        )

    def arrivals(self, n: int) -> List[Arrival]:
        return [self.next_arrival() for _ in range(n)]

    def rebase(self, at_ms: int) -> None:
        """Move the arrival clock forward to ``at_ms`` (never backward):
        the bench uses this to start a new load window after a virtual-time
        jump (e.g. a view change billed while the client was idle)."""
        self._t_ms = max(self._t_ms, float(at_ms))
