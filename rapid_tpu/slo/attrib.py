"""Churn-episode attribution: name the membership event a burn is paying for.

A burn-rate alert says the serving path is hurting; the flight-recorder
journal says what the membership plane was doing. This module joins them:

* :func:`episodes_from_journal` folds a journal tail (FlightRecorder
  entry dicts, or their JSON-line wire form) into :class:`Episode`
  values -- a ``view-change`` episode opens at the first ``fd_signal``
  carrying a churn trace id and closes at the ``view_install`` stamped
  with the same id (both planes stamp it since this PR), picking up the
  eviction count from the install and the moved-partition count from the
  matching ``placement_rebalance``; a ``recovery`` episode wraps a
  ``durability_recovered`` replay.
* :func:`attribute_burn` picks the episode overlapping a burn window
  (largest overlap wins, later start breaking ties -- the episode still
  in flight is the one you page about).
* :func:`describe` renders the operator line tools/slo.py and statusz
  print: ``attributed to view-change episode <trace-id> (3 nodes
  evicted, 41 partitions moved)``.

Pure data in, pure data out: no clock, no node handles, so the same code
attributes a live status response, a bench artifact, or a journal file.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Union


@dataclass(frozen=True)
class Episode:
    """One membership episode reconstructed from the journal."""

    kind: str            # "view-change" | "recovery"
    trace_id: int        # churn trace id (0 when the journal predates ids)
    start_ms: int
    end_ms: int
    nodes_evicted: int = 0
    nodes_added: int = 0
    partitions_moved: int = 0
    configuration_id: int = 0
    node: str = ""

    def overlap_ms(self, window_start_ms: int, window_end_ms: int) -> int:
        """Closed-interval overlap with a burn window (an instantaneous
        episode inside the window still counts as 1 ms)."""
        lo = max(self.start_ms, int(window_start_ms))
        hi = min(self.end_ms, int(window_end_ms))
        if lo > hi:
            return 0
        return max(hi - lo, 1)


def _parse_entries(
    journal: Sequence[Union[str, Dict[str, object]]],
) -> List[Dict[str, object]]:
    out: List[Dict[str, object]] = []
    for raw in journal:
        if isinstance(raw, str):
            try:
                entry = json.loads(raw)
            except (ValueError, TypeError):
                continue
        else:
            entry = raw
        if isinstance(entry, dict) and "kind" in entry:
            out.append(entry)
    return out


def _ms(entry: Dict[str, object]) -> int:
    value = entry.get("virtual_ms")
    try:
        return int(value)  # type: ignore[arg-type]
    except (TypeError, ValueError):
        return 0


def _detail_int(entry: Dict[str, object], key: str) -> int:
    detail = entry.get("detail")
    if not isinstance(detail, dict):
        return 0
    try:
        return int(detail.get(key, 0))  # type: ignore[arg-type]
    except (TypeError, ValueError):
        return 0


def episodes_from_journal(
    journal: Sequence[Union[str, Dict[str, object]]],
) -> List[Episode]:
    """Fold a journal tail into episodes, ordered by start time.

    Works across the journal dialects of both planes: entries may be JSON
    lines (the status-RPC wire form) or live entry dicts. An ``fd_signal``
    with a trace id opens (or extends) an episode; the ``view_install``
    carrying the same trace id closes it. An install with no matching
    signal in the tail (the ring evicted it) still yields an episode whose
    start is the install itself. A still-open signal with no install yet
    yields an in-flight episode (end = its own start)."""
    entries = sorted(_parse_entries(journal), key=_ms)
    open_signals: Dict[int, int] = {}   # trace_id -> first fd_signal ms
    moved_by_config: Dict[int, int] = {}
    episodes: List[Episode] = []
    for entry in entries:
        kind = entry.get("kind")
        if kind == "fd_signal":
            trace = _detail_int(entry, "trace_id")
            open_signals.setdefault(trace, _ms(entry))
        elif kind == "placement_rebalance":
            config = _detail_int(entry, "configuration_id")
            moved_by_config[config] = (
                moved_by_config.get(config, 0) + _detail_int(entry, "moved")
            )
        elif kind == "view_install":
            trace = _detail_int(entry, "trace_id")
            start = open_signals.pop(trace, _ms(entry)) if trace else _ms(entry)
            config = _detail_int(entry, "configuration_id")
            episodes.append(Episode(
                kind="view-change",
                trace_id=trace,
                start_ms=start,
                end_ms=_ms(entry),
                nodes_evicted=_detail_int(entry, "removed"),
                nodes_added=_detail_int(entry, "added"),
                partitions_moved=moved_by_config.get(config, 0),
                configuration_id=config,
                node=str(entry.get("node", "")),
            ))
        elif kind == "durability_recovered":
            episodes.append(Episode(
                kind="recovery",
                trace_id=0,
                start_ms=_ms(entry),
                end_ms=_ms(entry),
                partitions_moved=0,
                nodes_evicted=0,
                configuration_id=0,
                node=str(
                    (entry.get("detail") or {}).get("node", "")  # type: ignore[union-attr]
                    or entry.get("node", "")
                ),
            ))
    # signals whose install has not landed yet: in-flight episodes
    for trace, start in sorted(open_signals.items()):
        if trace:
            episodes.append(Episode(
                kind="view-change", trace_id=trace,
                start_ms=start, end_ms=start,
            ))
    episodes.sort(key=lambda e: (e.start_ms, e.end_ms, e.trace_id))
    return episodes


def attribute_burn(
    episodes: Sequence[Episode],
    window_start_ms: int, window_end_ms: int,
) -> Optional[Episode]:
    """The episode a burn window is attributed to: the one overlapping
    ``[window_start_ms, window_end_ms]`` the longest, later start winning
    ties. None when nothing overlaps (the burn is load-born, not
    churn-born -- the honest answer)."""
    best: Optional[Episode] = None
    best_key = (-1, -1)
    for episode in episodes:
        overlap = episode.overlap_ms(window_start_ms, window_end_ms)
        if overlap <= 0:
            continue
        key = (overlap, episode.start_ms)
        if key > best_key:
            best, best_key = episode, key
    return best


def describe(episode: Optional[Episode]) -> str:
    """The operator rendering of an attribution (tools/slo.py, statusz)."""
    if episode is None:
        return "unattributed (no overlapping membership episode)"
    if episode.kind == "recovery":
        where = f" on {episode.node}" if episode.node else ""
        return f"recovery replay{where}"
    bits = []
    if episode.nodes_evicted:
        bits.append(f"{episode.nodes_evicted} nodes evicted")
    if episode.nodes_added:
        bits.append(f"{episode.nodes_added} nodes added")
    if episode.partitions_moved:
        bits.append(f"{episode.partitions_moved} partitions moved")
    suffix = f" ({', '.join(bits)})" if bits else ""
    return (
        f"view-change episode {episode.trace_id or episode.configuration_id}"
        f"{suffix}"
    )
