"""Multi-window multi-burn-rate alerting over declared SLO targets.

The discipline is the SRE-workbook one: an alert fires only when BOTH a
short and a long window burn error budget faster than the pair's
threshold -- the long window proves the burn is sustained, the short
window makes the alert reset quickly once the burn stops. Two pairs are
declared: a fast pair (5m/1h at 14.4x budget) that pages on acute
incidents, and a slow pair (6h/3d at 6x) that catches slow leaks. Burn
rate is ``error_rate / (1 - objective)``: 1.0 means the budget is being
consumed exactly at the rate that exhausts it over the SLO period.

``SLOSettings.window_scale`` maps the wall-scale windows onto virtual
time: every declared window duration is multiplied by the scale before
use, and nothing else changes -- the burn arithmetic is scale-invariant,
which is what makes virtual-vs-wall parity testable (same engine, same
numbers, different clock feed).

``SLI_CATALOG`` / ``SLO_CATALOG`` / ``BURN_WINDOWS`` are pure module
literals so tools/check.py can lint them without importing (slo-catalog
rule): every declared SLO must name a cataloged SLI and a valid window
pair.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .attrib import Episode, attribute_burn, episodes_from_journal
from .sli import SliTracker

# The good-event predicates the tracker scores per request. A latency SLO
# is expressed as availability-of-fast-requests (good = OK AND latency at
# or under the objective's threshold) so one burn arithmetic covers both.
SLI_CATALOG = {
    "availability": {
        "doc": "good requests / total requests; good = the request "
               "completed with STATUS_OK (NOT_FOUND counts as good for "
               "reads: the store answered correctly)",
    },
    "fast-availability": {
        "doc": "requests both OK and completing within the declaring "
               "SLO's latency_threshold_ms / total requests -- the "
               "latency SLO as an availability ratio",
    },
    "goodput": {
        "doc": "completed-good requests vs offered open-loop arrivals; "
               "diverges from availability under overload because "
               "never-completed arrivals count against it",
    },
}

# Window pairs, wall-scale seconds. "burn" is the fire threshold in
# multiples of budget-exhaustion rate; the canonical SRE pairings.
BURN_WINDOWS = {
    "fast": {"short_s": 300, "long_s": 3600, "burn": 14.4},
    "slow": {"short_s": 21600, "long_s": 259200, "burn": 6.0},
}

# Declared SLO targets over the serving path. Every entry must name a
# cataloged SLI and valid window pairs (tools/check.py slo-catalog rule);
# fast-availability SLOs must declare latency_threshold_ms.
SLO_CATALOG = {
    "serving.availability": {
        "sli": "availability",
        "objective": 0.999,
        "windows": ("fast", "slow"),
        "doc": "99.9% of serving requests complete OK",
    },
    "serving.latency": {
        "sli": "fast-availability",
        "objective": 0.99,
        "latency_threshold_ms": 25.0,
        "windows": ("fast", "slow"),
        "doc": "99% of serving requests complete OK within 25 ms of their "
               "scheduled arrival (open-loop: queueing delay included)",
    },
}


@dataclass
class BurnAlert:
    """Live state of one (SLO, window-pair) alert."""

    slo: str
    window: str
    objective: float
    threshold: float          # fire threshold (burn multiple)
    short_ms: int             # scaled short-window duration
    long_ms: int              # scaled long-window duration
    firing: bool = False
    fired_at_ms: int = 0
    cleared_at_ms: int = 0
    burn_short: float = 0.0   # latest short-window burn rate
    burn_long: float = 0.0    # latest long-window burn rate
    peak_burn: float = 0.0    # max short-window burn observed
    fired_count: int = 0
    attributed: Optional[Episode] = None

    @property
    def name(self) -> str:
        return f"{self.slo}:{self.window}"


class BurnRateEngine:
    """Burn-rate evaluation for one declared SLO over a shared tracker.

    ``tick(now_ms)`` recomputes both windows of every declared pair and
    runs the fire/clear state machine:

    * FIRE when short-window burn >= threshold AND long-window burn >=
      threshold (both, per the multi-window rule);
    * CLEAR only when both burns drop below ``clear_fraction`` x the
      threshold (hysteresis: a burn hovering at the threshold cannot
      flap the alert).
    """

    def __init__(self, slo: str, spec: Dict[str, object],
                 tracker: SliTracker, *, window_scale: float = 1.0,
                 clear_fraction: float = 0.9,
                 windows: Optional[Dict[str, Dict[str, float]]] = None,
                 ) -> None:
        self.slo = slo
        self.spec = spec
        self.tracker = tracker
        self.sli = str(spec["sli"])
        self.objective = float(spec["objective"])  # type: ignore[arg-type]
        self.budget = 1.0 - self.objective
        assert self.budget > 0.0, f"objective for {slo} leaves no budget"
        self.clear_fraction = float(clear_fraction)
        window_table = windows if windows is not None else BURN_WINDOWS
        self.alerts: List[BurnAlert] = []
        for pair in spec["windows"]:  # type: ignore[union-attr]
            w = window_table[str(pair)]
            self.alerts.append(BurnAlert(
                slo=slo, window=str(pair),
                objective=self.objective, threshold=float(w["burn"]),
                short_ms=max(1, int(round(
                    float(w["short_s"]) * 1000.0 * window_scale))),
                long_ms=max(1, int(round(
                    float(w["long_s"]) * 1000.0 * window_scale))),
            ))

    def burn_rate(self, now_ms: int, duration_ms: int) -> float:
        """Error-budget consumption multiple over one trailing window."""
        window = self.tracker.window(now_ms, duration_ms)
        return window.error_rate(self.sli) / self.budget

    def tick(self, now_ms: int) -> List[Tuple[str, BurnAlert]]:
        """Re-evaluate every pair; returns ("fired"|"cleared", alert)
        transitions that happened on this tick."""
        transitions: List[Tuple[str, BurnAlert]] = []
        for alert in self.alerts:
            alert.burn_short = self.burn_rate(now_ms, alert.short_ms)
            alert.burn_long = self.burn_rate(now_ms, alert.long_ms)
            alert.peak_burn = max(alert.peak_burn, alert.burn_short)
            if not alert.firing:
                if (alert.burn_short >= alert.threshold
                        and alert.burn_long >= alert.threshold):
                    alert.firing = True
                    alert.fired_at_ms = int(now_ms)
                    alert.fired_count += 1
                    transitions.append(("fired", alert))
            else:
                clear_at = alert.threshold * self.clear_fraction
                if (alert.burn_short < clear_at
                        and alert.burn_long < clear_at):
                    alert.firing = False
                    alert.cleared_at_ms = int(now_ms)
                    transitions.append(("cleared", alert))
        return transitions


class SloPlane:
    """The online SLO plane: one shared SLI tracker fed from the serving
    path, a burn engine per declared SLO, and episode attribution against
    the flight-recorder journal.

    Composition-only: callers hand in the clock value with every call, so
    the same object serves the simulator's virtual clock and the protocol
    plane's scheduler clock. ``metrics``/``recorder`` are optional -- the
    plane works bare (bench/tests) and instruments when wired into a node.
    """

    def __init__(self, settings=None, metrics=None, recorder=None,
                 catalog: Optional[Dict[str, Dict[str, object]]] = None,
                 windows: Optional[Dict[str, Dict[str, float]]] = None,
                 ) -> None:
        if settings is None:
            from ..settings import SLOSettings

            settings = SLOSettings(enabled=True)
        self.settings = settings
        self.metrics = metrics
        self.recorder = recorder
        self.catalog = dict(catalog if catalog is not None else SLO_CATALOG)
        self._thresholds: Dict[str, float] = {}
        predicates = sorted({str(s["sli"]) for s in self.catalog.values()})
        self.tracker = SliTracker(
            bucket_ms=settings.bucket_ms,
            max_buckets=settings.max_buckets,
            predicates=tuple(predicates),
        )
        self.engines: Dict[str, BurnRateEngine] = {}
        for name, spec in sorted(self.catalog.items()):
            self.engines[name] = BurnRateEngine(
                name, spec, self.tracker,
                window_scale=settings.window_scale,
                clear_fraction=settings.clear_fraction,
                windows=windows,
            )
            if str(spec["sli"]) == "fast-availability":
                self._thresholds[name] = float(
                    spec["latency_threshold_ms"])  # type: ignore[arg-type]
        self._fast_threshold_ms = min(
            self._thresholds.values(), default=float("inf")
        )
        # single execution context per owner: the membership service feeds
        # the plane from its protocol executor (serving handlers and their
        # completion callbacks run there), bench/sim from the driving thread
        self._last_tick_bucket: Optional[int] = None  # guarded-by: protocol-executor
        # forensics-plane seam: invoked with the transition list whenever a
        # tick produces one (the burn-alert evidence-capture trigger); the
        # owner sets it, the plane never requires it
        self.on_transition: Optional[
            Callable[[List[Tuple[str, BurnAlert]]], None]
        ] = None

    # -- feeding ------------------------------------------------------------

    def record(self, now_ms: int, ok: bool, latency_ms: float) -> None:
        """Score one completed serving request."""
        good: List[str] = []
        if ok:
            good.append("availability")
            good.append("goodput")
            if latency_ms <= self._fast_threshold_ms:
                good.append("fast-availability")
        self.tracker.record(now_ms, latency_ms, good)
        if self.metrics is not None:
            self.metrics.incr("slo.requests")
        self.tick(now_ms)

    def record_offered(self, now_ms: int, n: int = 1) -> None:
        """Count open-loop arrivals offered to the serving path."""
        self.tracker.record_offered(now_ms, n)
        if self.metrics is not None:
            self.metrics.incr("slo.offered", n)

    # -- alerting -----------------------------------------------------------

    def tick(self, now_ms: int, force: bool = False) -> List[Tuple[str, BurnAlert]]:
        """Run every burn engine (at most once per SLI bucket unless
        ``force``), emit metrics + journal events on transitions."""
        bucket = int(now_ms) // self.tracker.bucket_ms
        if not force and bucket == self._last_tick_bucket:
            return []
        self._last_tick_bucket = bucket
        transitions: List[Tuple[str, BurnAlert]] = []
        for name, engine in self.engines.items():
            transitions.extend(engine.tick(now_ms))
            if self.metrics is not None:
                window = self.tracker.window(
                    now_ms, engine.alerts[0].long_ms
                )
                self.metrics.set_gauge(
                    "slo.availability",
                    round(window.availability(engine.sli) * 1000.0),
                    slo=name,
                )
                for alert in engine.alerts:
                    self.metrics.set_gauge(
                        "slo.burn_rate", alert.burn_short,
                        slo=name, window=alert.window,
                    )
        for kind, alert in transitions:
            if self.metrics is not None:
                self.metrics.incr(
                    "slo.alerts_fired" if kind == "fired"
                    else "slo.alerts_cleared"
                )
            if self.recorder is not None:
                self.recorder.record(
                    "slo_alert_fired" if kind == "fired"
                    else "slo_alert_cleared",
                    virtual_ms=int(now_ms),
                    slo=alert.slo, window=alert.window,
                    burn_milli=int(round(alert.burn_short * 1000)),
                )
        if self.metrics is not None and (transitions or force):
            self.metrics.set_gauge("slo.firing", self.firing_count())
        if transitions and self.on_transition is not None:
            try:
                self.on_transition(transitions)
            except Exception:  # noqa: BLE001 -- an evidence capture must
                # never sink the serving/status path that ticked the plane
                pass
        return transitions

    def alerts(self) -> List[BurnAlert]:
        out: List[BurnAlert] = []
        for name in sorted(self.engines):
            out.extend(self.engines[name].alerts)
        return out

    def firing_count(self) -> int:
        return sum(1 for a in self.alerts() if a.firing)

    # -- attribution --------------------------------------------------------

    def attribute(self, journal: Sequence[Dict[str, object]]) -> None:
        """Correlate every alert that has ever fired with the membership
        episode overlapping its burn window (attrib.py); idempotent, so
        status calls can re-run it as the journal grows."""
        episodes = episodes_from_journal(journal)
        if not episodes:
            return
        for alert in self.alerts():
            if alert.fired_count == 0:
                continue
            end = alert.cleared_at_ms if not alert.firing else None
            alert.attributed = attribute_burn(
                episodes,
                alert.fired_at_ms - alert.short_ms,
                end if end is not None else alert.fired_at_ms + alert.short_ms,
            ) or alert.attributed

    # -- export -------------------------------------------------------------

    def status_digest(self) -> Tuple[Tuple[str, ...], Tuple[int, ...],
                                     Tuple[int, ...], Tuple[int, ...]]:
        """Parallel arrays for ClusterStatusResponse: alert names
        ("slo:window"), short-window burn in thousandths, firing flags,
        and the attributed episode's trace id (0 = unattributed)."""
        alerts = self.alerts()
        return (
            tuple(a.name for a in alerts),
            tuple(int(round(a.burn_short * 1000)) for a in alerts),
            tuple(int(a.firing) for a in alerts),
            tuple(
                int(a.attributed.trace_id) if a.attributed is not None else 0
                for a in alerts
            ),
        )

    def summary(self, now_ms: int) -> Dict[str, object]:
        """JSON-ready SLI/alert summary (the bench artifact rides this)."""
        out: Dict[str, object] = {}
        for name, engine in sorted(self.engines.items()):
            long_ms = max(a.long_ms for a in engine.alerts)
            window = self.tracker.window(now_ms, long_ms)
            out[name] = {
                "objective": engine.objective,
                "availability": window.availability(engine.sli),
                "p99_ms": window.quantile(0.99),
                "goodput_ratio": window.goodput_ratio(engine.sli),
                "peak_burn": max(a.peak_burn for a in engine.alerts),
                "alerts": {
                    a.window: {
                        "firing": a.firing,
                        "fired_count": a.fired_count,
                        "burn_short": a.burn_short,
                        "burn_long": a.burn_long,
                    }
                    for a in engine.alerts
                },
            }
        return out
