"""SLO plane: online SLIs, multi-window burn-rate alerts, and
churn-episode attribution for the serving path.

Three layers, measurement to explanation:

* sli.py -- streaming SLI computation: windowed availability with
  explicit good-event predicates, latency percentiles from mergeable
  fixed-bucket histograms, goodput-vs-offered-load, and the open-loop
  arrival-rate load generator (zipfian keys, millions of simulated
  clients) that feeds them.
* burn.py -- declared SLO targets (SLO_CATALOG) evaluated by
  multi-window multi-burn-rate alerting (fast 5m/1h + slow 6h/3d pairs,
  scaled onto virtual time), composed into SloPlane behind the
  ``slo.enabled`` kill switch.
* attrib.py -- episode attribution: the flight-recorder journal names
  the view-change / recovery episode a burn window overlaps, so alerts
  read "p99 burning, attributed to view-change episode <trace-id>".
"""

from .attrib import Episode, attribute_burn, describe, episodes_from_journal
from .burn import (
    BURN_WINDOWS,
    SLI_CATALOG,
    SLO_CATALOG,
    BurnAlert,
    BurnRateEngine,
    SloPlane,
)
from .sli import (
    Arrival,
    OpenLoopGenerator,
    SliTracker,
    WindowStats,
    histogram_quantile,
)

__all__ = [
    "BURN_WINDOWS",
    "SLI_CATALOG",
    "SLO_CATALOG",
    "Arrival",
    "BurnAlert",
    "BurnRateEngine",
    "Episode",
    "OpenLoopGenerator",
    "SliTracker",
    "SloPlane",
    "WindowStats",
    "attribute_burn",
    "describe",
    "episodes_from_journal",
    "histogram_quantile",
]
