"""Tracing and metrics.

The reference has no tracing/metrics subsystem (SURVEY.md §5.1: jacoco +
surefire wall-times only; §5.5: four subscription events are the whole
observable surface). Since this framework's headline metric is
time-to-stable-view, observability is first-class here:

- ``Metrics``: cheap named counters, used by the protocol plane (messages by
  type, alerts, proposals, view changes) and the simulator (rounds, device
  dispatches).
- ``Tracer``: wall/virtual-time spans with a single flat log, suitable for
  both the event-driven protocol plane and the round-driven simulator.
- ``device_trace``: context manager around jax.profiler for capturing a TPU
  trace of the simulation hot loop (view in TensorBoard/XProf).
"""

from __future__ import annotations

import collections
import contextlib
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional


class Metrics:
    """Process-wide counter registry (per-Cluster instances get their own)."""

    def __init__(self) -> None:
        self._counters: Dict[str, int] = collections.defaultdict(int)

    def incr(self, name: str, amount: int = 1) -> None:
        self._counters[name] += amount

    def get(self, name: str) -> int:
        return self._counters.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        return dict(self._counters)

    def reset(self) -> None:
        self._counters.clear()


# Process-wide default registry for components that outlive any one Cluster:
# the nemesis fault plane (faults.py) counts injected faults here unless
# given a registry ("nemesis_*" counters), and the retry combinator counts
# "retry_*" when handed one. Tests snapshot/reset it around a run.
_GLOBAL_METRICS = Metrics()


def global_metrics() -> Metrics:
    return _GLOBAL_METRICS


@dataclass
class Span:
    name: str
    wall_start_s: float
    wall_end_s: float = 0.0
    virtual_start_ms: Optional[int] = None
    virtual_end_ms: Optional[int] = None
    attrs: Dict[str, object] = field(default_factory=dict)

    @property
    def wall_ms(self) -> float:
        return (self.wall_end_s - self.wall_start_s) * 1000.0


class Tracer:
    def __init__(self) -> None:
        self.spans: List[Span] = []

    @contextlib.contextmanager
    def span(self, name: str, virtual_ms: Optional[int] = None, **attrs) -> Iterator[Span]:
        s = Span(name=name, wall_start_s=time.perf_counter(),
                 virtual_start_ms=virtual_ms, attrs=dict(attrs))
        try:
            yield s
        finally:
            s.wall_end_s = time.perf_counter()
            self.spans.append(s)

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-name aggregate: count, total/mean wall ms."""
        agg: Dict[str, Dict[str, float]] = {}
        for s in self.spans:
            entry = agg.setdefault(s.name, {"count": 0, "total_ms": 0.0})
            entry["count"] += 1
            entry["total_ms"] += s.wall_ms
        for entry in agg.values():
            entry["mean_ms"] = entry["total_ms"] / entry["count"]
        return agg


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax/XLA profiler trace of everything inside the block."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
