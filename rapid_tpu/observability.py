"""The telemetry plane: labeled metrics, hierarchical tracing, exporters.

The reference has no tracing/metrics subsystem (SURVEY.md §5.1: jacoco +
surefire wall-times only; §5.5: four subscription events are the whole
observable surface). Since this framework's headline metric is
time-to-stable-view, observability is first-class here:

- ``Metrics``: thread-safe counters, gauges, and fixed-bucket histograms
  keyed by ``(name, labels)``. Per-``Cluster``/``Simulator`` instances get
  their own registry attached (via weakref) to the process-global one, so
  exporters see every plane merged while ``snapshot()``/``get()`` stay
  per-instance. ``NullMetrics`` is the no-op registry used to measure
  telemetry overhead.
- ``Tracer``: wall/virtual-time spans with parent ids and a contextvar-based
  current span, bounded by a ring buffer (``dropped`` counts evictions).
  Per-instance tracers attach to the process-global one the same way, so a
  single Chrome trace carries protocol, simulator, and fault-plane spans on
  one timeline.
- ``StableViewTimer``: derives per-view-change latency histograms
  (detection -> decision -> view-installed) on a caller-supplied clock --
  virtual ms on both the event-driven plane and the simulator, so the
  ``time_to_stable_view_ms`` distributions are directly comparable.
- Exporters: Chrome ``trace_event`` JSON (Perfetto-loadable; simulator spans
  additionally plotted on a virtual-time track), Prometheus text exposition
  (``rapid_*``-prefixed, labeled), and a JSON snapshot.
- ``device_trace``: context manager around jax.profiler for capturing a TPU
  trace of the simulation hot loop (view in TensorBoard/XProf).

Metric names are ``snake.dot`` strings from ``METRIC_CATALOG`` (enforced by
tools/check.py's metric-name lint); label conventions are documented in
ARCHITECTURE.md's "Telemetry plane" section.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import itertools
import json
import os
import re
import tempfile
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Tuple

try:
    from .runtime.lockdep import make_lock
except ImportError:  # loaded standalone by tools/check.py (no parent package)
    def make_lock(name: str) -> threading.Lock:  # noqa: ARG001
        return threading.Lock()

# --------------------------------------------------------------------------- #
# Metric name catalog
# --------------------------------------------------------------------------- #

# Every incr/observe call site in rapid_tpu/ must use one of these names (or
# a name under one of METRIC_PREFIXES); tools/check.py fails unknown names.
# Kept flat and exhaustive on purpose: the catalog doubles as the metric
# documentation index referenced from ARCHITECTURE.md.
METRIC_CATALOG = frozenset({
    # protocol plane (service.py)
    "alerts_enqueued",
    "proposals",
    "view_changes",
    "view_changes_refused_missing_identity",
    "fd.edge_failures",
    # failure detectors (monitoring/)
    "fd.probes",
    "fd.probe_failures",
    "fd.rtt_ms",  # per-probe round trip (the gray-node observable)
    # adaptive gray-aware FD (monitoring/adaptive.py)
    "fd.suspicion",            # per-probe tier-relative suspicion score
    "fd.adapted_interval_ms",  # probe interval chosen per edge tier
    "fd.gray_alerts",          # alerts fired by suspicion before hard-fail
    # cut detection (cut_detector.py)
    "cut.proposals_emitted",
    # consensus (fast_paxos.py / paxos.py)
    "consensus.fast_round_votes",
    "consensus.fast_decisions",
    "consensus.classic_rounds_started",
    "consensus.classic_decisions",
    # join pipeline (cluster.py)
    "join.exhausted",
    "join.phase1_no_response",
    # nemesis fault plane (faults.py)
    "nemesis_dropped",
    "nemesis_duplicated",
    "nemesis_delayed",
    "nemesis_reordered",
    "nemesis_passed",
    "nemesis_slowed",          # SlowNodeRule applied (gray node)
    "nemesis_wire_versioned",  # WireVersionRule codec round-trip applied
    "nemesis_zone_detection_ms",  # per-zone detection->decision (scenarios)
    # retry combinator (messaging/retries.py)
    "retry_attempts",
    "retry_exhausted",
    "retry_deadline_exceeded",
    "retry_backoff_ms",
    # messaging transport (messaging/reactor.py, messaging/tcp.py)
    "msg.sent",            # frames queued for transmission
    "msg.received",        # frames parsed off the wire
    "msg.bytes_sent",      # payload+header bytes actually written
    "msg.bytes_received",  # bytes read off the wire
    "msg.batch_size",      # frames coalesced per flush (histogram)
    "msg.flush_syscalls",  # sendmsg/send calls issued by channel flushes
    "msg.dial_backoffs",   # dials suppressed by the per-peer backoff gate
    "msg.batches_sent",    # MessageBatch envelopes emitted by broadcasters
    "msg.batched_messages",  # inner messages carried inside batch envelopes
    # simulator (sim/driver.py)
    "rounds",
    "device_dispatches",
    "classic_coordinator_races",
    "speculation_hits_fresh_state",
    "speculation_hits_config_id",
    # fault-array occupancy gauges (set once per flush, host mirrors only)
    "sim.fault.crashed",
    "sim.fault.ingress_partitioned",
    "sim.fault.lossy",
    "sim.membership_size",
    "sim.pending_joiners",
    # derived latency histograms (StableViewTimer, both planes)
    "latency.detection_to_decision_ms",
    "latency.decision_to_view_ms",
    "time_to_stable_view_ms",
    # placement plane (placement/, service.py, sim/driver.py)
    "placement.rebuilds",
    "placement.partitions_moved",
    "placement.imbalance",
    "placement.partitions_owned",
    # handoff plane (handoff/, service.py, sim/driver.py)
    "handoff.sessions_started",
    "handoff.sessions_completed",
    "handoff.sessions_failed",
    "handoff.chunks_sent",
    "handoff.chunks_received",
    "handoff.chunks_duplicate",
    "handoff.bytes_moved",
    "handoff.retries",
    "handoff.failovers",
    "handoff.fingerprint_mismatches",
    "handoff.session_bytes",
    "handoff.session_chunks",
    "handoff.releases",
    # serving plane (serving/, service.py, sim/driver.py)
    "serving.gets",
    "serving.puts",
    "serving.put_acks",
    "serving.put_retries",
    "serving.replication_writes",
    "serving.leader_reads",
    "serving.quorum_reads",
    "serving.not_leader_redirects",
    "serving.leader_changes",
    "serving.reconciled_replicas",
    "serving.request_ms",
    # profiling plane (profiling/, sim/driver.py, observability.py)
    "profile.phase_ms",    # per-phase device attribution (histogram)
    "profile.step_ms",     # shadow-measured full device step (histogram)
    "profile.samples",     # shadow attribution samples taken
    "profile.history_snapshots",  # metric history-ring snapshots recorded
    # durability plane (durability/)
    "durability.appends",           # WAL records appended (puts + deletes)
    "durability.fsyncs",            # physical fsync barriers issued
    "durability.snapshots",         # checkpoints written (snapshot + marker)
    "durability.segments",          # live WAL segment count (gauge)
    "durability.replayed_records",  # log records replayed by last recovery
    "durability.torn_truncations",  # torn tails truncated at a bad record
    # forensics plane (forensics/, observability.py)
    "journal.dropped_events",  # flight-recorder entries lost to overflow
    # SLO plane (slo/)
    "slo.requests",        # requests scored by the SLI tracker
    "slo.offered",         # open-loop arrivals offered to the serving path
    "slo.availability",    # windowed good/total ratio x1000 (gauge per SLO)
    "slo.burn_rate",       # short-window burn rate (gauge per SLO+window)
    "slo.firing",          # burn alerts currently firing (gauge)
    "slo.alerts_fired",    # burn-alert fire transitions
    "slo.alerts_cleared",  # burn-alert clear transitions (recovery)
    # hierarchy plane (hierarchy/, sim/driver.py)
    "hierarchy.cells",          # configured cell count (gauge)
    "hierarchy.live_cells",     # cells present in the composed global view
    "hierarchy.parent_rounds",  # parent configuration rounds advanced
})

# Dynamic name families: an f-string call site is legal iff its literal head
# starts with one of these prefixes (e.g. ``f"messages.{type_name}"``).
METRIC_PREFIXES = ("messages.",)

# Span names: every Tracer.span/begin/remote_span call site in rapid_tpu/
# must use one of these (tools/check.py lints literal first arguments, same
# discipline as METRIC_CATALOG).
SPAN_CATALOG = frozenset({
    "alert_batch",       # service.py: handling one BatchedAlertMessage
    "view_change",       # service.py + sim/driver.py: installing a view
    "device_rounds",     # sim/driver.py: a batch of device-dispatched rounds
    "placement_rebalance",  # placement map rebuilt after a view change
    "handoff_session",   # one partition's state transfer (handoff/engine.py)
    "serving_request",   # one client Get/Put through the serving engine
})

# Instant-event and flight-recorder kinds: every Tracer.event and
# FlightRecorder.record call site must use one of these.
EVENT_CATALOG = frozenset({
    # tracer instants
    "fd_signal",         # edge failure detector fired
    "alert_enqueued",    # alert queued for the next batch flush
    "proposal",          # cut detector emitted a proposal
    "cut_detected",      # H-th report crossed the watermark
    "fast_decision",     # Fast Paxos decided without a classic round
    "classic_decision",  # classic Paxos learner reached a majority
    # flight-recorder journal kinds (membership-relevant happenings)
    "alert_in",          # batched alerts received
    "alert_out",         # batched alerts flushed to the broadcaster
    "decision",          # consensus handed the service a proposal
    "view_install",      # view change applied
    "view_refused",      # view change refused (missing identity), parked
    "join_exhausted",    # a join burned all RETRIES attempts
    "kicked",            # this node was removed from the ring
    "status_served",     # answered a ClusterStatusRequest
    "placement_rebalance",  # placement map rebuilt (moved count + versions)
    "handoff_started",   # transfer sessions launched for a placement diff
    "handoff_complete",  # a session finished with a verified fingerprint
    "handoff_failed",    # a session exhausted sources/retries
    "handoff_release",   # source released a partition after a verified ack
    "serving_leader_change",  # a partition's leader moved with the view
    "serving_sync",      # churned partition re-synced from replica snapshots
    "durability_recovered",   # store reopened: snapshot loaded + log replayed
    "durability_checkpoint",  # snapshot + marker written, old segments culled
    "slo_alert_fired",   # multi-window burn-rate alert started firing
    "slo_alert_cleared",  # burn rates fell back under the clear threshold
    "bundle_captured",   # forensic evidence bundle written (trigger + path)
    "parent_round",      # hierarchy parent round advanced (composition moved)
})

# Histogram bucket upper edges (``le``, inclusive -- Prometheus convention).
DEFAULT_LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000,
)

# One shared definition for the headline distribution on BOTH planes: the
# acceptance criterion is that the simulator's and the protocol plane's
# time_to_stable_view_ms histograms are bucket-for-bucket comparable.
STABLE_VIEW_BUCKETS_MS: Tuple[float, ...] = (
    10, 50, 100, 250, 500, 1000, 2500, 5000, 10000, 15000, 30000, 60000,
    120000,
)

# Partitions moved per rebalance (placement.partitions_moved): powers of two
# up to the largest supported map so correlated-failure motion is directly
# readable off the histogram on both planes.
PARTITIONS_MOVED_BUCKETS: Tuple[float, ...] = (
    0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192,
)

# Bytes moved per handoff session (handoff.session_bytes): powers of four
# from 1 KiB to 1 GiB, wide enough for both the in-memory reference store
# and a real partition payload.
HANDOFF_BYTES_BUCKETS: Tuple[float, ...] = (
    0, 1024, 4096, 16384, 65536, 262144, 1048576, 4194304, 16777216,
    67108864, 268435456, 1073741824,
)

# Chunks per handoff session (handoff.session_chunks): powers of two.
HANDOFF_CHUNKS_BUCKETS: Tuple[float, ...] = (
    0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
)

# Per-request serving latency (serving.request_ms): sub-millisecond through
# view-change-window tails. Finer low end than DEFAULT_LATENCY_BUCKETS_MS
# because a leader read inside one process is typically < 1 ms, while a
# quorum write during churn can stretch to seconds.
SERVING_LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    0.25, 0.5, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000,
)

# Frames coalesced per channel flush (msg.batch_size): powers of two. A
# saturated broadcast storm should push mass well past 1 -- that ratio IS
# the write-coalescing win (syscalls per message = 1 / batch size).
MSG_BATCH_BUCKETS: Tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
)

# Per-phase device attribution (profile.phase_ms / profile.step_ms): a
# finer low end than DEFAULT_LATENCY_BUCKETS_MS because a single fused
# round at small N is tens of microseconds, while a 1M-node dispatch
# stretches to seconds.
PROFILE_PHASE_BUCKETS_MS: Tuple[float, ...] = (
    0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500,
    5000,
)


# --------------------------------------------------------------------------- #
# Histograms
# --------------------------------------------------------------------------- #


class Histogram:
    """Fixed-bucket histogram (no locking of its own; the owning Metrics
    serializes access). ``counts`` has one slot per bucket edge plus +Inf."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...]) -> None:
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        i = 0
        for i, edge in enumerate(self.buckets):  # noqa: B007
            if value <= edge:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += 1
        self.sum += value
        self.count += 1

    def copy(self) -> "Histogram":
        out = Histogram(self.buckets)
        out.counts = list(self.counts)
        out.sum = self.sum
        out.count = self.count
        return out

    def merge(self, other: "Histogram") -> None:
        if other.buckets != self.buckets:
            return  # mismatched definitions never merge (catalog bug)
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.sum += other.sum
        self.count += other.count

    def snapshot(self) -> Dict[str, object]:
        return {
            "buckets": list(self.buckets),
            "counts": list(self.counts),
            "sum": self.sum,
            "count": self.count,
        }


# --------------------------------------------------------------------------- #
# Metrics registry
# --------------------------------------------------------------------------- #

LabelItems = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> LabelItems:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _render(name: str, labels: LabelItems) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{inner}}}"


class Metrics:
    """Thread-safe labeled registry (counters, gauges, histograms).

    ``parent``: attach this registry (weakly) to another one; exporters
    walking the parent's ``collect()`` see this registry's samples with
    ``const_labels`` merged in. Per-Cluster/Simulator registries attach to
    ``global_metrics()`` by default, so one Prometheus scrape covers every
    plane while per-instance ``get``/``snapshot`` stay isolated.
    """

    def __init__(self, parent: Optional["Metrics"] = None,
                 **const_labels: object) -> None:
        self._lock = make_lock("Metrics._lock")
        self._counters: Dict[Tuple[str, LabelItems], int] = {}
        self._gauges: Dict[Tuple[str, LabelItems], float] = {}
        self._histograms: Dict[Tuple[str, LabelItems], Histogram] = {}
        self._const_labels: Dict[str, str] = {
            k: str(v) for k, v in sorted(const_labels.items())
        }
        self._children: List["weakref.ref[Metrics]"] = []
        # dead children's final samples, appended by GC finalizers and folded
        # in lazily by _drain_absorbed(). The finalizer must NOT take _lock:
        # cyclic GC can run inside this registry's own locked sections (any
        # allocation can trigger it), and a lock-taking finalizer would then
        # self-deadlock the thread. list.append is atomic and lock-free.
        self._pending_absorbs: List[tuple] = []  # guarded-by: gil-atomic-append
        if parent is not None:
            parent.attach(self)

    # -- registry tree ------------------------------------------------------

    def attach(self, child: "Metrics") -> None:
        """Attach ``child`` weakly: while alive it is merged into this
        registry's ``collect()``; when garbage-collected, its final samples
        are folded into this registry (the finalizer captures the child's
        data dicts, not the child), so a shut-down Cluster's telemetry
        survives into exports without the tree pinning dead components."""
        with self._lock:
            self._children = [r for r in self._children if r() is not None]
            self._children.append(weakref.ref(child))
        weakref.finalize(
            child, self._pending_absorbs.append,
            (child._counters, child._gauges, child._histograms,
             dict(child._const_labels)),
        )

    def detach(self, child: "Metrics") -> None:
        with self._lock:
            self._children = [
                r for r in self._children
                if r() is not None and r() is not child
            ]

    def _drain_absorbed(self) -> None:
        """Fold any dead children's queued samples into this registry.
        Called from every read/collect path (never from GC) so absorbed
        telemetry is visible by the time anyone looks."""
        while self._pending_absorbs:
            try:
                counters, gauges, hists, const = self._pending_absorbs.pop(0)
            except IndexError:  # pragma: no cover - concurrent drain
                break
            self._absorb(counters, gauges, hists, const)

    def _absorb(self, counters: Dict, gauges: Dict, hists: Dict,
                const: Dict[str, str]) -> None:
        """Fold a dead child's samples into this registry, const labels
        applied (the child's lock is irrelevant -- nothing else references
        its dicts anymore)."""
        with self._lock:
            for (name, labels), value in counters.items():
                key = (name, tuple(sorted({**const, **dict(labels)}.items())))
                self._counters[key] = self._counters.get(key, 0) + value
            for (name, labels), value in gauges.items():
                key = (name, tuple(sorted({**const, **dict(labels)}.items())))
                self._gauges[key] = value
            for (name, labels), hist in hists.items():
                key = (name, tuple(sorted({**const, **dict(labels)}.items())))
                mine = self._histograms.get(key)
                if mine is None:
                    self._histograms[key] = hist.copy()
                else:
                    mine.merge(hist)

    # -- recording ----------------------------------------------------------

    def incr(self, name: str, amount: int = 1, **labels: object) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + amount

    def set_gauge(self, name: str, value: float, **labels: object) -> None:
        with self._lock:
            self._gauges[(name, _label_key(labels))] = value

    def observe(self, name: str, value: float,
                buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_MS,
                **labels: object) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            hist = self._histograms.get(key)
            if hist is None:
                hist = self._histograms[key] = Histogram(buckets)
            hist.observe(value)

    # -- reading ------------------------------------------------------------

    def get(self, name: str, **labels: object) -> int:
        """Exact ``(name, labels)`` counter; with no labels, the sum over
        every label set of ``name`` (so legacy unlabeled reads keep working
        after a call site gains labels)."""
        self._drain_absorbed()
        with self._lock:
            if labels:
                return self._counters.get((name, _label_key(labels)), 0)
            return sum(
                v for (n, _), v in self._counters.items() if n == name
            )

    def get_gauge(self, name: str, **labels: object) -> Optional[float]:
        self._drain_absorbed()
        with self._lock:
            return self._gauges.get((name, _label_key(labels)))

    def histogram(self, name: str, **labels: object) -> Optional[Dict[str, object]]:
        """Merged snapshot of ``name`` over this registry AND its attached
        children; ``labels`` filter as a subset (``plane="sim"`` matches any
        series also carrying node/other labels). None if never observed."""
        want = {k: str(v) for k, v in labels.items()}
        merged: Optional[Histogram] = None
        for kind, n, sample_labels, value in self.collect():
            if kind != "histogram" or n != name:
                continue
            if any(sample_labels.get(k) != v for k, v in want.items()):
                continue
            if merged is None:
                merged = value.copy()
            else:
                merged.merge(value)
        return merged.snapshot() if merged is not None else None

    def snapshot(self) -> Dict[str, int]:
        """Flat counter view of THIS registry (children excluded): unlabeled
        counters keep their bare names, labeled ones render as
        ``name{k=v,...}``. Existing consumers that parse dotted names (e.g.
        experiments/message_load.py over ``messages.*``) are unaffected."""
        self._drain_absorbed()
        with self._lock:
            return {
                _render(name, labels): value
                for (name, labels), value in self._counters.items()
            }

    def gauges(self) -> Dict[str, float]:
        self._drain_absorbed()
        with self._lock:
            return {
                _render(name, labels): value
                for (name, labels), value in self._gauges.items()
            }

    def histograms(self) -> Dict[str, Dict[str, object]]:
        self._drain_absorbed()
        with self._lock:
            return {
                _render(name, labels): hist.snapshot()
                for (name, labels), hist in self._histograms.items()
            }

    def reset(self) -> None:
        """Atomically clear this registry's own series (children keep
        theirs: they belong to live components). Queued dead-child samples
        are discarded too -- reset means a clean slate."""
        del self._pending_absorbs[:]
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._children = [r for r in self._children if r() is not None]

    # -- export -------------------------------------------------------------

    def collect(self) -> List[Tuple[str, str, Dict[str, str], object]]:
        """Merged samples of this registry and every live child:
        ``(kind, name, labels, value)`` with kind in counter/gauge/histogram
        and const labels folded into each sample's labels."""
        self._drain_absorbed()
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: h.copy() for k, h in self._histograms.items()}
            children = [r() for r in self._children]
        const = self._const_labels
        out: List[Tuple[str, str, Dict[str, str], object]] = []
        for (name, labels), value in counters.items():
            out.append(("counter", name, {**const, **dict(labels)}, value))
        for (name, labels), value in gauges.items():
            out.append(("gauge", name, {**const, **dict(labels)}, value))
        for (name, labels), hist in hists.items():
            out.append(("histogram", name, {**const, **dict(labels)}, hist))
        for child in children:
            if child is not None:
                for kind, name, labels, value in child.collect():
                    out.append((kind, name, {**const, **labels}, value))
        return out


class NullMetrics(Metrics):
    """No-op registry: the telemetry-overhead baseline (never attaches to
    the global tree, records nothing)."""

    def __init__(self) -> None:  # noqa: super-init intentional
        super().__init__()

    def incr(self, name: str, amount: int = 1, **labels: object) -> None:
        pass

    def set_gauge(self, name: str, value: float, **labels: object) -> None:
        pass

    def observe(self, name: str, value: float,
                buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_MS,
                **labels: object) -> None:
        pass


# Process-wide default registry for components that outlive any one Cluster:
# the nemesis fault plane (faults.py) counts injected faults here unless
# given a registry ("nemesis_*" counters), the retry combinator counts
# "retry_*" when handed one, and per-instance registries attach here so
# exporters see every plane. Tests snapshot/reset it around a run.
_GLOBAL_METRICS = Metrics()


def global_metrics() -> Metrics:
    return _GLOBAL_METRICS


# --------------------------------------------------------------------------- #
# Metric history rings
# --------------------------------------------------------------------------- #

DEFAULT_HISTORY_CAPACITY = 128
DEFAULT_HISTORY_INTERVAL_S = 1.0


class MetricsHistory:
    """Bounded fixed-interval snapshot ring over a ``Metrics`` registry tree.

    Point-in-time registries answer "what is the value now"; the history
    ring answers "what was it over the last while" without an external
    scraper. ``maybe_snapshot`` is called opportunistically from whatever
    loop the owner already runs (the sim dispatch loop, a service timer, a
    test); it records at most one snapshot per ``interval_s``. Each
    snapshot captures every counter/gauge sample of ``collect()`` plus each
    histogram's (count, sum) -- enough to reconstruct rates and means per
    interval without shipping full bucket vectors.

    Retention is bounded AND downsampled: the ring holds at most
    ``capacity`` snapshots, and on overflow the oldest half is decimated
    (every other entry dropped), so recent history keeps full resolution
    while older history coarsens geometrically instead of falling off a
    cliff. A ring that snapshots forever stays within
    [3/4 * capacity, capacity] entries.

    Lock order: ``collect()`` runs OUTSIDE the ring lock, so this class
    adds no ``MetricsHistory._lock -> Metrics._lock`` edge.
    """

    def __init__(self, metrics: Optional[Metrics] = None,
                 interval_s: float = DEFAULT_HISTORY_INTERVAL_S,
                 capacity: int = DEFAULT_HISTORY_CAPACITY) -> None:
        self._metrics = metrics if metrics is not None else global_metrics()
        self.interval_s = max(float(interval_s), 0.0)
        self.capacity = max(int(capacity), 4)
        self._lock = make_lock("MetricsHistory._lock")
        self._snaps: List[Dict[str, object]] = []
        self._last_ts: Optional[float] = None
        # per-instance monotonic snapshot stamp: strictly increasing within
        # one ring's lifetime, restarting at 1 when a restarted node builds
        # a fresh ring -- the reset signal profiling/scrape.py splits
        # series on (a restarted node's clock may replay earlier ts_s)
        self._seq = itertools.count(1)

    def __len__(self) -> int:
        with self._lock:
            return len(self._snaps)

    def maybe_snapshot(self, now_s: Optional[float] = None) -> bool:
        """Record a snapshot iff at least ``interval_s`` elapsed since the
        last one (first call always records). Returns whether it did."""
        now = float(now_s) if now_s is not None else time.time()
        with self._lock:
            last = self._last_ts
        if last is not None and now - last < self.interval_s:
            return False
        self.snapshot(now)
        return True

    def snapshot(self, now_s: Optional[float] = None) -> Dict[str, object]:
        """Unconditionally record one snapshot of the registry tree."""
        now = float(now_s) if now_s is not None else time.time()
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        hists: Dict[str, List[float]] = {}
        for kind, name, labels, value in self._metrics.collect():
            rendered = _render(name, tuple(sorted(labels.items())))
            if kind == "counter":
                counters[rendered] = counters.get(rendered, 0) + value
            elif kind == "gauge":
                gauges[rendered] = value
            elif kind == "histogram":
                prev = hists.get(rendered)
                if prev is None:
                    hists[rendered] = [value.count, value.sum]
                else:
                    prev[0] += value.count
                    prev[1] += value.sum
        snap: Dict[str, object] = {
            "ts_s": now,
            "seq": next(self._seq),
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
        }
        with self._lock:
            self._snaps.append(snap)
            self._last_ts = now
            if len(self._snaps) >= self.capacity:
                self._downsample_locked()
        self._metrics.incr("profile.history_snapshots")
        return snap

    def _downsample_locked(self) -> None:
        """Decimate the oldest half in place (caller holds ``_lock``)."""
        half = len(self._snaps) // 2
        self._snaps[:half] = self._snaps[:half][::2]

    # -- reading ------------------------------------------------------------

    def entries(self) -> List[Dict[str, object]]:
        with self._lock:
            return list(self._snaps)

    def series(self, name: str) -> List[Tuple[float, float]]:
        """(ts_s, value) timeseries of one rendered series name, searched
        across counters, then gauges, then histogram counts. Snapshots in
        which the series did not yet exist are skipped."""
        out: List[Tuple[float, float]] = []
        for snap in self.entries():
            for table, pick in (("counters", None), ("gauges", None),
                                ("histograms", 0)):
                value = snap[table].get(name)  # type: ignore[union-attr]
                if value is not None:
                    out.append((
                        snap["ts_s"],  # type: ignore[arg-type]
                        float(value[pick] if pick is not None else value),
                    ))
                    break
        return out

    def reset(self) -> None:
        with self._lock:
            self._snaps.clear()
            self._last_ts = None

    # -- wire ---------------------------------------------------------------

    def to_wire(self, n: Optional[int] = None) -> Tuple[str, ...]:
        """The ring's tail as sorted-key JSON lines: the form
        ``ClusterStatusResponse.history`` carries on both transports."""
        entries = self.entries()
        if n is not None:
            entries = entries[-n:]
        return tuple(
            json.dumps(snap, sort_keys=True, default=str)
            for snap in entries
        )

    @staticmethod
    def from_wire(lines: Tuple[str, ...]) -> List[Dict[str, object]]:
        """Parse ``to_wire`` output back into snapshot dicts (malformed
        lines are skipped -- a truncated scrape never breaks assembly)."""
        out: List[Dict[str, object]] = []
        for line in lines:
            try:
                snap = json.loads(line)
            except (ValueError, TypeError):
                continue
            if isinstance(snap, dict) and "ts_s" in snap:
                out.append(snap)
        return out


# --------------------------------------------------------------------------- #
# Tracing
# --------------------------------------------------------------------------- #

_SPAN_IDS = itertools.count(1)
_SPAN_ID_LOCK = make_lock("observability._SPAN_ID_LOCK")

# One process-wide current-span so nesting works across tracer instances
# (e.g. a fault-plane event inside a protocol-plane span): each task/thread
# context carries its own value.
_CURRENT_SPAN: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "rapid_tpu_current_span", default=None
)


def _next_span_id() -> int:
    with _SPAN_ID_LOCK:
        return next(_SPAN_IDS)


@dataclass(frozen=True)
class TraceContext:
    """The cross-node trace coordinates a message carries on the wire.

    ``trace_id`` names the whole causal chain (minted by the root span on
    the node that detected the churn); ``parent_span_id`` is the sending
    side's span, so the receiving side's ``remote_span`` parents under it;
    ``origin`` is the sender's track (its address) for display; ``flags``
    is reserved (0 today -- a sampling bit later). Serialized as a compact
    4-list (msgpack ``__tc`` key / proto ``traceCtx`` message)."""

    trace_id: int
    parent_span_id: int
    origin: str = ""
    flags: int = 0

    def to_wire(self) -> List[object]:
        return [self.trace_id, self.parent_span_id, self.origin, self.flags]

    @classmethod
    def from_wire(cls, raw: object) -> Optional["TraceContext"]:
        try:
            trace_id, parent_span_id, origin, flags = raw  # type: ignore[misc]
            return cls(int(trace_id), int(parent_span_id), str(origin),
                       int(flags))
        except (TypeError, ValueError):
            return None  # malformed context never breaks message handling


# Messages are frozen dataclasses; the trace context rides as a sidecar
# attribute (object.__setattr__) so it stays invisible to dataclass fields,
# equality, hashing, and the codec's field walk -- old peers simply never
# see it.
_TRACE_CTX_ATTR = "trace_ctx"


def stamp_trace_context(msg: object, ctx: Optional[TraceContext]) -> object:
    if ctx is not None:
        try:
            object.__setattr__(msg, _TRACE_CTX_ATTR, ctx)
        except (AttributeError, TypeError):
            pass  # slotted/immutable object: carriage degrades to none
    return msg


def trace_context_of(msg: object) -> Optional[TraceContext]:
    ctx = getattr(msg, _TRACE_CTX_ATTR, None)
    return ctx if isinstance(ctx, TraceContext) else None


def current_trace_context(origin: str = "") -> Optional[TraceContext]:
    """TraceContext for the ambient span (None outside any span): what a
    send site stamps on an outgoing message unless it has an explicit
    context of its own."""
    cur = _CURRENT_SPAN.get()
    if cur is None:
        return None
    return TraceContext(
        trace_id=cur.trace_id or cur.span_id,
        parent_span_id=cur.span_id,
        origin=origin or cur.track,
    )


@dataclass
class Span:
    name: str
    wall_start_s: float
    wall_end_s: float = 0.0
    virtual_start_ms: Optional[int] = None
    virtual_end_ms: Optional[int] = None
    attrs: Dict[str, object] = field(default_factory=dict)
    span_id: int = 0
    parent_id: Optional[int] = None
    plane: str = "protocol"
    track: str = "main"
    trace_id: int = 0

    @property
    def wall_ms(self) -> float:
        return (self.wall_end_s - self.wall_start_s) * 1000.0


DEFAULT_MAX_SPANS = 8192


class Tracer:
    """Span recorder with a bounded ring buffer.

    ``spans`` is the ring (oldest evicted first; ``dropped`` counts
    evictions). ``parent`` attaches this tracer (weakly) to another one so
    ``collect_spans()`` on the parent -- and therefore the Chrome-trace
    exporter -- sees every attached plane on one timeline. ``plane``/``track``
    stamp each span for the exporter's process/thread grouping."""

    def __init__(self, max_spans: int = DEFAULT_MAX_SPANS,
                 parent: Optional["Tracer"] = None,
                 plane: str = "protocol", track: str = "main") -> None:
        self.spans: List[Span] = []
        self._dropped_box = [0]  # boxed so the parent's finalizer sees it
        self.plane = plane
        self.track = track
        self._max_spans = max_spans
        self._lock = make_lock("Tracer._lock")
        self._children: List["weakref.ref[Tracer]"] = []
        # dead children's (spans, dropped_box), appended by GC finalizers --
        # lock-free on purpose: cyclic GC can fire inside this tracer's own
        # locked sections, so a lock-taking finalizer would self-deadlock.
        self._pending_absorbs: List[tuple] = []  # guarded-by: gil-atomic-append
        if parent is not None:
            parent.attach(self)

    @property
    def dropped(self) -> int:
        self._drain_absorbed()
        return self._dropped_box[0]

    # -- tracer tree --------------------------------------------------------

    def attach(self, child: "Tracer") -> None:
        """Attach ``child`` weakly; when it is garbage-collected its spans
        fold into this tracer's (bounded) ring, so a shut-down component's
        trace survives into exports."""
        with self._lock:
            self._children = [r for r in self._children if r() is not None]
            self._children.append(weakref.ref(child))
        weakref.finalize(
            child, self._pending_absorbs.append,
            (child.spans, child._dropped_box),
        )

    def _drain_absorbed(self) -> None:
        """Fold dead children's queued spans into the ring (called from the
        read paths, never from GC)."""
        while self._pending_absorbs:
            try:
                spans, dropped_box = self._pending_absorbs.pop(0)
            except IndexError:  # pragma: no cover - concurrent drain
                break
            for s in spans:
                self._append(s)
            with self._lock:
                self._dropped_box[0] += dropped_box[0]

    # -- recording ----------------------------------------------------------

    def _new_span(self, name: str, virtual_ms: Optional[int],
                  attrs: Dict[str, object]) -> Span:
        parent = _CURRENT_SPAN.get()
        span_id = _next_span_id()
        return Span(
            name=name,
            wall_start_s=time.perf_counter(),
            virtual_start_ms=virtual_ms,
            attrs=attrs,
            span_id=span_id,
            parent_id=parent.span_id if parent is not None else None,
            plane=self.plane,
            track=self.track,
            # roots mint the trace id (their own span id: process-unique);
            # children inherit, so one id names the whole causal chain
            trace_id=(
                (parent.trace_id or parent.span_id)
                if parent is not None
                else span_id
            ),
        )

    def _append(self, s: Span) -> None:
        with self._lock:
            if self._max_spans > 0 and len(self.spans) >= self._max_spans:
                self.spans.pop(0)
                self._dropped_box[0] += 1
            self.spans.append(s)

    @contextlib.contextmanager
    def span(self, name: str, virtual_ms: Optional[int] = None,
             **attrs: object) -> Iterator[Span]:
        s = self._new_span(name, virtual_ms, dict(attrs))
        token = _CURRENT_SPAN.set(s)
        try:
            yield s
        finally:
            _CURRENT_SPAN.reset(token)
            s.wall_end_s = time.perf_counter()
            self._append(s)

    def begin(self, name: str, virtual_ms: Optional[int] = None,
              **attrs: object) -> Span:
        """Non-contextmanager start (paired with ``end``), for spans whose
        close site is far from their open site (e.g. view-change application
        that returns mid-function)."""
        return self._new_span(name, virtual_ms, dict(attrs))

    def end(self, s: Span, virtual_ms: Optional[int] = None) -> None:
        s.wall_end_s = time.perf_counter()
        if virtual_ms is not None:
            s.virtual_end_ms = virtual_ms
        self._append(s)

    def event(self, name: str, virtual_ms: Optional[int] = None,
              **attrs: object) -> Span:
        """Zero-duration instant (still parented under the current span)."""
        s = self._new_span(name, virtual_ms, dict(attrs))
        s.wall_end_s = s.wall_start_s
        s.virtual_end_ms = virtual_ms
        self._append(s)
        return s

    # -- cross-node propagation ---------------------------------------------

    def inject(self) -> Optional[TraceContext]:
        """The context an outgoing message should carry: the ambient span's
        coordinates with this tracer's track as the origin (None outside
        any span -- unsolicited sends stay traceless)."""
        return current_trace_context(origin=self.track)

    @staticmethod
    def extract(msg: object) -> Optional[TraceContext]:
        """The context an incoming message carried (None if it had none or
        the peer predates trace propagation)."""
        return trace_context_of(msg)

    @contextlib.contextmanager
    def remote_span(self, name: str, ctx: Optional[TraceContext] = None,
                    virtual_ms: Optional[int] = None,
                    **attrs: object) -> Iterator[Span]:
        """Like ``span`` but parented under a *remote* span: the receiving
        half of a cross-node edge. With ``ctx=None`` this degrades to a
        plain ``span`` (untraced peers cost nothing). The remote parent id
        may not resolve locally -- ``span_tree`` re-roots such spans and
        tools/tracecat.py stitches them back together by trace id, so a
        duplicated or reordered message can at worst repeat an edge, never
        corrupt parenting or accumulate state."""
        if ctx is not None and ctx.origin:
            attrs.setdefault("origin", ctx.origin)
        s = self._new_span(name, virtual_ms, dict(attrs))
        if ctx is not None:
            s.parent_id = ctx.parent_span_id
            s.trace_id = ctx.trace_id or s.trace_id
        token = _CURRENT_SPAN.set(s)
        try:
            yield s
        finally:
            _CURRENT_SPAN.reset(token)
            s.wall_end_s = time.perf_counter()
            self._append(s)

    # -- reading ------------------------------------------------------------

    def collect_spans(self) -> List[Span]:
        """This tracer's spans plus every live child's (exporter input)."""
        self._drain_absorbed()
        with self._lock:
            out = list(self.spans)
            children = [r() for r in self._children]
        for child in children:
            if child is not None:
                out.extend(child.collect_spans())
        return out

    def span_tree(self) -> Dict[Optional[int], List[Span]]:
        """parent span id -> children, root spans under None (a span whose
        parent was evicted from the ring is re-rooted under None)."""
        self._drain_absorbed()
        with self._lock:
            spans = list(self.spans)
        known = {s.span_id for s in spans}
        tree: Dict[Optional[int], List[Span]] = {}
        for s in spans:
            parent = s.parent_id if s.parent_id in known else None
            tree.setdefault(parent, []).append(s)
        return tree

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-name aggregate: count, total/mean wall ms."""
        self._drain_absorbed()
        with self._lock:
            spans = list(self.spans)
        agg: Dict[str, Dict[str, float]] = {}
        for s in spans:
            entry = agg.setdefault(s.name, {"count": 0, "total_ms": 0.0})
            entry["count"] += 1
            entry["total_ms"] += s.wall_ms
        for entry in agg.values():
            entry["mean_ms"] = entry["total_ms"] / entry["count"]
        return agg

    def reset(self) -> None:
        del self._pending_absorbs[:]
        with self._lock:
            self.spans.clear()
            self._dropped_box[0] = 0
            self._children = [r for r in self._children if r() is not None]


_GLOBAL_TRACER = Tracer(plane="global", track="global")


def global_tracer() -> Tracer:
    return _GLOBAL_TRACER


# --------------------------------------------------------------------------- #
# Derived latency: detection -> decision -> view-installed
# --------------------------------------------------------------------------- #


class StableViewTimer:
    """Per-view-change latency decomposition on a caller-supplied clock.

    ``detection(t)`` marks the first failure/join signal since the last view
    change (first call sticks); ``decision(t)`` marks when consensus decided
    (last call wins -- a parked decision re-applies later); ``view_installed``
    closes the cycle and records three histograms labeled with ``plane``:
    detection->decision, decision->view, and the headline
    ``time_to_stable_view_ms`` -- all on STABLE_VIEW_BUCKETS_MS so the
    simulator (virtual clock) and the protocol plane (scheduler clock)
    distributions are bucket-for-bucket comparable."""

    def __init__(self, metrics: Metrics, plane: str,
                 clock: Callable[[], int]) -> None:
        self._metrics = metrics
        self._plane = plane
        self._clock = clock
        self._detect_ms: Optional[int] = None  # guarded-by: protocol-thread
        self._decide_ms: Optional[int] = None  # guarded-by: protocol-thread

    def _now(self, now_ms: Optional[int]) -> int:
        return int(now_ms if now_ms is not None else self._clock())

    def detection(self, now_ms: Optional[int] = None) -> None:
        if self._detect_ms is None:
            self._detect_ms = self._now(now_ms)

    def decision(self, now_ms: Optional[int] = None) -> None:
        if self._detect_ms is not None:
            self._decide_ms = self._now(now_ms)

    def view_installed(self, now_ms: Optional[int] = None) -> None:
        detect, decide = self._detect_ms, self._decide_ms
        self._detect_ms = None
        self._decide_ms = None
        if detect is None:
            return  # e.g. the initial view: nothing was detected
        installed = self._now(now_ms)
        if decide is None:
            decide = installed
        self._metrics.observe(
            "latency.detection_to_decision_ms", decide - detect,
            buckets=STABLE_VIEW_BUCKETS_MS, plane=self._plane,
        )
        self._metrics.observe(
            "latency.decision_to_view_ms", installed - decide,
            buckets=STABLE_VIEW_BUCKETS_MS, plane=self._plane,
        )
        self._metrics.observe(
            "time_to_stable_view_ms", installed - detect,
            buckets=STABLE_VIEW_BUCKETS_MS, plane=self._plane,
        )


# --------------------------------------------------------------------------- #
# Flight recorder
# --------------------------------------------------------------------------- #

DEFAULT_JOURNAL_CAPACITY = 256


class FlightRecorder:
    """Bounded journal of the last N membership-relevant events on one node.

    A black box for post-mortems without a live scraper: each entry carries
    a monotonic sequence number, the event kind (from ``EVENT_CATALOG``),
    wall-clock seconds, the node's virtual/scheduler milliseconds, and a
    small detail dict. The deque drops the oldest entry on overflow, so a
    recorder can run forever; ``dropped`` counts those losses (and bills
    the ``journal.dropped_events`` counter when a metrics registry is
    attached) so evidence bundles report truncation instead of hiding it.
    When the forensics plane wires an HLC clock, each entry also carries an
    ``hlc`` coordinate (``[physical_ms, logical, incarnation]``) so skewed
    nodes' journals merge into one causal timeline. ``to_wire`` serializes
    the tail as JSON lines (the form both the msgpack codec and the proto
    wire carry in ``ClusterStatusResponse.journal``); ``dump`` writes the
    same lines to a file on crash/exit -- atomically, via tmp +
    ``os.replace``, so a crash mid-dump never leaves a torn journal."""

    def __init__(self, capacity: int = DEFAULT_JOURNAL_CAPACITY,
                 node: str = "",
                 clock: Optional[Callable[[], int]] = None,
                 hlc=None, metrics: Optional["Metrics"] = None) -> None:
        self.node = node
        self._clock = clock
        # duck-typed forensics.hlc.HlcClock (kept import-free: this module
        # is also loaded standalone by tools/check.py)
        self._hlc = hlc
        self._metrics = metrics
        self._seq = itertools.count(1)
        self._lock = make_lock("FlightRecorder._lock")
        # guarded-by: _lock
        self._dropped = 0
        self._events: "collections.deque[Dict[str, object]]" = (
            collections.deque(maxlen=max(1, capacity))
        )

    @property
    def capacity(self) -> int:
        return self._events.maxlen or 0

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def hlc_now(self):
        """The attached HLC clock's current stamp, or None when the
        forensics plane is off."""
        if self._hlc is None:
            return None
        try:
            return self._hlc.peek()
        except Exception:  # noqa: BLE001 -- forensics never loses the event
            return None

    def record(self, kind: str, virtual_ms: Optional[int] = None,
               **detail: object) -> Dict[str, object]:
        if virtual_ms is None and self._clock is not None:
            try:
                virtual_ms = int(self._clock())
            except Exception:  # noqa: BLE001 -- a dying clock never loses the event
                virtual_ms = None
        entry: Dict[str, object] = {
            "seq": next(self._seq),
            "kind": kind,
            "wall_s": time.time(),
            "virtual_ms": virtual_ms,
            "node": self.node,
            "detail": {str(k): v for k, v in detail.items()},
        }
        if self._hlc is not None:
            try:
                entry["hlc"] = self._hlc.now().to_wire()
            except Exception:  # noqa: BLE001 -- forensics never loses the event
                pass
        with self._lock:
            overflowing = len(self._events) == self._events.maxlen
            self._events.append(entry)
            if overflowing:
                self._dropped += 1
        if overflowing and self._metrics is not None:
            self._metrics.incr("journal.dropped_events")
        return entry

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def tail(self, n: Optional[int] = None) -> List[Dict[str, object]]:
        with self._lock:
            events = list(self._events)
        return events if n is None else events[-n:]

    def to_wire(self, n: Optional[int] = None) -> Tuple[str, ...]:
        return tuple(
            json.dumps(entry, sort_keys=True, default=str)
            for entry in self.tail(n)
        )

    def dump(self, path: str, n: Optional[int] = None) -> None:
        directory = os.path.dirname(os.path.abspath(path))
        fd, tmp = tempfile.mkstemp(prefix=".journal-", dir=directory)
        try:
            with os.fdopen(fd, "w") as fh:
                for line in self.to_wire(n):
                    fh.write(line + "\n")
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise


# --------------------------------------------------------------------------- #
# Exporters
# --------------------------------------------------------------------------- #

_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    sanitized = _PROM_NAME_RE.sub("_", name)
    return sanitized if sanitized.startswith("rapid_") else f"rapid_{sanitized}"


def _prom_label_value(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _prom_labels(labels: Dict[str, str], extra: Optional[Dict[str, str]] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        f'{k}="{_prom_label_value(v)}"' for k, v in sorted(merged.items())
    )
    return f"{{{inner}}}"


def _num(value: float) -> str:
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return str(value)


def prometheus_text(metrics: Optional[Metrics] = None) -> str:
    """Prometheus text exposition of a registry tree (default: the process
    global, i.e. every attached Cluster/Simulator plane merged). Counters
    gain ``_total``; histograms expand to ``_bucket``/``_sum``/``_count``
    with inclusive ``le`` edges. Output is sorted for determinism."""
    registry = metrics if metrics is not None else global_metrics()
    counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    hists: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Histogram] = {}
    for kind, name, labels, value in registry.collect():
        key = (name, tuple(sorted(labels.items())))
        if kind == "counter":
            counters[key] = counters.get(key, 0) + value
        elif kind == "gauge":
            gauges[key] = value
        elif kind == "histogram":
            if key in hists:
                hists[key].merge(value)
            else:
                hists[key] = value.copy()
    lines: List[str] = []
    typed: set = set()

    def type_line(name: str, kind: str) -> None:
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for (name, labels) in sorted(counters):
        prom = f"{_prom_name(name)}_total"
        type_line(prom, "counter")
        lines.append(
            f"{prom}{_prom_labels(dict(labels))} {_num(counters[(name, labels)])}"
        )
    for (name, labels) in sorted(gauges):
        prom = _prom_name(name)
        type_line(prom, "gauge")
        lines.append(
            f"{prom}{_prom_labels(dict(labels))} {_num(gauges[(name, labels)])}"
        )
    for (name, labels) in sorted(hists):
        hist = hists[(name, labels)]
        prom = _prom_name(name)
        type_line(prom, "histogram")
        cumulative = 0
        for edge, count in zip(hist.buckets, hist.counts):
            cumulative += count
            lines.append(
                f"{prom}_bucket"
                f"{_prom_labels(dict(labels), {'le': _num(float(edge))})} "
                f"{cumulative}"
            )
        cumulative += hist.counts[-1]
        lines.append(
            f"{prom}_bucket{_prom_labels(dict(labels), {'le': '+Inf'})} "
            f"{cumulative}"
        )
        lines.append(f"{prom}_sum{_prom_labels(dict(labels))} {_num(hist.sum)}")
        lines.append(f"{prom}_count{_prom_labels(dict(labels))} {hist.count}")
    return "\n".join(lines) + ("\n" if lines else "")


def chrome_trace(tracer: Optional[Tracer] = None) -> Dict[str, object]:
    """Chrome ``trace_event`` JSON (load in Perfetto / chrome://tracing).

    One process per plane; one thread per track (a protocol node's address,
    the simulator, ...). Spans carrying virtual timestamps are ADDITIONALLY
    plotted on a synthetic "virtual-time" process whose microseconds are
    virtual milliseconds x1000, so protocol time lines up across planes
    regardless of host wall-time jitter."""
    root = tracer if tracer is not None else global_tracer()
    spans = sorted(
        root.collect_spans(), key=lambda s: (s.wall_start_s, s.span_id)
    )
    planes = sorted({s.plane for s in spans})
    pid_of = {plane: i + 1 for i, plane in enumerate(planes)}
    virtual_pid = len(planes) + 1
    tracks = sorted({(s.plane, s.track) for s in spans})
    tid_of = {pt: i + 1 for i, pt in enumerate(tracks)}
    events: List[Dict[str, object]] = []
    for plane, pid in pid_of.items():
        events.append({
            "ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": plane},
        })
    has_virtual = any(s.virtual_start_ms is not None for s in spans)
    if has_virtual:
        events.append({
            "ph": "M", "pid": virtual_pid, "name": "process_name",
            "args": {"name": "virtual-time (ms)"},
        })
    for (plane, track), tid in tid_of.items():
        events.append({
            "ph": "M", "pid": pid_of[plane], "tid": tid,
            "name": "thread_name", "args": {"name": track},
        })
    t0 = min((s.wall_start_s for s in spans), default=0.0)
    for s in spans:
        args: Dict[str, object] = {str(k): v for k, v in s.attrs.items()}
        args["span_id"] = s.span_id
        if s.parent_id is not None:
            args["parent_id"] = s.parent_id
        if s.trace_id:
            args["trace_id"] = s.trace_id
        ts = int(round((s.wall_start_s - t0) * 1e6))
        dur = max(int(round((s.wall_end_s - s.wall_start_s) * 1e6)), 1)
        events.append({
            "name": s.name, "ph": "X", "pid": pid_of[s.plane],
            "tid": tid_of[(s.plane, s.track)], "ts": ts, "dur": dur,
            "args": args,
        })
        if s.virtual_start_ms is not None:
            v_end = (
                s.virtual_end_ms
                if s.virtual_end_ms is not None
                else s.virtual_start_ms
            )
            events.append({
                "name": s.name, "ph": "X", "pid": virtual_pid,
                "tid": tid_of[(s.plane, s.track)],
                "ts": int(s.virtual_start_ms) * 1000,
                "dur": max((int(v_end) - int(s.virtual_start_ms)) * 1000, 1),
                "args": args,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def json_snapshot(metrics: Optional[Metrics] = None,
                  tracer: Optional[Tracer] = None) -> Dict[str, object]:
    """Everything in one JSON-serializable dict: merged counter/gauge/
    histogram samples plus the span summary."""
    registry = metrics if metrics is not None else global_metrics()
    root = tracer if tracer is not None else global_tracer()
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Dict[str, object]] = {}
    for kind, name, labels, value in registry.collect():
        rendered = _render(name, tuple(sorted(labels.items())))
        if kind == "counter":
            counters[rendered] = counters.get(rendered, 0) + value
        elif kind == "gauge":
            gauges[rendered] = value
        elif kind == "histogram":
            hists[rendered] = value.snapshot()
    return {
        "counters": dict(sorted(counters.items())),
        "gauges": dict(sorted(gauges.items())),
        "histograms": dict(sorted(hists.items())),
        "spans": root.summary(),
        "spans_dropped": root.dropped,
    }


def write_prometheus(path: str, metrics: Optional[Metrics] = None) -> None:
    with open(path, "w") as fh:
        fh.write(prometheus_text(metrics))


def write_chrome_trace(path: str, tracer: Optional[Tracer] = None) -> None:
    with open(path, "w") as fh:
        json.dump(chrome_trace(tracer), fh)


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax/XLA profiler trace of everything inside the block."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
