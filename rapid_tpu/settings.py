"""Configuration knobs.

Reference: Settings.java:21-112 -- one mutable object implementing the narrow
per-consumer ISettings interfaces. Python needs no interface split; consumers
take the whole Settings (defaults cited per reference location).
"""

from __future__ import annotations

from dataclasses import dataclass, field

# Bounds for every adaptive-FD knob, keyed "adaptive_fd.<field>". A pure
# module-level literal so tools/check.py can lint it without importing this
# module (settings-catalog rule): every AdaptiveFdSettings field must have an
# entry here with its legal [min, max] range, and no stale keys may remain.
SETTINGS_CATALOG = {
    "adaptive_fd.enabled": {
        "min": 0, "max": 1,
        "doc": "kill switch: False preserves exact static-FD behavior",
    },
    "adaptive_fd.warmup_probes": {
        "min": 1, "max": 64,
        "doc": "RTT samples seeding the variance estimate before any "
               "suspicion can accrue (cold-start bias guard)",
    },
    "adaptive_fd.gray_confirm": {
        "min": 1, "max": 255,
        "doc": "consecutive outlier/missed probes before a gray alert",
    },
    "adaptive_fd.outlier_z": {
        "min": 1.0, "max": 16.0,
        "doc": "robust z-score vs the tier peer group marking one probe "
               "as an RTT outlier",
    },
    "adaptive_fd.min_spread_ms": {
        "min": 0.0, "max": 1000.0,
        "doc": "floor on the tier RTT spread so quiet LAN tiers cannot "
               "flag microsecond jitter as outliers",
    },
    "adaptive_fd.interval_floor_ms": {
        "min": 10, "max": 60000,
        "doc": "fastest adapted probe interval (suspect edges)",
    },
    "adaptive_fd.interval_ceiling_ms": {
        "min": 10, "max": 60000,
        "doc": "slowest adapted probe interval (healthy WAN edges)",
    },
    "adaptive_fd.threshold_floor": {
        "min": 1, "max": 255,
        "doc": "lowest adapted hard-failure threshold",
    },
    "adaptive_fd.threshold_ceiling": {
        "min": 1, "max": 255,
        "doc": "highest adapted hard-failure threshold",
    },
    "adaptive_fd.flush_floor_ms": {
        "min": 0, "max": 60000,
        "doc": "shortest adapted alert-batching flush window",
    },
    "adaptive_fd.flush_ceiling_ms": {
        "min": 0, "max": 60000,
        "doc": "longest adapted alert-batching flush window",
    },
    "profiling.enabled": {
        "min": 0, "max": 1,
        "doc": "kill switch: False runs the raw dispatch loop with zero "
               "profiling work on any path",
    },
    "profiling.sample_every_dispatches": {
        "min": 1, "max": 1000000,
        "doc": "shadow-profile one of every N device dispatches (1 = every "
               "dispatch; large N keeps steady-state overhead negligible)",
    },
    "profiling.history_interval_ms": {
        "min": 1, "max": 3600000,
        "doc": "minimum spacing between metric history-ring snapshots",
    },
    "profiling.history_capacity": {
        "min": 4, "max": 65536,
        "doc": "history-ring size before the oldest half is downsampled",
    },
    "profiling.overhead_budget_pct": {
        "min": 0.0, "max": 100.0,
        "doc": "overhead guard: instrumented warmed decision loop must stay "
               "within this percentage of the raw one",
    },
    "durability.enabled": {
        "min": 0, "max": 1,
        "doc": "kill switch: False keeps the in-memory store and the exact "
               "pre-durability decision loop",
    },
    "durability.fsync_policy": {
        "min": 0, "max": 2,
        "doc": "0 = never fsync (page cache only), 1 = fsync on explicit "
               "sync/checkpoint barriers, 2 = fsync every append",
    },
    "durability.segment_bytes": {
        "min": 4096, "max": 1073741824,
        "doc": "WAL segment rotation threshold; retention deletes whole "
               "segments below the last snapshot marker",
    },
    "durability.snapshot_every_records": {
        "min": 0, "max": 1048576,
        "doc": "auto-checkpoint after this many log records since the last "
               "snapshot (0 disables auto-checkpointing)",
    },
    "slo.enabled": {
        "min": 0, "max": 1,
        "doc": "kill switch: False attaches no SLO plane and reproduces the "
               "exact pre-SLO serving path",
    },
    "slo.bucket_ms": {
        "min": 1, "max": 3600000,
        "doc": "SLI aggregation time-bucket width; burn windows are sums of "
               "whole buckets, so this bounds alert-edge resolution",
    },
    "slo.window_scale": {
        "min": 0.000001, "max": 1000.0,
        "doc": "multiplier on the declared burn windows (1.0 = wall-scale "
               "SRE windows; small values shrink 5m/1h/6h/3d onto short "
               "virtual-time runs without changing the burn arithmetic)",
    },
    "slo.max_buckets": {
        "min": 16, "max": 1048576,
        "doc": "SLI ring capacity in time buckets; the oldest buckets are "
               "evicted beyond this, bounding memory for any run length",
    },
    "slo.clear_fraction": {
        "min": 0.1, "max": 1.0,
        "doc": "alert hysteresis: a firing burn alert clears only when both "
               "window burn rates drop below clear_fraction x the fire "
               "threshold (1.0 disables the hysteresis band)",
    },
    "forensics.enabled": {
        "min": 0, "max": 1,
        "doc": "kill switch: False attaches no HLC sidecar, no bundle "
               "triggers, no exit hooks, and reproduces the exact "
               "pre-forensics wire bytes",
    },
    "forensics.journal_capacity": {
        "min": 1, "max": 1048576,
        "doc": "FlightRecorder ring capacity in events; overflow drops the "
               "oldest entry and counts journal.dropped_events so bundles "
               "report truncation instead of hiding it",
    },
    "forensics.bundle_journal_tail": {
        "min": 1, "max": 65536,
        "doc": "journal entries captured per member in an evidence bundle",
    },
    "forensics.bundle_history_tail": {
        "min": 0, "max": 65536,
        "doc": "metric-history ring snapshots captured per member in an "
               "evidence bundle (0 skips the history carriage)",
    },
    "forensics.bundle_member_timeout_ms": {
        "min": 1, "max": 600000,
        "doc": "per-member status-RPC deadline during cluster-wide bundle "
               "capture; a member that misses it is marked unreachable and "
               "the capture proceeds without blocking",
    },
    "hierarchy.enabled": {
        "min": 0, "max": 1,
        "doc": "kill switch: False runs the flat single-level protocol and "
               "reproduces the exact pre-hierarchy wire bytes",
    },
    "hierarchy.cells": {
        "min": 0, "max": 65536,
        "doc": "number of cells for the rendezvous-hash fallback assignment "
               "(0 derives the cell count from the attached topology's "
               "zones, or 1 when there is no topology)",
    },
    "hierarchy.leaders_per_cell": {
        "min": 1, "max": 7,
        "doc": "size of each cell's deterministic leader set participating "
               "in the parent configuration (failover promotes the next "
               "member in leader order on an ordinary intra-cell view "
               "change)",
    },
    "hierarchy.parent_flush_ms": {
        "min": 0, "max": 60000,
        "doc": "flush window coalescing a leader's parent-level traffic "
               "into one MessageBatch per peer per window (0 sends each "
               "cell digest as its own frame)",
    },
    "hierarchy.parent_round_ms": {
        "min": 0, "max": 600000,
        "doc": "parent heartbeat period: every period each leader advances "
               "its parent round, re-announces its cell's digest to peer "
               "leaders, and ages out cells idle for eviction_rounds "
               "rounds -- this is what evicts a whole lost cell in O(1) "
               "rounds even when the survivors see no churn (0 disables "
               "the heartbeat; rounds then only advance on view changes)",
    },
    "hierarchy.eviction_rounds": {
        "min": 1, "max": 100,
        "doc": "parent rounds a foreign cell's row may stay idle before a "
               "leader drops it from the composed view (whole-cell loss "
               "detection horizon = eviction_rounds * parent_round_ms)",
    },
}


@dataclass(frozen=True)
class AdaptiveFdSettings:
    """Knobs for the adaptive gray-aware failure detector
    (monitoring/adaptive.py). Defaults are conservative: adaptation is off
    (``enabled=False`` reproduces the static PingPong detector bit-for-bit)
    and every controller output is clamped to the floors/ceilings below.
    Bounds live in SETTINGS_CATALOG (linted by tools/check.py)."""

    enabled: bool = False
    warmup_probes: int = 4
    gray_confirm: int = 3
    outlier_z: float = 4.0
    min_spread_ms: float = 5.0
    interval_floor_ms: int = 250
    interval_ceiling_ms: int = 4000
    threshold_floor: int = 3
    threshold_ceiling: int = 30
    flush_floor_ms: int = 10
    flush_ceiling_ms: int = 500

    def __post_init__(self) -> None:
        for key, value in (
            ("enabled", int(self.enabled)),
            ("warmup_probes", self.warmup_probes),
            ("gray_confirm", self.gray_confirm),
            ("outlier_z", self.outlier_z),
            ("min_spread_ms", self.min_spread_ms),
            ("interval_floor_ms", self.interval_floor_ms),
            ("interval_ceiling_ms", self.interval_ceiling_ms),
            ("threshold_floor", self.threshold_floor),
            ("threshold_ceiling", self.threshold_ceiling),
            ("flush_floor_ms", self.flush_floor_ms),
            ("flush_ceiling_ms", self.flush_ceiling_ms),
        ):
            bounds = SETTINGS_CATALOG[f"adaptive_fd.{key}"]
            assert bounds["min"] <= value <= bounds["max"], (
                f"adaptive_fd.{key}={value!r} outside "
                f"[{bounds['min']}, {bounds['max']}]"
            )
        assert self.interval_floor_ms <= self.interval_ceiling_ms
        assert self.threshold_floor <= self.threshold_ceiling
        assert self.flush_floor_ms <= self.flush_ceiling_ms


@dataclass(frozen=True)
class ProfilingSettings:
    """Knobs for the continuous profiling plane (profiling/). Defaults are
    conservative: profiling is off (``enabled=False`` leaves the dispatch
    loop untouched) and, when on, shadow attribution samples only one of
    every ``sample_every_dispatches`` dispatches so the steady-state loop
    stays within ``overhead_budget_pct`` of the raw one. Bounds live in
    SETTINGS_CATALOG (linted by tools/check.py)."""

    enabled: bool = False
    sample_every_dispatches: int = 16
    history_interval_ms: int = 1000
    history_capacity: int = 128
    overhead_budget_pct: float = 10.0

    def __post_init__(self) -> None:
        for key, value in (
            ("enabled", int(self.enabled)),
            ("sample_every_dispatches", self.sample_every_dispatches),
            ("history_interval_ms", self.history_interval_ms),
            ("history_capacity", self.history_capacity),
            ("overhead_budget_pct", self.overhead_budget_pct),
        ):
            bounds = SETTINGS_CATALOG[f"profiling.{key}"]
            assert bounds["min"] <= value <= bounds["max"], (
                f"profiling.{key}={value!r} outside "
                f"[{bounds['min']}, {bounds['max']}]"
            )


@dataclass(frozen=True)
class DurabilitySettings:
    """Knobs for the durability plane (durability/). Defaults are
    conservative: durability is off (``enabled=False`` keeps the in-memory
    store and the exact pre-durability decision loop) and, when on, fsync
    batching amortizes the stable-storage write path the way real Paxos
    deployments do. Bounds live in SETTINGS_CATALOG (linted by
    tools/check.py); the fsync policy is int-coded (0=never, 1=batch,
    2=always) so the catalog can bound it."""

    enabled: bool = False
    fsync_policy: int = 1
    segment_bytes: int = 1048576
    snapshot_every_records: int = 4096

    def __post_init__(self) -> None:
        for key, value in (
            ("enabled", int(self.enabled)),
            ("fsync_policy", self.fsync_policy),
            ("segment_bytes", self.segment_bytes),
            ("snapshot_every_records", self.snapshot_every_records),
        ):
            bounds = SETTINGS_CATALOG[f"durability.{key}"]
            assert bounds["min"] <= value <= bounds["max"], (
                f"durability.{key}={value!r} outside "
                f"[{bounds['min']}, {bounds['max']}]"
            )


@dataclass(frozen=True)
class SLOSettings:
    """Knobs for the SLO plane (slo/). Defaults are conservative: the plane
    is off (``enabled=False`` attaches nothing to the serving path) and,
    when on, SLIs aggregate into fixed-width time buckets whose windowed
    sums drive the multi-window burn-rate alerts. ``window_scale`` maps the
    wall-scale SRE windows (5m/1h fast, 6h/3d slow) onto virtual-time runs;
    the burn arithmetic is scale-invariant so alerts fire at the same
    error-budget consumption either way. Bounds live in SETTINGS_CATALOG
    (linted by tools/check.py)."""

    enabled: bool = False
    bucket_ms: int = 1000
    window_scale: float = 1.0
    max_buckets: int = 4096
    clear_fraction: float = 0.9

    def __post_init__(self) -> None:
        for key, value in (
            ("enabled", int(self.enabled)),
            ("bucket_ms", self.bucket_ms),
            ("window_scale", self.window_scale),
            ("max_buckets", self.max_buckets),
            ("clear_fraction", self.clear_fraction),
        ):
            bounds = SETTINGS_CATALOG[f"slo.{key}"]
            assert bounds["min"] <= value <= bounds["max"], (
                f"slo.{key}={value!r} outside "
                f"[{bounds['min']}, {bounds['max']}]"
            )


@dataclass(frozen=True)
class ForensicsSettings:
    """Knobs for the forensics plane (forensics/). Defaults are
    conservative: the plane is off (``enabled=False`` attaches no HLC
    sidecar and reproduces the exact pre-forensics wire bytes) and, when
    on, outbound messages carry hybrid-logical-clock stamps, journal
    entries gain HLC coordinates, and evidence bundles capture bounded
    tails from every reachable member. Bounds live in SETTINGS_CATALOG
    (linted by tools/check.py)."""

    enabled: bool = False
    journal_capacity: int = 256
    bundle_journal_tail: int = 128
    bundle_history_tail: int = 32
    bundle_member_timeout_ms: int = 2000

    def __post_init__(self) -> None:
        for key, value in (
            ("enabled", int(self.enabled)),
            ("journal_capacity", self.journal_capacity),
            ("bundle_journal_tail", self.bundle_journal_tail),
            ("bundle_history_tail", self.bundle_history_tail),
            ("bundle_member_timeout_ms", self.bundle_member_timeout_ms),
        ):
            bounds = SETTINGS_CATALOG[f"forensics.{key}"]
            assert bounds["min"] <= value <= bounds["max"], (
                f"forensics.{key}={value!r} outside "
                f"[{bounds['min']}, {bounds['max']}]"
            )


@dataclass(frozen=True)
class HierarchySettings:
    """Knobs for the hierarchy plane (hierarchy/). Defaults are
    conservative: the plane is off (``enabled=False`` runs the flat
    single-level protocol and reproduces the exact pre-hierarchy wire
    bytes) and, when on, the membership splits into deterministic cells
    that each run Rapid internally while the cells' leader sets agree on
    the composed global view, so cross-cell churn costs O(cells) instead
    of O(members). Bounds live in SETTINGS_CATALOG (linted by
    tools/check.py)."""

    enabled: bool = False
    cells: int = 0
    leaders_per_cell: int = 1
    parent_flush_ms: int = 50
    parent_round_ms: int = 1000
    eviction_rounds: int = 3

    def __post_init__(self) -> None:
        for key, value in (
            ("enabled", int(self.enabled)),
            ("cells", self.cells),
            ("leaders_per_cell", self.leaders_per_cell),
            ("parent_flush_ms", self.parent_flush_ms),
            ("parent_round_ms", self.parent_round_ms),
            ("eviction_rounds", self.eviction_rounds),
        ):
            bounds = SETTINGS_CATALOG[f"hierarchy.{key}"]
            assert bounds["min"] <= value <= bounds["max"], (
                f"hierarchy.{key}={value!r} outside "
                f"[{bounds['min']}, {bounds['max']}]"
            )


@dataclass
class Settings:
    # Transport timeouts/retries (GrpcClient.java:55-59)
    message_timeout_ms: int = 1000
    join_message_timeout_ms: int = 5000
    probe_message_timeout_ms: int = 1000
    message_retries: int = 5

    # Retry backoff between attempts (messaging/retries.py). The reference
    # resubscribes immediately (Retries.java:44-91), which the 0 default
    # preserves; a nonzero base delay turns on capped exponential backoff
    # with the chosen jitter discipline, spaced through the scheduler seam
    # so virtual-time runs stay deterministic.
    retry_base_delay_ms: int = 0
    retry_max_delay_ms: int = 4000
    retry_jitter: str = "decorrelated"

    # Dial backoff at the transport's connect seam (messaging/tcp.py).
    # A peer whose dial failed is gated behind a decorrelated-jitter delay
    # (base..max, the retries.py discipline) so a crashed peer costs one
    # pending dial per window instead of a connect-syscall storm; the gate
    # epoch resets every dial_deadline_ms so a long-dead peer still gets
    # rate-limited fresh dials (it may have rebooted).
    dial_backoff_base_ms: int = 50
    dial_backoff_max_ms: int = 1000
    dial_deadline_ms: int = 30000

    # Protocol engine (MembershipService.java:75-77)
    failure_detector_interval_ms: int = 1000
    batching_window_ms: int = 100

    # Broadcast flush window (messaging/unicast.py, messaging/gossip.py):
    # when > 0, per-recipient sends accumulate for this many ms and leave as
    # one MessageBatch envelope per peer per window -- a churn wave's alerts
    # ride one frame per peer. 0 preserves the legacy send-per-message path
    # (and exact virtual-time timing) on both broadcasters.
    broadcast_flush_window_ms: int = 0

    # Failure-detector policy, mirrored from the sim plane's SimConfig
    # (fd_policy/fd_window/fd_window_threshold) so both planes expose the
    # same knobs: "cumulative" = the reference's never-reset counter
    # (PingPongFailureDetector.java:69-77, FAILURE_THRESHOLD=10);
    # "windowed" = the paper's policy (atc-2018 section 6): faulty when
    # >= fd_window_threshold of the last fd_window probes failed.
    fd_policy: str = "cumulative"
    fd_failure_threshold: int = 10
    fd_window: int = 10
    fd_window_threshold: float = 0.4

    # Adaptive gray-aware failure detection (monitoring/adaptive.py):
    # per-tier RTT-outlier scoring with adapted probe intervals, failure
    # thresholds, and alert-flush windows. Off by default; the enabled
    # flag is the kill switch back to the static reference behavior.
    adaptive_fd: AdaptiveFdSettings = field(default_factory=AdaptiveFdSettings)

    # Continuous profiling plane (profiling/): per-phase device attribution
    # sampling, metric history rings, and the telemetry scrape surface. Off
    # by default; the enabled flag is the kill switch back to the raw,
    # uninstrumented dispatch loop.
    profiling: ProfilingSettings = field(default_factory=ProfilingSettings)

    # Durability plane (durability/): per-node write-ahead log + snapshot
    # crash recovery mounted under the handoff PartitionStore seam. Off by
    # default; the enabled flag is the kill switch back to the in-memory
    # store and the untouched decision loop.
    durability: DurabilitySettings = field(default_factory=DurabilitySettings)

    # SLO plane (slo/): online SLIs over the serving path, multi-window
    # burn-rate alerts over declared objectives, and churn-episode
    # attribution. Off by default; the enabled flag is the kill switch
    # back to the exact pre-SLO serving path.
    slo: SLOSettings = field(default_factory=SLOSettings)

    # Forensics plane (forensics/): hybrid logical clocks on the wire,
    # HLC-stamped journals, and automatic incident evidence bundles. Off
    # by default; the enabled flag is the kill switch back to the exact
    # pre-forensics wire bytes and journal shape.
    forensics: ForensicsSettings = field(default_factory=ForensicsSettings)

    # Hierarchy plane (hierarchy/): two-level cell-based membership --
    # cells run Rapid internally, cell leader sets agree on the composed
    # global view. Off by default; the enabled flag is the kill switch
    # back to the flat single-level protocol and the exact pre-hierarchy
    # wire bytes.
    hierarchy: HierarchySettings = field(default_factory=HierarchySettings)

    def __post_init__(self) -> None:
        assert self.fd_policy in ("cumulative", "windowed"), (
            f"fd_policy must be 'cumulative' or 'windowed', got "
            f"{self.fd_policy!r}"
        )
        assert self.retry_jitter in ("decorrelated", "none"), (
            f"retry_jitter must be 'decorrelated' or 'none', got "
            f"{self.retry_jitter!r}"
        )
        assert 0 <= self.retry_base_delay_ms <= self.retry_max_delay_ms
        assert 0 <= self.dial_backoff_base_ms <= self.dial_backoff_max_ms
        assert self.dial_deadline_ms >= 0
        assert self.broadcast_flush_window_ms >= 0

    # Consensus fallback (FastPaxos.java:46)
    consensus_fallback_base_delay_ms: int = 1000

    # Graceful leave wait (MembershipService.java:78)
    leave_message_timeout_ms: int = 1500

    def timeout_for(self, msg) -> int:
        """Per-message-type deadline (GrpcClient.getTimeoutForMessageMs,
        GrpcClient.java:194-203)."""
        from .types import JoinMessage, PreJoinMessage, ProbeMessage

        if isinstance(msg, (JoinMessage, PreJoinMessage)):
            return self.join_message_timeout_ms
        if isinstance(msg, ProbeMessage):
            return self.probe_message_timeout_ms
        return self.message_timeout_ms

    def retry_policy(self):
        """The backoff schedule these settings describe (RetryPolicy)."""
        from .messaging.retries import RetryPolicy

        return RetryPolicy(
            base_delay_ms=self.retry_base_delay_ms,
            max_delay_ms=self.retry_max_delay_ms,
            jitter=self.retry_jitter,
        )

    def deadline_for(self, msg) -> int:
        """Overall per-message-type send deadline across every retry: the
        budget the legacy immediate-resubscribe loop consumed in the worst
        case, now enforced explicitly however the attempts are spaced."""
        return self.timeout_for(msg) * (self.message_retries + 1)
