"""Configuration knobs.

Reference: Settings.java:21-112 -- one mutable object implementing the narrow
per-consumer ISettings interfaces. Python needs no interface split; consumers
take the whole Settings (defaults cited per reference location).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class Settings:
    # Transport timeouts/retries (GrpcClient.java:55-59)
    message_timeout_ms: int = 1000
    join_message_timeout_ms: int = 5000
    probe_message_timeout_ms: int = 1000
    message_retries: int = 5

    # Retry backoff between attempts (messaging/retries.py). The reference
    # resubscribes immediately (Retries.java:44-91), which the 0 default
    # preserves; a nonzero base delay turns on capped exponential backoff
    # with the chosen jitter discipline, spaced through the scheduler seam
    # so virtual-time runs stay deterministic.
    retry_base_delay_ms: int = 0
    retry_max_delay_ms: int = 4000
    retry_jitter: str = "decorrelated"

    # Dial backoff at the transport's connect seam (messaging/tcp.py).
    # A peer whose dial failed is gated behind a decorrelated-jitter delay
    # (base..max, the retries.py discipline) so a crashed peer costs one
    # pending dial per window instead of a connect-syscall storm; the gate
    # epoch resets every dial_deadline_ms so a long-dead peer still gets
    # rate-limited fresh dials (it may have rebooted).
    dial_backoff_base_ms: int = 50
    dial_backoff_max_ms: int = 1000
    dial_deadline_ms: int = 30000

    # Protocol engine (MembershipService.java:75-77)
    failure_detector_interval_ms: int = 1000
    batching_window_ms: int = 100

    # Broadcast flush window (messaging/unicast.py, messaging/gossip.py):
    # when > 0, per-recipient sends accumulate for this many ms and leave as
    # one MessageBatch envelope per peer per window -- a churn wave's alerts
    # ride one frame per peer. 0 preserves the legacy send-per-message path
    # (and exact virtual-time timing) on both broadcasters.
    broadcast_flush_window_ms: int = 0

    # Failure-detector policy, mirrored from the sim plane's SimConfig
    # (fd_policy/fd_window/fd_window_threshold) so both planes expose the
    # same knobs: "cumulative" = the reference's never-reset counter
    # (PingPongFailureDetector.java:69-77, FAILURE_THRESHOLD=10);
    # "windowed" = the paper's policy (atc-2018 section 6): faulty when
    # >= fd_window_threshold of the last fd_window probes failed.
    fd_policy: str = "cumulative"
    fd_failure_threshold: int = 10
    fd_window: int = 10
    fd_window_threshold: float = 0.4

    def __post_init__(self) -> None:
        assert self.fd_policy in ("cumulative", "windowed"), (
            f"fd_policy must be 'cumulative' or 'windowed', got "
            f"{self.fd_policy!r}"
        )
        assert self.retry_jitter in ("decorrelated", "none"), (
            f"retry_jitter must be 'decorrelated' or 'none', got "
            f"{self.retry_jitter!r}"
        )
        assert 0 <= self.retry_base_delay_ms <= self.retry_max_delay_ms
        assert 0 <= self.dial_backoff_base_ms <= self.dial_backoff_max_ms
        assert self.dial_deadline_ms >= 0
        assert self.broadcast_flush_window_ms >= 0

    # Consensus fallback (FastPaxos.java:46)
    consensus_fallback_base_delay_ms: int = 1000

    # Graceful leave wait (MembershipService.java:78)
    leave_message_timeout_ms: int = 1500

    def timeout_for(self, msg) -> int:
        """Per-message-type deadline (GrpcClient.getTimeoutForMessageMs,
        GrpcClient.java:194-203)."""
        from .types import JoinMessage, PreJoinMessage, ProbeMessage

        if isinstance(msg, (JoinMessage, PreJoinMessage)):
            return self.join_message_timeout_ms
        if isinstance(msg, ProbeMessage):
            return self.probe_message_timeout_ms
        return self.message_timeout_ms

    def retry_policy(self):
        """The backoff schedule these settings describe (RetryPolicy)."""
        from .messaging.retries import RetryPolicy

        return RetryPolicy(
            base_delay_ms=self.retry_base_delay_ms,
            max_delay_ms=self.retry_max_delay_ms,
            jitter=self.retry_jitter,
        )

    def deadline_for(self, msg) -> int:
        """Overall per-message-type send deadline across every retry: the
        budget the legacy immediate-resubscribe loop consumed in the worst
        case, now enforced explicitly however the attempts are spaced."""
        return self.timeout_for(msg) * (self.message_retries + 1)
