"""Configuration knobs.

Reference: Settings.java:21-112 -- one mutable object implementing the narrow
per-consumer ISettings interfaces. Python needs no interface split; consumers
take the whole Settings (defaults cited per reference location).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class Settings:
    # Transport timeouts/retries (GrpcClient.java:55-59)
    message_timeout_ms: int = 1000
    join_message_timeout_ms: int = 5000
    probe_message_timeout_ms: int = 1000
    message_retries: int = 5

    # Protocol engine (MembershipService.java:75-77)
    failure_detector_interval_ms: int = 1000
    batching_window_ms: int = 100

    # Failure-detector policy, mirrored from the sim plane's SimConfig
    # (fd_policy/fd_window/fd_window_threshold) so both planes expose the
    # same knobs: "cumulative" = the reference's never-reset counter
    # (PingPongFailureDetector.java:69-77, FAILURE_THRESHOLD=10);
    # "windowed" = the paper's policy (atc-2018 section 6): faulty when
    # >= fd_window_threshold of the last fd_window probes failed.
    fd_policy: str = "cumulative"
    fd_failure_threshold: int = 10
    fd_window: int = 10
    fd_window_threshold: float = 0.4

    def __post_init__(self) -> None:
        assert self.fd_policy in ("cumulative", "windowed"), (
            f"fd_policy must be 'cumulative' or 'windowed', got "
            f"{self.fd_policy!r}"
        )

    # Consensus fallback (FastPaxos.java:46)
    consensus_fallback_base_delay_ms: int = 1000

    # Graceful leave wait (MembershipService.java:78)
    leave_message_timeout_ms: int = 1500

    def timeout_for(self, msg) -> int:
        """Per-message-type deadline (GrpcClient.getTimeoutForMessageMs,
        GrpcClient.java:194-203)."""
        from .types import JoinMessage, PreJoinMessage, ProbeMessage

        if isinstance(msg, (JoinMessage, PreJoinMessage)):
            return self.join_message_timeout_ms
        if isinstance(msg, ProbeMessage):
            return self.probe_message_timeout_ms
        return self.message_timeout_ms
