"""ctypes bindings for the native host control plane (native/rapid_native.cpp).

Build with ``python -m rapid_tpu.native`` (or ``make -C native``). Every entry
point has a pure-numpy fallback (rapid_tpu.hashing / rapid_tpu.sim.topology),
so the framework works without the library; with it, ring construction for
100k endpoints drops from seconds to tens of milliseconds -- the cost that
gates how fast the simulator can apply view changes.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "librapid_native.so")

_lib: Optional[ctypes.CDLL] = None


def build(quiet: bool = False) -> str:
    """Compile the shared library with make/g++."""
    subprocess.run(
        ["make", "-C", _NATIVE_DIR],
        check=True,
        capture_output=quiet,
    )
    return _LIB_PATH


def _stale() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    src = os.path.join(_NATIVE_DIR, "rapid_native.cpp")
    try:
        return os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
    except OSError:
        return False


def _warn_if_stale() -> None:
    if os.path.exists(_LIB_PATH):
        import warnings

        warnings.warn(
            f"loading {_LIB_PATH} although its source is newer (rebuild "
            "unavailable); native results may not reflect source edits",
            RuntimeWarning,
            stacklevel=3,
        )


def load(auto_build: bool = True) -> Optional[ctypes.CDLL]:
    """Load the library, optionally building it on first use. Rebuilds when
    the source is newer than the binary so edits are never shadowed by a
    stale .so; if that rebuild is impossible (no toolchain) the stale binary
    is still loaded, but with a loud warning -- silently-stale native code
    must at least be visible. None if unavailable (callers fall back to
    numpy)."""
    global _lib
    if _lib is not None:
        return _lib
    if _stale():
        if not auto_build:
            # never build here: load the (possibly stale) binary if present
            if not os.path.exists(_LIB_PATH):
                return None
            _warn_if_stale()
        else:
            try:
                build(quiet=True)
            except Exception:  # noqa: BLE001 -- no toolchain: numpy fallback
                if not os.path.exists(_LIB_PATH):
                    return None
                _warn_if_stale()
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None

    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")

    lib.rapid_xxh64_batch.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int64, i64p, ctypes.c_uint64, u64p
    ]
    lib.rapid_endpoint_hash_batch.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int64, i64p, i64p, ctypes.c_uint64, u64p
    ]
    lib.rapid_ring_hashes.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int64, i64p, i64p, ctypes.c_int64, u64p
    ]
    lib.rapid_build_adjacency.argtypes = [
        u64p, u8p, ctypes.c_int64, ctypes.c_int64, i32p, i32p
    ]
    lib.rapid_config_fold.argtypes = [u64p, ctypes.c_int64]
    lib.rapid_config_fold.restype = ctypes.c_uint64
    _lib = lib
    return lib


def available() -> bool:
    return load(auto_build=True) is not None


# -- numpy-compatible wrappers ------------------------------------------------


def xxh64_batch(data: np.ndarray, lengths: np.ndarray, seed: int) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    data = np.ascontiguousarray(data, dtype=np.uint8)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    out = np.empty(data.shape[0], dtype=np.uint64)
    lib.rapid_xxh64_batch(
        data, data.shape[0], data.shape[1], lengths,
        ctypes.c_uint64(seed & (2**64 - 1)), out,
    )
    return out


def ring_hashes(
    hostnames: np.ndarray, lengths: np.ndarray, ports: np.ndarray, k: int
) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    hostnames = np.ascontiguousarray(hostnames, dtype=np.uint8)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    ports = np.ascontiguousarray(ports, dtype=np.int64)
    n = hostnames.shape[0]
    out = np.empty((k, n), dtype=np.uint64)
    lib.rapid_ring_hashes(
        hostnames, n, hostnames.shape[1], lengths, ports, k, out
    )
    return out


def build_adjacency(
    ring_hashes_arr: np.ndarray, active: np.ndarray
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    lib = load()
    if lib is None:
        return None
    k, capacity = ring_hashes_arr.shape
    ring_hashes_arr = np.ascontiguousarray(ring_hashes_arr, dtype=np.uint64)
    active_u8 = np.ascontiguousarray(active, dtype=np.uint8)
    base = np.tile(np.arange(capacity, dtype=np.int32)[:, None], (1, k))
    subjects = np.ascontiguousarray(base)
    observers = np.ascontiguousarray(base.copy())
    lib.rapid_build_adjacency(ring_hashes_arr, active_u8, capacity, k, subjects, observers)
    return subjects, observers


def config_fold(xs: np.ndarray) -> Optional[int]:
    """Chained configuration-id fold h=1; h=h*37+x (mod 2^64) over the
    already-interleaved element hashes; returns the Java-signed value."""
    lib = load()
    if lib is None:
        return None
    xs = np.ascontiguousarray(xs, dtype=np.uint64)
    total = lib.rapid_config_fold(xs, xs.shape[0])
    return int(np.uint64(total).astype(np.int64))


if __name__ == "__main__":
    path = build()
    print(f"built {path}")  # noqa: print-in-lib
    print("loadable:", available())  # noqa: print-in-lib
