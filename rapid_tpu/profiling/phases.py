"""Per-phase device attribution for the sim-plane round pipeline.

The production dispatch loop is untouched: attribution is a *shadow*
measurement. Every sampled dispatch, the profiler re-executes the current
round's computation through three jitted prefixes of ``sim.engine.step``
(non-donated, outputs discarded) and differences their wall times:

    fd_scan         = t(step_fd_scan)
    cut_detector    = t(step_cut_detector) - t(step_fd_scan)
    consensus_count = t(step)              - t(step_cut_detector)

so the three device phases sum to the measured full-step time by
construction (ROADMAP item 2's megakernel fusion needs exactly this
breakdown to know what to fuse). The fourth phase, ``host_transfer``, is
not shadowed: the driver times the real post-dispatch decision fetch
(``jitwatch.fetch("sim.decision_words", ...)``) and reports it here.

Overhead discipline: the prefixes are compiled at ``warm()`` time (never
inside a jitwatch timed window, so the bench's zero-steady-state-compile
pin holds), and sampling is 1-of-N dispatches
(``ProfilingSettings.sample_every_dispatches``), so the instrumented
warmed decision loop stays within ``overhead_budget_pct`` of the raw one
-- pinned by tests/test_profiling.py's overhead guard.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..observability import PROFILE_PHASE_BUCKETS_MS, Metrics, MetricsHistory
from ..runtime import jitwatch
from ..runtime.jitwatch import make_jit
from ..settings import ProfilingSettings
from ..sim.engine import step, step_cut_detector, step_fd_scan

DEVICE_PHASES = ("fd_scan", "cut_detector", "consensus_count")
PHASES = DEVICE_PHASES + ("host_transfer",)

# The shadow entry points: plain (non-donated) jits of the engine's phase
# prefixes -- the sampled state is still live in the production loop.
profile_fd_scan = make_jit(
    "sim.profile.fd_scan", step_fd_scan, static_argnums=(0, 3)
)
profile_cut_detector = make_jit(
    "sim.profile.cut_detector", step_cut_detector, static_argnums=(0, 3)
)
profile_full_step = make_jit(
    "sim.profile.full_step", step, static_argnums=(0, 3)
)

_PROFILE_FNS = (profile_fd_scan, profile_cut_detector, profile_full_step)


class PhaseProfiler:  # guarded-by: dispatch-thread
    """Sampled per-phase attribution plus the owning plane's history ring.

    One instance per Simulator (sim/driver.py ``enable_profiling``), driven
    entirely from the dispatch loop's thread. Phase times land in the
    ``profile.phase_ms`` histogram (labels: phase, plane) and accumulate in
    ``attribution()`` for direct assertions; ``history`` is the plane's
    MetricsHistory ring, ticked once per dispatch."""

    def __init__(self, metrics: Metrics,
                 settings: Optional[ProfilingSettings] = None,
                 plane: str = "sim") -> None:
        self.settings = (
            settings if settings is not None else ProfilingSettings(enabled=True)
        )
        self.metrics = metrics
        self.plane = plane
        self.samples = 0
        self.last_sample: Optional[Dict[str, float]] = None
        self._dispatches = 0
        self._totals: Dict[str, float] = {phase: 0.0 for phase in PHASES}
        self.history = MetricsHistory(
            metrics,
            interval_s=self.settings.history_interval_ms / 1000.0,
            capacity=self.settings.history_capacity,
        )

    @property
    def enabled(self) -> bool:
        return bool(self.settings.enabled)

    def should_sample(self) -> bool:
        """Advance the dispatch counter; True on 1 of every N dispatches."""
        if not self.enabled:
            return False
        self._dispatches += 1
        return (
            (self._dispatches - 1) % self.settings.sample_every_dispatches == 0
        )

    # -- measurement --------------------------------------------------------

    def _timed_ms(self, fn, config, state, inputs, random_loss: bool) -> float:
        t0 = time.perf_counter()
        out = fn(config, state, inputs, random_loss)
        jitwatch.drain("sim.profile.sample", out)
        return (time.perf_counter() - t0) * 1000.0

    def warm(self, config, state, inputs, random_loss: bool = False) -> None:
        """Compile (and first-run) every shadow prefix for this (config,
        shapes, random_loss) class, outside any timed window -- so no later
        sample ever compiles on a steady-state path."""
        for fn in _PROFILE_FNS:
            jitwatch.drain(
                "sim.profile.warm", fn(config, state, inputs, random_loss)
            )

    def sample(self, config, state, inputs, random_loss: bool = False,
               repeats: int = 1) -> Dict[str, float]:
        """One shadow attribution of the current round's computation.
        ``repeats`` takes the best-of-N per prefix (timing noise guard for
        assertions; the in-loop default is one shot)."""
        reps = max(1, int(repeats))
        t_fd = min(
            self._timed_ms(profile_fd_scan, config, state, inputs, random_loss)
            for _ in range(reps)
        )
        t_cut = min(
            self._timed_ms(
                profile_cut_detector, config, state, inputs, random_loss
            )
            for _ in range(reps)
        )
        t_full = min(
            self._timed_ms(profile_full_step, config, state, inputs, random_loss)
            for _ in range(reps)
        )
        phases = {
            "fd_scan": t_fd,
            "cut_detector": max(t_cut - t_fd, 0.0),
            "consensus_count": max(t_full - t_cut, 0.0),
        }
        for phase, ms in phases.items():
            self.metrics.observe(
                "profile.phase_ms", ms, buckets=PROFILE_PHASE_BUCKETS_MS,
                phase=phase, plane=self.plane,
            )
            self._totals[phase] += ms
        self.metrics.observe(
            "profile.step_ms", t_full, buckets=PROFILE_PHASE_BUCKETS_MS,
            plane=self.plane,
        )
        self.metrics.incr("profile.samples")
        self.samples += 1
        self.last_sample = dict(phases, step_ms=t_full)
        return self.last_sample

    def record_host_transfer(self, ms: float) -> None:
        """The real decision-fetch leg, timed by the driver per dispatch."""
        self.metrics.observe(
            "profile.phase_ms", ms, buckets=PROFILE_PHASE_BUCKETS_MS,
            phase="host_transfer", plane=self.plane,
        )
        self._totals["host_transfer"] += ms

    def tick_history(self, now_s: Optional[float] = None) -> bool:
        return self.history.maybe_snapshot(now_s)

    # -- reading ------------------------------------------------------------

    def attribution(self) -> Dict[str, float]:
        """Accumulated per-phase wall ms across every sample so far."""
        return dict(self._totals)
