"""The continuous profiling plane.

Three pieces ride the existing telemetry substrate (observability.py):

- ``PhaseProfiler`` (phases.py): sampled shadow attribution of the sim
  plane's device round pipeline -- wall time split into FD-scan /
  cut-detector / consensus-count / host-transfer phases via jitted phase
  prefixes of ``sim.engine.step``, differenced so the phases sum to the
  full step by construction. Off by default
  (``settings.ProfilingSettings.enabled`` is the kill switch); when on,
  only one of every N dispatches is sampled so the steady-state loop
  stays within the overhead budget.
- ``MetricsHistory`` (re-exported from observability.py): bounded,
  downsample-on-overflow snapshot rings giving every counter/gauge/
  histogram queryable recent history.
- ``cluster_timeseries`` (scrape.py): assembles the per-node history
  lines scraped off ``ClusterStatusResponse.history`` into a
  cluster-wide timeseries view (the form tools/statusz.py and
  tools/perfscope.py render).
"""

from ..observability import MetricsHistory
from .phases import DEVICE_PHASES, PHASES, PhaseProfiler
from .scrape import cluster_timeseries, merge_by_series, node_segments

__all__ = [
    "DEVICE_PHASES",
    "PHASES",
    "PhaseProfiler",
    "MetricsHistory",
    "cluster_timeseries",
    "merge_by_series",
    "node_segments",
]
