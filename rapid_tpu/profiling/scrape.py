"""Cluster-wide timeseries assembly from scraped status history lines.

Any member answers a ``ClusterStatusRequest`` with its history ring's tail
(``ClusterStatusResponse.history``, JSON lines -- the same carriage as the
flight-recorder journal). These helpers fold a set of such responses into
queryable views: per-node series maps (``cluster_timeseries``) and the
transposed per-series node map (``merge_by_series``) that tools/statusz.py
and tools/perfscope.py render."""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple

from ..observability import MetricsHistory

# node -> series name -> [(ts_s, value)]
ClusterSeries = Dict[str, Dict[str, List[Tuple[float, float]]]]


def node_segments(
    history_lines: Iterable[str],
) -> List[Dict[str, List[Tuple[float, float]]]]:
    """One node's scraped history lines -> one series map per process
    incarnation. A restart hands the node a fresh ring whose ``seq`` stamp
    restarts at 1 (and, under virtual time, whose clock may restart too);
    a seq -- or, for seq-less old lines, timestamp -- regression therefore
    marks a segment boundary. Points are sorted within a segment only:
    sorting across segments would interleave the incarnations into one
    zig-zag series."""
    segments: List[Dict[str, List[Tuple[float, float]]]] = []
    series: Dict[str, List[Tuple[float, float]]] = {}
    prev_seq: float = float("-inf")
    prev_ts: float = float("-inf")
    for snap in MetricsHistory.from_wire(tuple(history_lines)):
        try:
            ts = float(snap.get("ts_s", 0.0))
        except (TypeError, ValueError):
            continue
        raw_seq = snap.get("seq")
        try:
            seq = float(raw_seq) if raw_seq is not None else None
        except (TypeError, ValueError):
            seq = None
        reset = (seq is not None and seq <= prev_seq) or (
            seq is None and ts < prev_ts
        )
        if reset and series:
            segments.append(
                {name: sorted(points) for name, points in series.items()}
            )
            series = {}
        prev_seq = seq if seq is not None else float("-inf")
        prev_ts = ts
        for table in ("counters", "gauges"):
            rows = snap.get(table)
            if not isinstance(rows, dict):
                continue
            for name, value in rows.items():
                try:
                    series.setdefault(str(name), []).append((ts, float(value)))
                except (TypeError, ValueError):
                    continue
        hists = snap.get("histograms")
        if isinstance(hists, dict):
            for name, pair in hists.items():
                try:
                    count, total = pair
                    series.setdefault(f"{name}.count", []).append(
                        (ts, float(count))
                    )
                    series.setdefault(f"{name}.sum", []).append(
                        (ts, float(total))
                    )
                except (TypeError, ValueError):
                    continue
    if series:
        segments.append(
            {name: sorted(points) for name, points in series.items()}
        )
    return segments


def node_series(history_lines: Iterable[str]) -> Dict[str, List[Tuple[float, float]]]:
    """One node's scraped history lines -> series name -> points, segments
    concatenated in incarnation order (see ``node_segments``). Counters and
    gauges map to their values; each histogram contributes ``<name>.count``
    and ``<name>.sum`` series."""
    series: Dict[str, List[Tuple[float, float]]] = {}
    for segment in node_segments(history_lines):
        for name, points in segment.items():
            series.setdefault(name, []).extend(points)
    return series


def cluster_timeseries(statuses: Iterable[object]) -> ClusterSeries:
    """A set of ``ClusterStatusResponse``s -> node -> series -> points.
    Responses without history (old peers, profiling off) contribute an
    empty map; duplicate responses from one node keep the larger scrape."""
    out: ClusterSeries = {}
    for status in statuses:
        node = str(getattr(status, "sender", ""))
        lines = tuple(getattr(status, "history", ()) or ())
        series = node_series(lines)
        prev = out.get(node)
        if prev is None or sum(map(len, series.values())) > sum(
            map(len, prev.values())
        ):
            out[node] = series
    return out


def merge_by_series(cluster: ClusterSeries) -> Dict[str, Dict[str, List[Tuple[float, float]]]]:
    """Transpose: series name -> node -> points (the cross-node comparison
    view -- e.g. one ``rounds`` panel with a line per member)."""
    out: Dict[str, Dict[str, List[Tuple[float, float]]]] = {}
    for node, series in cluster.items():
        for name, points in series.items():
            out.setdefault(name, {})[node] = points
    return out
