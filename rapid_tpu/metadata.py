"""Per-node application metadata registry.

Reference: MetadataManager.java:38-69 -- immutable key->bytes tags per node,
shipped to joiners in JoinResponses; put-if-absent semantics.
"""

from __future__ import annotations

from typing import Dict, Mapping, Tuple

from .types import Endpoint

FrozenMetadata = Tuple[Tuple[str, bytes], ...]


class MetadataManager:
    def __init__(self) -> None:
        self._table: Dict[Endpoint, FrozenMetadata] = {}  # guarded-by: protocol-executor

    def get(self, node: Endpoint) -> FrozenMetadata:
        return self._table.get(node, ())

    def add_metadata(self, roles: Mapping[Endpoint, FrozenMetadata]) -> None:
        """put-if-absent per node (MetadataManager.java:47-55)."""
        for node, metadata in roles.items():
            self._table.setdefault(node, metadata)

    def remove_node(self, node: Endpoint) -> None:
        self._table.pop(node, None)

    def get_all_metadata(self) -> Dict[Endpoint, FrozenMetadata]:
        return dict(self._table)
