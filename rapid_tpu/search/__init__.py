"""Coverage-guided nemesis search over the fault-plan space -- "Jepsen in
a box" (ROADMAP item 4).

The pieces compose as a pipeline: :mod:`.generator` samples seeded
``FaultPlan`` specs within the builders' validity rules, :mod:`.runner`
executes one spec as a probe (serving fabric or device-plane simulator)
and extracts a coverage fingerprint (:mod:`.coverage`) plus invariant
verdicts (:mod:`.checkers`), :mod:`.hunt` drives a budgeted search that
biases generation toward unvisited coverage, and :mod:`.shrinker`
delta-debugs any violating plan down to a minimal corpus artifact.
"""

from .checkers import (
    InvariantViolation,
    check_config_parity,
    check_fingerprint_agreement,
    check_leader_agreement,
    check_linearizable_history,
    check_linearizable_single_client,
    check_view_agreement,
    ClientOp,
)

__all__ = [
    "ClientOp",
    "InvariantViolation",
    "check_config_parity",
    "check_fingerprint_agreement",
    "check_leader_agreement",
    "check_linearizable_history",
    "check_linearizable_single_client",
    "check_view_agreement",
]
