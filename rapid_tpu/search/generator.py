"""Seeded FaultPlan generator + mutator for the nemesis search.

Plans are handled as their ``FaultPlan.to_json`` dicts (specs), so the
generator, shrinker, and corpus files all speak the same format. Every
candidate is validated by actually building it through
``FaultPlan.from_json`` -- a sampled rule the builders reject (window
sanity, partition conflicts, parameter ranges) is resampled, never
emitted.

All randomness is ``random.Random`` seeded from ``(seed, purpose,
index)`` mixed through crc32, so the same seed produces the same plan
stream in every process regardless of hash randomization.
"""

from __future__ import annotations

import random
import zlib
from typing import List, Sequence

from ..faults import FaultPlan

# Every Rule subclass the generator can emit. tools/check.py lints this
# literal against the Rule subclasses defined in rapid_tpu/faults.py (the
# same sync discipline RULE_CATALOG enforces), so a new fault rule cannot
# silently stay unreachable by the search.
GEN_RULES = (
    "CellPartitionRule",
    "ClockSkewRule",
    "DelayRule",
    "DiskStallRule",
    "DropRule",
    "DuplicateRule",
    "FlipFlopRule",
    "LossyLinkRule",
    "PartitionRule",
    "ReorderRule",
    "RestartNodeRule",
    "SlowNodeRule",
    "TornWriteRule",
    "WireVersionRule",
)

HARNESSES = ("engine", "sim")


def _mix(*parts: object) -> int:
    return zlib.crc32("|".join(str(p) for p in parts).encode("utf-8"))


class PlanGenerator:
    """Samples fresh plan specs and mutates corpus members.

    ``harness="engine"`` targets the serving fabric (full rule algebra,
    Put/Get wire matches, occasional latency topologies); ``harness="sim"``
    emits only rules the device plane can compile (``_device_rules``) plus
    Put-wire serving rules the sim's serving nemesis understands -- the
    runner splits those two families before replay.
    """

    def __init__(self, seed: int, endpoints: Sequence[object],
                 horizon_ms: int, harness: str = "engine") -> None:
        assert harness in HARNESSES, harness
        self.seed = int(seed)
        self.endpoints = [str(ep) for ep in endpoints]
        self.horizon_ms = int(horizon_ms)
        self.harness = harness

    def _rng(self, purpose: str, index: int) -> random.Random:
        return random.Random(
            self.seed * 1_000_003 + _mix(self.harness, purpose, index)
        )

    # -- sampling ---------------------------------------------------------

    def fresh(self, index: int) -> dict:
        # fresh plans are deliberately sparse (mostly one rule): compound
        # faults are supposed to be *composed* by corpus mutation, so the
        # guided search earns its coverage edge by stacking rules that each
        # proved interesting, rather than fresh sampling lucking into them
        rnd = self._rng("fresh", index)
        n_rules = 1
        if rnd.random() < 0.2:
            n_rules += 1
        spec: dict = {"seed": self.seed * 100_000 + index, "rules": []}
        if self.harness == "engine" and rnd.random() < 0.15:
            # named nemesis archetype (Jepsen style): a churn-split stacks
            # an eviction-grade fault on one node with message-class drops
            # on two others -- the shape that stresses promote-time sync
            # quorums. Targeting is random; guidance tunes it by mutation.
            self._churn_split(spec, rnd)
        else:
            for _ in range(n_rules):
                self._append_rule(spec, rnd)
        if self.harness == "engine" and rnd.random() < 0.2:
            self._attach_topology(spec, rnd)
        return spec

    def _churn_split(self, spec: dict, rnd: random.Random) -> None:
        nodes = list(self.endpoints)
        rnd.shuffle(nodes)
        evicted, starved, muted = nodes[0], nodes[1], nodes[2 % len(nodes)]
        split_ms = rnd.randrange(
            self.horizon_ms // 8, self.horizon_ms * 5 // 8
        )
        spec["rules"] = [
            {"type": "DropRule", "at": "egress", "windows": [[0, None]],
             "src": None, "dst": starved, "msg_types": ["Put"],
             "probability": 1.0},
            {"type": "PartitionRule", "at": "egress",
             "windows": [[split_ms, None]], "src": None, "dst": evicted,
             "msg_types": None},
            {"type": "DropRule", "at": "egress",
             "windows": [[split_ms, None]], "src": None, "dst": muted,
             "msg_types": ["Get"], "probability": 1.0},
        ]

    def mutate(self, base: dict, index: int) -> dict:
        """One mutation step on a corpus member: add a rule (the compound-
        fault driver), retarget a link, resample a window, or drop a rule.
        Falls back to a fresh plan if the mutant fails validation."""
        rnd = self._rng("mutate", index)
        spec = {
            **base,
            "rules": [dict(r) for r in base.get("rules", [])],
        }
        rules: List[dict] = spec["rules"]
        choice = rnd.random()
        if choice < 0.5 or not rules:
            self._append_rule(spec, rnd)
        elif choice < 0.7:
            rule = rules[rnd.randrange(len(rules))]
            if rule.get("dst") is not None:
                rule["dst"] = self._node(rnd)
        elif choice < 0.9:
            rule = rules[rnd.randrange(len(rules))]
            rule["windows"] = [self._window(rnd)]
        elif len(rules) > 1:
            rules.pop(rnd.randrange(len(rules)))
        if not self._valid(spec):
            return self.fresh(index)
        return spec

    def _valid(self, spec: dict) -> bool:
        try:
            FaultPlan.from_json(spec)
        except (ValueError, AssertionError, KeyError):
            return False
        return True

    def _append_rule(self, spec: dict, rnd: random.Random) -> None:
        # bounded resample: a candidate the builders reject (e.g. a
        # partition-window conflict) is replaced, not emitted
        for _ in range(8):
            rule = self._sample_rule(rnd)
            trial = {**spec, "rules": list(spec["rules"]) + [rule]}
            if self._valid(trial):
                spec["rules"].append(rule)
                return

    def _node(self, rnd: random.Random) -> str:
        return rnd.choice(self.endpoints)

    def _window(self, rnd: random.Random) -> list:
        start = rnd.randrange(0, max(1, self.horizon_ms * 3 // 4))
        if rnd.random() < 0.5:
            return [start, None]
        span = rnd.randrange(self.horizon_ms // 8 + 1, self.horizon_ms + 1)
        return [start, start + span]

    def _sample_rule(self, rnd: random.Random) -> dict:
        if self.harness == "engine":
            return self._sample_engine_rule(rnd)
        return self._sample_sim_rule(rnd)

    def _base(self, kind: str, rnd: random.Random, *, dst=None, src=None,
              msg_types=None, windows=None) -> dict:
        return {
            "type": kind,
            "at": "egress",
            "windows": windows if windows is not None else [self._window(rnd)],
            "src": src,
            "dst": dst,
            "msg_types": msg_types,
        }

    def _sample_engine_rule(self, rnd: random.Random) -> dict:
        kind = rnd.choice(GEN_RULES)
        wire = rnd.choice([["Put"], ["Get"], None])
        dst = self._node(rnd) if rnd.random() < 0.8 else None
        if kind == "DropRule":
            spec = self._base(kind, rnd, dst=dst, msg_types=wire)
            spec["probability"] = rnd.choice([0.5, 0.75, 1.0])
        elif kind == "PartitionRule":
            spec = self._base(kind, rnd, dst=self._node(rnd))
        elif kind == "CellPartitionRule":
            cells = rnd.choice([2, 3, 4, 8])
            spec = self._base(kind, rnd)
            spec["cells"] = cells
            spec["cell"] = rnd.randrange(0, cells)
        elif kind == "FlipFlopRule":
            spec = self._base(kind, rnd, dst=self._node(rnd))
            spec["period_ms"] = rnd.choice([800, 1600, 2400])
            spec["start_ms"] = rnd.randrange(0, 400)
        elif kind == "DelayRule":
            spec = self._base(kind, rnd, dst=dst, msg_types=wire)
            spec["base_ms"] = rnd.choice([5, 20, 45])
            spec["jitter_ms"] = rnd.randrange(0, 20)
        elif kind == "DuplicateRule":
            spec = self._base(kind, rnd, dst=dst,
                              msg_types=wire or ["Put"])
            spec["probability"] = round(0.3 + 0.5 * rnd.random(), 3)
        elif kind == "ReorderRule":
            spec = self._base(kind, rnd, dst=dst, msg_types=wire)
            spec["probability"] = round(0.3 + 0.5 * rnd.random(), 3)
            spec["max_extra_ms"] = rnd.choice([20, 40, 80])
        elif kind == "LossyLinkRule":
            spec = self._base(kind, rnd, dst=dst, msg_types=wire)
            spec["probability"] = rnd.choice([0.3, 0.6])
        elif kind == "SlowNodeRule":
            spec = self._base(kind, rnd, dst=self._node(rnd))
            spec["response_delay_ms"] = rnd.choice([30, 80, 200])
        elif kind == "ClockSkewRule":
            spec = self._base(kind, rnd, src=self._node(rnd),
                              windows=[[0, None]])
            spec["offset_ms"] = rnd.choice([-200, 0, 200])
            spec["rate"] = rnd.choice([0.75, 1.0, 1.25])
        elif kind == "RestartNodeRule":
            # closed down windows short enough that the fabric's recovery
            # path (not its eviction machinery) is what gets exercised
            start = rnd.randrange(0, max(1, self.horizon_ms // 2))
            down = rnd.choice([150, 300, 600])
            spec = self._base(kind, rnd, dst=self._node(rnd),
                              windows=[[start, start + down]])
        elif kind == "TornWriteRule":
            spec = self._base(kind, rnd, dst=self._node(rnd),
                              windows=[[0, None]])
            spec["drop_bytes"] = rnd.choice([1, 3, 9])
            spec["corrupt"] = rnd.random() < 0.5
        elif kind == "DiskStallRule":
            spec = self._base(kind, rnd, dst=self._node(rnd),
                              msg_types=["Put"])
            spec["stall_ms"] = rnd.choice([10, 40, 120])
        else:  # WireVersionRule
            spec = self._base(kind, rnd, src=self._node(rnd))
            spec["version"] = rnd.choice([1, 3])
        return spec

    def _sample_sim_rule(self, rnd: random.Random) -> dict:
        # serving-wire family: rules the sim's serving nemesis applies to
        # Put replication (the runner routes these to enable_serving)
        if rnd.random() < 0.4:
            kind = rnd.choice(
                ("DropRule", "DuplicateRule", "ReorderRule", "DelayRule",
                 "DiskStallRule")
            )
            spec = self._base(kind, rnd, msg_types=["Put"],
                              windows=[[0, None]])
            if kind == "DropRule":
                spec["probability"] = rnd.choice([0.25, 0.5])
            elif kind == "DuplicateRule":
                spec["probability"] = rnd.choice([0.3, 0.6])
            elif kind == "ReorderRule":
                spec["probability"] = rnd.choice([0.3, 0.6])
                spec["max_extra_ms"] = rnd.choice([20, 50])
            elif kind == "DiskStallRule":
                spec["dst"] = self._node(rnd)
                spec["stall_ms"] = rnd.choice([5, 20])
            else:
                spec["base_ms"] = rnd.choice([2, 5])
                spec["jitter_ms"] = rnd.randrange(0, 4)
            return spec
        # device family: only what _device_rules compiles (no src matches,
        # probe-wire only, skew rate in the supported band, sub-round
        # delays)
        kind = rnd.choice(
            ("DropRule", "PartitionRule", "CellPartitionRule",
             "FlipFlopRule", "LossyLinkRule", "SlowNodeRule",
             "ClockSkewRule", "DelayRule", "RestartNodeRule")
        )
        dst = self._node(rnd)
        if kind == "DropRule":
            spec = self._base(kind, rnd, dst=dst)
            spec["probability"] = rnd.choice([0.5, 1.0])
        elif kind == "PartitionRule":
            spec = self._base(kind, rnd, dst=dst)
        elif kind == "CellPartitionRule":
            cells = rnd.choice([2, 4, 8])
            spec = self._base(kind, rnd)
            spec["cells"] = cells
            spec["cell"] = rnd.randrange(0, cells)
        elif kind == "FlipFlopRule":
            spec = self._base(kind, rnd, dst=dst)
            spec["period_ms"] = rnd.choice([2000, 4000, 8000])
        elif kind == "LossyLinkRule":
            spec = self._base(kind, rnd, dst=dst)
            spec["probability"] = rnd.choice([0.3, 0.6])
        elif kind == "SlowNodeRule":
            spec = self._base(kind, rnd, dst=dst)
            spec["response_delay_ms"] = rnd.choice([300, 1000, 4000])
        elif kind == "ClockSkewRule":
            spec = self._base(kind, rnd, src=dst, windows=[[0, None]])
            spec["offset_ms"] = rnd.choice([-500, 0, 500])
            spec["rate"] = rnd.choice([0.8, 1.0, 1.25])
        elif kind == "RestartNodeRule":
            # down spans on the sim's detection timescale: long enough to
            # exercise the membership reaction, always closed
            start = rnd.randrange(0, max(1, self.horizon_ms // 2))
            down = rnd.choice([2000, 4000, 8000])
            spec = self._base(kind, rnd, dst=dst,
                              windows=[[start, start + down]])
        else:  # DelayRule: must stay under the FD round to compile
            spec = self._base(kind, rnd, dst=dst)
            spec["base_ms"] = rnd.choice([10, 40])
            spec["jitter_ms"] = rnd.randrange(0, 10)
        return spec

    def _attach_topology(self, spec: dict, rnd: random.Random) -> None:
        spec["topology"] = {
            "racks": max(4, len(self.endpoints)),
            "zones": rnd.choice([1, 2]),
            "regions": 1,
            "rack_rtt_ms": 0,
            "zone_rtt_ms": rnd.choice([1, 2]),
            "region_rtt_ms": rnd.choice([2, 4]),
            "inter_region_rtt_ms": rnd.choice([4, 8]),
        }
        spec["topology_slots"] = {
            ep: i for i, ep in enumerate(self.endpoints)
        }
