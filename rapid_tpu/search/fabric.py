"""A virtual-time serving-plane fabric for nemesis-search probes.

Why not the simulator: the sim plane's serving mirror reconciles by
max-merging across every live old-row replica, so quorum-counting bugs
in the real :class:`~..serving.engine.ServingEngine` promote path are
structurally invisible there. This fabric runs N real engines over one
``VirtualScheduler`` with every request routed through
``Nemesis.decide`` (drops, delays, duplicates, gray slowness, skewed
clocks, WAN topology latency), so a probe exercises the actual quorum
arithmetic in milliseconds of wall time.

The membership/placement plane is compiled, not simulated: long-lived
partitions, flappy links and heavily-slowed nodes against a member are
treated as what the failure detector would eventually conclude --
eviction -- scheduled ``DETECT_MS`` after the fault window opens. An
eviction rebuilds the placement map, replays the diff's handoff copies
store-to-store (donor first, then live old-row survivors: the failover
chain), and installs the new map on every engine *including the victim*
(the "kicked" signal; read fencing on a deposed leader is a lease
protocol the engine does not implement, so the fabric does not probe
that window).

Every delivery costs ``DELIVERY_MS`` so map installs, which are
synchronous, always complete before the first promote-sync probe lands;
a dropped or too-slow message surfaces to the sender as a TimeoutError
at ``DROP_TIMEOUT_MS``, feeding the engine's own retry loop.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Set, Tuple

from ..faults import (
    EGRESS,
    CellPartitionRule,
    FaultPlan,
    FlipFlopRule,
    Nemesis,
    PartitionRule,
    RestartNodeRule,
    SlowNodeRule,
    TornWriteRule,
)
from ..handoff.store import InMemoryPartitionStore
from ..observability import FlightRecorder, Metrics
from ..placement.engine import PlacementConfig, build_map, diff_maps
from ..runtime.futures import Promise
from ..runtime.scheduler import VirtualScheduler
from ..serving.engine import ServingEngine
from ..serving.kv import encode_kv
from ..types import Endpoint, Get, PutAck
from .checkers import ClientOp

DELIVERY_MS = 1        # per-hop latency: installs land before sync probes
DROP_TIMEOUT_MS = 60   # sender-side deadline for dropped/slow messages
DETECT_MS = 400        # fault window opens -> eviction decision
SETTLE_MS = 1500       # post-horizon drain for retries and syncs


def fabric_endpoints(n: int) -> List[Endpoint]:
    return [Endpoint.from_parts("node", 7000 + i) for i in range(n)]


class _FabricClient:
    """The engine-facing transport half: requests go through the fabric's
    nemesis-routed send."""

    def __init__(self, fabric: "ServingFabric", address: Endpoint) -> None:
        self._fabric = fabric
        self.address = address

    def send_message(self, remote: Endpoint, msg) -> Promise:
        return self._fabric._send(self.address, remote, msg)


class ServingFabric:
    """One probe's worth of cluster: N engines, one plan, one clock."""

    def __init__(self, plan: FaultPlan, n: int = 5, partitions: int = 16,
                 replicas: int = 3, config_seed: int = 0,
                 forensics: bool = False) -> None:
        self.plan = plan
        self.scheduler = VirtualScheduler()
        self.metrics = Metrics()
        # forensics mirror: HLC on the fabric's virtual clock stamps every
        # journal entry, so a violating probe's journal pins into the same
        # causal-timeline tooling as real members' bundles (off = exact
        # pre-forensics entries)
        self.hlc = None
        if forensics:
            from ..forensics.hlc import HlcClock

            self.hlc = HlcClock(clock=self.scheduler.now_ms)
        self.recorder = FlightRecorder(
            capacity=4096, node="fabric", clock=self.scheduler.now_ms,
            hlc=self.hlc, metrics=self.metrics,
        )
        self.nemesis = Nemesis(plan, self.scheduler, metrics=self.metrics)
        self.nemesis.arm(epoch_ms=0)
        self.endpoints = fabric_endpoints(n)
        self.live: Set[Endpoint] = set(self.endpoints)
        self.down: Set[Endpoint] = set()
        self.recovered: List[Endpoint] = []
        self.config = PlacementConfig(
            partitions=partitions, replicas=replicas, seed=config_seed
        )
        self.stores: Dict[Endpoint, InMemoryPartitionStore] = {}
        self.engines: Dict[Endpoint, ServingEngine] = {}
        for ep in self.endpoints:
            store = InMemoryPartitionStore()
            self.stores[ep] = store
            self.engines[ep] = ServingEngine(
                store, ep, _FabricClient(self, ep),
                self.nemesis.scheduler_for(ep),
                metrics=self.metrics, recorder=self.recorder,
            )
        self.epoch = 1
        self.map = build_map(
            tuple(self.endpoints), {}, self.config, self.epoch
        )
        # seed every owned partition with an empty blob (what a real
        # bootstrap's handoff plane leaves behind): a store holding nothing
        # abstains from sync/quorum answers, so an unseeded fabric would
        # churn forever on its very first map
        for p, row in enumerate(self.map.assignments):
            for ep in row:
                self.stores[ep].put(p, encode_kv({}))
        for ep in self.endpoints:
            self.engines[ep].update_map(self.map)
        self.history: List[ClientOp] = []
        # restart plane: a RestartNodeRule window is a crash-and-recover, not
        # an eviction -- the store survives, the identity is retained, and
        # recovery catches the node up through the replica row (the fabric
        # analogue of WAL replay + verified handoff pull). TornWriteRule
        # marks the victim's local copies untrustworthy past the last
        # snapshot, forcing the catch-up pull.
        self.torn: Set[Endpoint] = {
            r.match.dst for r in plan.rules
            if isinstance(r, TornWriteRule) and r.match.dst in self.stores
        }
        for rule in plan.rules:
            if not isinstance(rule, RestartNodeRule):
                continue
            victim = rule.match.dst
            if victim not in self.stores:
                continue
            for start, end in rule.windows:
                if end is None:
                    continue  # builder enforces closed; tolerate mutations
                self.scheduler.schedule(
                    start, lambda ep=victim: self._crash(ep)
                )
                self.scheduler.schedule(
                    end, lambda ep=victim: self._recover(ep)
                )
        for when_ms, ep in self._eviction_schedule(plan):
            self.scheduler.schedule(
                when_ms, lambda victim=ep: self._evict(victim)
            )

    # -- compiled membership plane --------------------------------------- #

    def _eviction_schedule(
        self, plan: FaultPlan
    ) -> List[Tuple[int, Endpoint]]:
        """What the FD would eventually decide: a member behind a lasting
        partition, flappy link, or timeout-scale slowness gets evicted
        DETECT_MS after the fault window opens."""
        out: List[Tuple[int, Endpoint]] = []
        victims: Set[Endpoint] = set()
        for rule in plan.rules:
            if isinstance(rule, CellPartitionRule):
                # a lasting cell partition isolates the named cell from the
                # rest of the fabric: outside the boundary every member of
                # that cell is probe-dead, so the FD evicts the whole cell
                # (the same externally visible outcome apply_plan_at
                # compiles for the device plane)
                from ..hierarchy.cells import cell_of

                for start, end in rule.windows:
                    if end is not None and end - start < DETECT_MS:
                        continue
                    for ep in self.endpoints:
                        if ep in victims or cell_of(
                            ep, rule.cells,
                            topology=plan.topology,
                            slots=plan.topology_slots or None,
                        ) != rule.cell:
                            continue
                        out.append((start + DETECT_MS, ep))
                        victims.add(ep)
                    break
                continue
            dst = rule.match.dst
            if dst is None or dst not in self.stores or dst in victims:
                continue
            if isinstance(rule, SlowNodeRule):
                if rule.response_delay_ms < DROP_TIMEOUT_MS:
                    continue  # slow but under timeouts: gray, not evicted
            elif not isinstance(rule, (PartitionRule, FlipFlopRule)):
                continue
            for start, end in rule.windows:
                if end is not None and end - start < DETECT_MS:
                    continue  # heals before the detector concludes
                out.append((start + DETECT_MS, dst))
                victims.add(dst)
                break
        return sorted(out, key=lambda pair: (pair[0], str(pair[1])))

    def _evict(self, victim: Endpoint) -> None:
        if victim not in self.live or len(self.live) <= 1:
            return
        self.live.discard(victim)
        # the detector's verdict, then the membership consequence: the
        # fd_signal/kicked pair brackets each eviction in the journal, so
        # multi-eviction plans produce edge vocabulary single-fault plans
        # cannot (that tail is what guided search climbs toward)
        self.recorder.record(
            "fd_signal", node=str(victim), verdict="evict",
        )
        self.epoch += 1
        self.metrics.incr("view_changes")
        old = self.map
        new = build_map(
            tuple(sorted(self.live)), {}, self.config, self.epoch
        )
        diff = diff_maps(old, new)
        self.recorder.record(
            "view_install", epoch=self.epoch, evicted=str(victim),
            members=len(self.live),
        )
        self.recorder.record(
            "placement_rebalance", version=new.version, moved=diff.moved,
        )
        for p, donor, recipient in diff.handoffs:
            if recipient not in self.stores:
                continue
            self.recorder.record(
                "handoff_started", partition=p,
                donor=None if donor is None else str(donor),
                recipient=str(recipient),
            )
            old_row = old.assignments[p] if p < len(old.assignments) else ()
            sources = [donor] if donor is not None else []
            sources.extend(n for n in old_row if n not in sources)
            blob = None
            used: Optional[Endpoint] = None
            for source in sources:
                if source not in self.live or source == recipient:
                    continue
                held = self.stores[source].get(p)
                if held is not None:
                    blob, used = held, source
                    break
            if blob is None:
                self.metrics.incr("handoff.sessions_failed")
                self.recorder.record(
                    "handoff_failed", partition=p, recipient=str(recipient),
                )
                continue
            if donor is not None and used != donor:
                self.metrics.incr("handoff.failovers")
            self.stores[recipient].put(p, blob)
            self.recorder.record(
                "handoff_complete", partition=p, source=str(used),
                recipient=str(recipient),
            )
        # victim included: the kicked signal (see module docstring)
        for ep in sorted(self.engines):
            self.engines[ep].update_map(new)
        self.recorder.record("kicked", node=str(victim), epoch=self.epoch)
        self.map = new

    # -- restart plane ----------------------------------------------------- #

    def _crash(self, ep: Endpoint) -> None:
        if ep not in self.live:
            return  # already evicted: nothing left to restart
        self.down.add(ep)
        self.recorder.record("fd_signal", node=str(ep), verdict="restart")

    def _recover(self, ep: Endpoint) -> None:
        if ep not in self.down:
            return
        self.down.discard(ep)
        from ..serving.kv import decode_kv, encode_kv

        torn = ep in self.torn
        if torn:
            self.metrics.incr("durability.torn_truncations")
        replayed = 0
        for p, row in enumerate(self.map.assignments):
            if ep not in row:
                continue
            # max-merge across the live row plus the survivor's own copy
            # (unless torn): any acked write reached a majority, so at least
            # one live replica still holds it, and the merged blob written
            # back everywhere is what fingerprint convergence asserts
            merged: dict = {}
            holders = [
                peer for peer in row
                if peer in self.live and peer not in self.down
            ]
            for holder in holders:
                blob = self.stores[holder].get(p)
                if holder == ep and torn:
                    continue  # torn tail: local copy is not trustworthy
                for key, (version, value) in decode_kv(blob).items():
                    cur = merged.get(key)
                    if cur is None or version > cur[0]:
                        merged[key] = (version, value)
            blob = encode_kv(merged)
            for holder in holders:
                if self.stores[holder].get(p) != blob:
                    self.stores[holder].put(p, blob)
                    if holder == ep:
                        replayed += 1
        self.engines[ep].update_map(self.map)  # may have moved while down
        self.recovered.append(ep)
        if replayed:
            self.metrics.incr("durability.replayed_records", replayed)
        self.recorder.record(
            "durability_recovered", node=str(ep), replayed=replayed,
        )

    # -- nemesis-routed transport ----------------------------------------- #

    def _send(self, src: Endpoint, dst: Endpoint, msg) -> Promise:
        kind = type(msg).__name__
        if src in self.down or dst in self.down:
            # a restarting process neither sends nor answers: the sender
            # sees the same deadline a dropped message produces
            out: Promise = Promise()
            self.scheduler.schedule(
                DROP_TIMEOUT_MS,
                lambda: out.try_set_exception(
                    TimeoutError(f"{dst} is restarting")
                ),
            )
            return out
        d = self.nemesis.decide(src, dst, msg, EGRESS)
        if d.drop:
            self.metrics.incr("nemesis_dropped", at="egress", msg=kind)
            out: Promise = Promise()
            self.scheduler.schedule(
                DROP_TIMEOUT_MS,
                lambda: out.try_set_exception(
                    TimeoutError(f"nemesis dropped {kind} to {dst}")
                ),
            )
            return out
        for _ in range(d.duplicates):
            self.metrics.incr("nemesis_duplicated", at="egress", msg=kind)
            self._deliver(dst, msg, DELIVERY_MS + d.delay_ms, Promise())
        out = Promise()
        total = DELIVERY_MS + d.delay_ms + d.slow_ms
        if d.slow_ms > 0:
            self.metrics.incr("nemesis_slowed", at="egress", msg=kind)
            if total >= DROP_TIMEOUT_MS:
                # gray node: delivered and applied, but the sender's
                # deadline fires first -- indistinguishable from a drop
                self.scheduler.schedule(
                    DROP_TIMEOUT_MS,
                    lambda: out.try_set_exception(TimeoutError(
                        f"{dst} answered {total} ms late"
                    )),
                )
        elif d.delay_ms > 0:
            self.metrics.incr(
                "nemesis_reordered" if d.reordered else "nemesis_delayed",
                at="egress", msg=kind,
            )
        else:
            self.metrics.incr("nemesis_passed", at="egress", msg=kind)
        self._deliver(dst, msg, total, out)
        return out

    def _deliver(self, dst: Endpoint, msg, after_ms: int,
                 out: Promise) -> None:
        def dispatch() -> None:
            engine = self.engines.get(dst)
            if engine is None or dst in self.down:
                out.try_set_exception(TimeoutError(f"no such node {dst}"))
                return
            reply = (
                engine.handle_get(msg) if isinstance(msg, Get)
                else engine.handle_put(msg)
            )
            reply.add_callback(
                lambda p: self.scheduler.schedule(
                    DELIVERY_MS, lambda: _settle(p, out)
                )
            )

        self.scheduler.schedule(after_ms, dispatch)

    # -- workload ---------------------------------------------------------- #

    def run(self, horizon_ms: int, ops: int, keys: int = 6) -> List[ClientOp]:
        """Seeded closed-ish workload: ops spread evenly over the horizon,
        puts and gets from every node's co-located client, then a settle
        drain. Returns the completed-op history."""
        rnd = random.Random(self.plan.seed * 2_000_003 + 17)
        gap = max(1, horizon_ms // (ops + 1))
        for i in range(ops):
            client = self.endpoints[rnd.randrange(len(self.endpoints))]
            key = b"k%02d" % rnd.randrange(keys)
            if rnd.random() < 0.55:
                self._schedule_op(
                    (i + 1) * gap, "put", client, key, b"v-%d" % i
                )
            else:
                self._schedule_op((i + 1) * gap, "get", client, key)
        self.scheduler.run_until_time(horizon_ms + SETTLE_MS)
        return self.history

    def _schedule_op(self, at_ms: int, op: str, client: Endpoint,
                     key: bytes, value: bytes = b"") -> None:
        self.scheduler.schedule(
            at_ms, lambda: self._issue(op, client, key, value)
        )

    def _issue(self, op: str, client: Endpoint, key: bytes,
               value: bytes) -> None:
        if client in self.down:
            return  # co-located client restarts with its node: no op issued
        engine = self.engines[client]
        invoke_ms = self.scheduler.now_ms()
        promise = (
            engine.client_put(key, value) if op == "put"
            else engine.client_get(key)
        )

        def finish(p: Promise) -> None:
            ack = None if p.exception() is not None else p._result  # noqa: SLF001
            if not isinstance(ack, PutAck):
                return  # never completed: no linearizability obligation
            self.history.append(ClientOp(
                client=str(client), op=op, key=key,
                value=value if op == "put" else ack.value,
                version=ack.version, status=ack.status,
                invoke_ms=invoke_ms, complete_ms=self.scheduler.now_ms(),
            ))

        promise.add_callback(finish)

    # -- probe outputs ----------------------------------------------------- #

    def journal(self) -> List[dict]:
        return self.recorder.tail(4096)

    def live_digests(self) -> Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]]:
        return {
            str(ep): self.engines[ep].leader_digest()
            for ep in sorted(self.live)
        }

    def map_versions(self) -> Dict[str, int]:
        return {
            str(ep): getattr(self.engines[ep]._map, "version", None)  # noqa: SLF001
            for ep in sorted(self.live)
        }

    def hierarchy_digests(
        self, cells: int
    ) -> Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...], int]]:
        """Each live node's composed hierarchy digest, derived from that
        node's OWN map (not shared fabric state): cells, per-cell leaders,
        and composed global fingerprint -- the triple
        ``check_hierarchy_agreement`` consumes. Nodes whose maps diverged
        mid-probe produce divergent fingerprints."""
        from ..hierarchy.cells import cell_members
        from ..hierarchy.parent import (
            CellState, cell_fingerprint, cell_leaders, compose_fingerprint,
        )

        out: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...], int]] = {}
        for ep in sorted(self.live):
            held = self.engines[ep]._map  # noqa: SLF001
            members = sorted(
                {n for row in held.assignments for n in row},
                key=lambda e: (e.hostname, e.port),
            )
            grouped = cell_members(members, cells)
            rows = []
            for cell in sorted(grouped):
                group = grouped[cell]
                rows.append(CellState(
                    cell=cell,
                    epoch=cell_fingerprint(group),
                    size=len(group),
                    leader=str(cell_leaders(group, 1)[0]),
                    fingerprint=cell_fingerprint(group),
                ))
            out[str(ep)] = (
                tuple(r.cell for r in rows),
                tuple(r.leader for r in rows),
                compose_fingerprint(rows),
            )
        return out

    def durable_versions(self) -> Dict[bytes, int]:
        """Ground truth for the durability invariant: per key, the highest
        version any live, up replica holds in stable storage."""
        from ..serving.kv import decode_kv

        out: Dict[bytes, int] = {}
        for p, row in enumerate(self.map.assignments):
            for ep in row:
                if ep not in self.live or ep in self.down:
                    continue
                blob = self.stores[ep].get(p)
                if blob is None:
                    continue
                for key, (version, _value) in decode_kv(blob).items():
                    if version > out.get(key, 0):
                        out[key] = version
        return out

    def recovery_fingerprints(self) -> List[Tuple[int, str, object]]:
        """``(partition, node, fingerprint)`` over every row that contains a
        recovered node -- the durability checker's convergence witness."""
        recovered = set(self.recovered)
        out: List[Tuple[int, str, object]] = []
        for p, row in enumerate(self.map.assignments):
            if not any(ep in recovered for ep in row):
                continue
            for ep in row:
                if ep not in self.live or ep in self.down:
                    continue
                out.append((p, str(ep), self.stores[ep].fingerprint(p)))
        return out


def _settle(src: Promise, dst: Promise) -> None:
    exc = src.exception()
    if exc is not None:
        dst.try_set_exception(exc)
    else:
        dst.try_set_result(src._result)  # noqa: SLF001