"""Delta-debugging shrinker for violating probe specs.

Greedy ddmin to a fixpoint: try removing rules one at a time, then
narrowing each rule's windows (pin the start to 0, close open ends,
halve closed spans), then dropping the topology, shrinking the cluster,
and halving the workload -- keeping any reduction under which the
original violation still reproduces (same invariant tags, judged by
re-running the probe). Every reduction attempt costs one probe; the
whole shrink is bounded by ``max_probes``.

The plan seed is never touched: a shrunk plan reproduces with the exact
decision streams that found the violation.
"""

from __future__ import annotations

import copy
from typing import Callable, FrozenSet, Optional, Tuple

from .runner import run_probe


def violation_kinds(spec: dict) -> FrozenSet[str]:
    return frozenset(
        v["invariant"] for v in run_probe(spec).violations
    )


def shrink_spec(
    spec: dict,
    target_kinds: Optional[FrozenSet[str]] = None,
    max_probes: int = 200,
) -> Tuple[dict, int]:
    """Minimize ``spec`` while ``target_kinds`` (default: the kinds the
    unshrunk spec violates) all still reproduce. Returns the minimized
    spec and the number of probes spent."""
    spent = [0]
    target = (
        frozenset(target_kinds) if target_kinds is not None
        else violation_kinds(spec)
    )
    if target_kinds is None:
        spent[0] += 1
    if not target:
        return copy.deepcopy(spec), spent[0]

    def reproduces(candidate: dict) -> bool:
        if spent[0] >= max_probes:
            return False
        spent[0] += 1
        return target <= violation_kinds(candidate)

    current = copy.deepcopy(spec)
    changed = True
    while changed and spent[0] < max_probes:
        changed = False
        changed |= _drop_rules(current, reproduces)
        changed |= _narrow_windows(current, reproduces)
        changed |= _drop_topology(current, reproduces)
        changed |= _shrink_cluster(current, reproduces)
        changed |= _halve_ops(current, reproduces)
    return current, spent[0]


def _plan(spec: dict) -> dict:
    return spec["plan"]


def _with_rules(spec: dict, rules: list) -> dict:
    out = copy.deepcopy(spec)
    out["plan"]["rules"] = rules
    return out


def _drop_rules(current: dict, reproduces: Callable[[dict], bool]) -> bool:
    changed = False
    i = 0
    while i < len(_plan(current)["rules"]):
        rules = _plan(current)["rules"]
        if len(rules) <= 1:
            break
        trial = _with_rules(current, rules[:i] + rules[i + 1:])
        if reproduces(trial):
            current["plan"] = trial["plan"]
            changed = True
        else:
            i += 1
    return changed


def _narrow_windows(current: dict,
                    reproduces: Callable[[dict], bool]) -> bool:
    changed = False
    for i, rule in enumerate(_plan(current)["rules"]):
        for j, (start, end) in enumerate(list(rule.get("windows", []))):
            if start > 0:
                trial = copy.deepcopy(current)
                trial["plan"]["rules"][i]["windows"][j] = [0, end]
                if reproduces(trial):
                    current["plan"] = trial["plan"]
                    rule = _plan(current)["rules"][i]
                    start = 0
                    changed = True
            if end is not None and end - start > 2:
                trial = copy.deepcopy(current)
                trial["plan"]["rules"][i]["windows"][j] = [
                    start, start + (end - start) // 2
                ]
                if reproduces(trial):
                    current["plan"] = trial["plan"]
                    rule = _plan(current)["rules"][i]
                    changed = True
    return changed


def _drop_topology(current: dict,
                   reproduces: Callable[[dict], bool]) -> bool:
    if "topology" not in _plan(current):
        return False
    trial = copy.deepcopy(current)
    trial["plan"].pop("topology", None)
    trial["plan"].pop("topology_slots", None)
    if reproduces(trial):
        current["plan"] = trial["plan"]
        return True
    return False


def _shrink_cluster(current: dict,
                    reproduces: Callable[[dict], bool]) -> bool:
    """Engine harness only: drop the highest-numbered node while no rule
    references it and a replica row still fits."""
    if current.get("harness", "engine") != "engine":
        return False
    changed = False
    while True:
        n = current.get("n", 5)
        if n <= current.get("replicas", 3) + 1:
            break
        top = f"node:{7000 + n - 1}"
        if any(
            top in (rule.get("src"), rule.get("dst"))
            for rule in _plan(current)["rules"]
        ) or top in (_plan(current).get("topology_slots") or {}):
            break
        trial = copy.deepcopy(current)
        trial["n"] = n - 1
        if not reproduces(trial):
            break
        current["n"] = n - 1
        changed = True
    return changed


def _halve_ops(current: dict, reproduces: Callable[[dict], bool]) -> bool:
    changed = False
    while True:
        ops = current.get("ops", 40)
        if ops < 16:
            break
        trial = copy.deepcopy(current)
        trial["ops"] = ops // 2
        if not reproduces(trial):
            break
        current["ops"] = ops // 2
        changed = True
    return changed
