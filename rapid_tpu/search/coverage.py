"""Coverage fingerprints for nemesis-search probes.

A probe's coverage is a frozenset of hashable signals extracted from its
flight-recorder journal and metrics snapshot:

* ``("kind", k)``            -- an EVENT_CATALOG kind fired at least once;
* ``("edge", a, b)``         -- kinds a, b fired back-to-back in journal
                                sequence order (the "transition" signal the
                                guided search optimizes for);
* ``("metric", name)``       -- a counter from COVERAGE_METRICS went
                                nonzero (fast vs classic consensus paths,
                                handoff failover chains, serving churn);
* ``("fault", rendered)``    -- a labeled nemesis counter went nonzero
                                (``nemesis_dropped{at=egress,msg=Put}``);
                                the action x message-kind cross product is
                                what makes compound plans score higher
                                than any single rule.

The hunter unions these across probes; a plan that contributes any new
signal enters the mutation corpus.
"""

from __future__ import annotations

from typing import FrozenSet, Iterable, Mapping, Sequence, Tuple

from ..observability import EVENT_CATALOG

Signal = Tuple[str, ...]

# behavior-path counters worth distinguishing probes by (all in
# METRIC_CATALOG); names, not values: coverage is "did this path fire",
# not "how often"
COVERAGE_METRICS = (
    "classic_coordinator_races",
    "consensus.classic_decisions",
    "consensus.classic_rounds_started",
    "consensus.fast_decisions",
    "handoff.failovers",
    "handoff.retries",
    "handoff.sessions_failed",
    "serving.not_leader_redirects",
    "serving.put_retries",
    "serving.quorum_reads",
    "serving.reconciled_replicas",
    "view_changes",
)


def coverage_from_journal(entries: Sequence[Mapping]) -> FrozenSet[Signal]:
    """Kind singletons + adjacent-pair transitions over the journal's kind
    sequence (entries as FlightRecorder.tail returns them)."""
    kinds = [e["kind"] for e in sorted(entries, key=lambda e: e["seq"])]
    signals = {("kind", k) for k in kinds}
    signals.update(("edge", a, b) for a, b in zip(kinds, kinds[1:]))
    return frozenset(signals)


def coverage_from_metrics(snapshot: Mapping[str, float]) -> FrozenSet[Signal]:
    return frozenset(
        ("metric", name) for name in COVERAGE_METRICS if snapshot.get(name)
    )


def coverage_from_fault_actions(
    rendered: Mapping[str, float],
) -> FrozenSet[Signal]:
    """Per-label nemesis-action signals from a Metrics.snapshot() flat view
    (labeled counters render as ``name{k=v,...}``). Only the nemesis_*
    family counts: which fault actions hit which message kinds."""
    return frozenset(
        ("fault", name) for name, value in rendered.items()
        if value and name.startswith("nemesis_")
    )


def transitions(signals: Iterable[Signal]) -> FrozenSet[Signal]:
    """The distinct EVENT_CATALOG transitions in a coverage set: edges
    whose endpoints are both catalog kinds (the guided-vs-unguided report
    metric)."""
    return frozenset(
        s for s in signals
        if s[0] == "edge" and s[1] in EVENT_CATALOG and s[2] in EVENT_CATALOG
    )
