"""Execute one nemesis-search probe: plan spec in, coverage + verdicts out.

Two harnesses share the probe-spec format (a JSON-able dict)::

    {"harness": "engine", "n": 5, "partitions": 16, "horizon_ms": 4000,
     "ops": 40, "keys": 6, "plan": {...FaultPlan.to_json...}}

``engine`` runs the real ServingEngine cluster on the virtual-time
fabric (:mod:`.fabric`) -- the harness that exercises the serving plane's
actual quorum arithmetic. ``sim`` replays the plan's device-compilable
rules on the Simulator via the ``apply_plan_at`` segment loop while a
seeded Get/Put workload rides the sim's serving mirror; rules matching
the Put wire are split out and handed to ``enable_serving`` (the sim's
serving nemesis), mirroring how ``_device_rules`` refuses non-probe
message matches.

Both harnesses are deterministic per (spec, plan seed): same spec, same
history, same coverage, same verdicts.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import FrozenSet, List, Tuple

from ..faults import FaultPlan
from .checkers import (
    ClientOp,
    InvariantViolation,
    check_config_parity,
    check_durability,
    check_fingerprint_agreement,
    check_gray_collateral,
    check_hierarchy_agreement,
    check_leader_agreement,
    check_linearizable_history,
    check_metastable_recovery,
    check_view_agreement,
)
from .coverage import (
    COVERAGE_METRICS,
    coverage_from_fault_actions,
    coverage_from_journal,
    coverage_from_metrics,
)

# fixed sim identity: matches tests/test_serving.py's serving-sim shape so
# probes reuse the same jit cache entries as the existing suite
SIM_SEED = 11
SIM_PLACEMENT = {"partitions": 32, "replicas": 3, "seed": 7}


@dataclass(frozen=True)
class ProbeResult:
    coverage: FrozenSet[tuple]
    violations: Tuple[dict, ...]
    info: dict = field(default_factory=dict)

    @property
    def violated(self) -> bool:
        return bool(self.violations)


def run_probe(spec: dict) -> ProbeResult:
    harness = spec.get("harness", "engine")
    if harness == "engine":
        return run_engine_probe(spec)
    if harness == "sim":
        return run_sim_probe(spec)
    raise ValueError(f"unknown harness {harness!r}")


def _gray_plan_victims(plan: FaultPlan):
    """``(is_pure_gray, victims)`` for the gray-collateral invariant:
    pure gray means every rule is a SlowNodeRule or LossyLinkRule (faults
    that degrade, never kill). ``victims`` is the set of dst endpoints
    those rules name, or None when any gray rule is unscoped (dst=None
    faults every link, making collateral attribution vacuous)."""
    from ..faults import LossyLinkRule, SlowNodeRule

    if not plan.rules:
        return False, None
    victims = set()
    for rule in plan.rules:
        if not isinstance(rule, (SlowNodeRule, LossyLinkRule)):
            return False, None
        dst = rule.match.dst
        if dst is None:
            return True, None
        victims.add(dst)
    return True, victims


def _plan_fault_span(plan: FaultPlan):
    """``(first_open_ms, last_clear_ms)`` across every rule window of the
    plan, or None when the plan has no rules or any window is open-ended
    (a fault that never heals supports no recovery claim, so the
    metastable-recovery check must stay vacuous)."""
    starts: List[int] = []
    ends: List[int] = []
    for rule in plan.rules:
        for start, end in rule.windows:
            if end is None:
                return None
            starts.append(int(start))
            ends.append(int(end))
    if not starts:
        return None
    return min(starts), max(ends)


def _collect(checks) -> List[dict]:
    violations: List[dict] = []
    for check in checks:
        try:
            check()
        except InvariantViolation as violation:
            violations.append(violation.to_json())
    return violations


def _witness_bundle(node: str, journal, metrics_snapshot, violations,
                    harness: str, hlc=None) -> dict:
    """Forensic evidence for a violating probe (spec flag ``forensics``):
    the harness journal (HLC-stamped when the mirror is on) and metric
    digest, bundled with the verdicts under the ``invariant_violation``
    trigger -- the same document ``tools/forensics.py`` merges, so a hunt
    witness replays into a causal timeline."""
    from ..forensics.bundle import build_bundle, member_record

    stamp = None
    if hlc is not None:
        try:
            stamp = hlc.peek().to_wire()
        except Exception:  # noqa: BLE001 -- evidence degrades, never throws
            stamp = None
    local = member_record(
        node, hlc=stamp, journal=list(journal),
        metrics={k: int(v) for k, v in dict(metrics_snapshot).items()},
    )
    return build_bundle("invariant_violation", local, detail={
        "harness": harness,
        "kinds": sorted({v["invariant"] for v in violations}),
    })


# -- engine harness ------------------------------------------------------- #

def run_engine_probe(spec: dict) -> ProbeResult:
    from .fabric import ServingFabric

    plan = FaultPlan.from_json(spec["plan"])
    fabric = ServingFabric(
        plan,
        n=spec.get("n", 5),
        partitions=spec.get("partitions", 16),
        replicas=spec.get("replicas", 3),
        forensics=bool(spec.get("forensics", False)),
    )
    history = fabric.run(
        spec.get("horizon_ms", 4000), spec.get("ops", 40),
        keys=spec.get("keys", 6),
    )
    checks = [
        lambda: check_linearizable_history(history),
        lambda: check_leader_agreement(fabric.live_digests()),
        lambda: check_view_agreement(fabric.map_versions()),
    ]
    from ..faults import CellPartitionRule, RestartNodeRule, TornWriteRule

    hier_rules = [r for r in plan.rules if isinstance(r, CellPartitionRule)]
    if hier_rules:
        # cell-partition plans additionally carry the hierarchy oracle:
        # every live node's composed global view (derived from its own
        # map) must agree, and no cell may see two live leaders
        cells = max(r.cells for r in hier_rules)
        checks.append(
            lambda: check_hierarchy_agreement(fabric.hierarchy_digests(cells))
        )
    if any(isinstance(r, (RestartNodeRule, TornWriteRule)) for r in plan.rules):
        # restart-bearing plans additionally carry the durability oracle:
        # acked writes must survive every crash-and-recover, and each
        # recovered node's row must hold converged fingerprints
        acked_versions: dict = {}
        for o in history:
            if o.op == "put" and o.status == 0:  # PutAck.STATUS_OK
                if o.version > acked_versions.get(o.key, 0):
                    acked_versions[o.key] = o.version
        # a drop-class Put rule still open when the run ends legitimately
        # leaves replica rows lagging -- the sim probe's lossy-replication
        # fingerprint carve-out, applied to the recovery witness
        end_ms = spec.get("horizon_ms", 4000)
        lossy_at_end = any(
            rs.get("type") in ("DropRule", "LossyLinkRule")
            and (rs.get("msg_types") is None or "Put" in rs["msg_types"])
            and any(
                w[1] is None or w[1] >= end_ms
                for w in rs.get("windows", ())
            )
            for rs in spec["plan"].get("rules", ())
        )
        checks.append(
            lambda: check_durability(
                acked_versions,
                fabric.durable_versions(),
                () if lossy_at_end else fabric.recovery_fingerprints(),
            )
        )
    pure_gray, victims = _gray_plan_victims(plan)
    if pure_gray and victims is not None:
        evicted = [
            entry["detail"]["evicted"]
            for entry in fabric.journal()
            if entry["kind"] == "view_install"
            and "evicted" in entry["detail"]
        ]
        checks.append(
            lambda: check_gray_collateral(
                {str(v) for v in victims}, evicted
            )
        )
    span = _plan_fault_span(plan)
    horizon = spec.get("horizon_ms", 4000)
    if span is not None and span[1] < horizon:
        # every fault heals inside the horizon: the back half of the
        # post-heal window must see goodput return to the pre-fault
        # baseline (metastability check; vacuous when either segment is
        # too thin -- see checkers.check_metastable_recovery)
        faulted_from, healed_at = span[0], span[1] + (horizon - span[1]) // 2
        checks.append(
            lambda: check_metastable_recovery(
                history,
                faulted_from_ms=faulted_from,
                healed_at_ms=healed_at,
            )
        )
    violations = _collect(checks)
    snapshot = {
        name: fabric.metrics.get(name) for name in COVERAGE_METRICS
    }
    coverage = (
        coverage_from_journal(fabric.journal())
        | coverage_from_metrics(snapshot)
        | coverage_from_fault_actions(fabric.metrics.snapshot())
    )
    acked = sum(1 for o in history if o.op == "put" and o.status == 0)
    info = {
        "harness": "engine",
        "history": len(history),
        "acked_puts": acked,
        "virtual_ms": fabric.scheduler.now_ms(),
        "live": len(fabric.live),
    }
    if violations and spec.get("forensics"):
        info["bundle"] = _witness_bundle(
            "fabric", fabric.journal(), fabric.metrics.snapshot(),
            violations, "engine", hlc=fabric.hlc,
        )
    return ProbeResult(
        coverage=coverage,
        violations=tuple(violations),
        info=info,
    )


# -- sim harness ---------------------------------------------------------- #

def _is_serving_rule(rule_spec: dict) -> bool:
    # Put-wire matches and storage-plane stalls both land on the serving
    # mirror's nemesis; everything else is the device plane's problem
    return (
        rule_spec.get("msg_types") == ["Put"]
        or rule_spec.get("type") == "DiskStallRule"
    )


def run_sim_probe(spec: dict) -> ProbeResult:
    from ..faults import (
        RestartNodeRule,
        UnsupportedDeviceFault,
        _boundaries,
        _device_rules,
        apply_plan_at,
        endpoint_slots,
    )
    from ..sim.driver import Simulator
    from ..sim.engine import SimConfig
    from ..types import PutAck

    plan_spec = spec["plan"]
    rule_specs = plan_spec.get("rules", [])
    base = {k: v for k, v in plan_spec.items() if k != "rules"}
    serving_specs = [r for r in rule_specs if _is_serving_rule(r)]
    device_specs = [r for r in rule_specs if not _is_serving_rule(r)]
    serving_plan = (
        FaultPlan.from_json({**base, "rules": serving_specs})
        if serving_specs else None
    )
    device_plan = FaultPlan.from_json({**base, "rules": device_specs})

    capacity = spec.get("capacity", 5)
    # "fd_gray_confirm" > 0 runs the probe with the adaptive FD's sim-plane
    # mirror on (engine.py gray streak path) -- the seam the regression
    # suite uses to pin that adaptation does not perturb probe verdicts
    sim = Simulator(
        spec.get("n", 4), capacity=capacity,
        config=SimConfig(
            capacity=capacity,
            fd_gray_confirm=spec.get("fd_gray_confirm", 0),
            fd_gray_warmup=spec.get("fd_gray_warmup", 3),
            forensics=bool(spec.get("forensics", False)),
        ),
        seed=SIM_SEED,
    ).ready()
    sim.enable_placement(**SIM_PLACEMENT)
    sim.enable_handoff(chunk_size=1024)
    sim.enable_serving(request_ms=1, fault_plan=serving_plan)
    hier_cells = max(
        (int(r.get("cells", 0)) for r in rule_specs
         if r.get("type") == "CellPartitionRule"),
        default=0,
    )
    if hier_cells:
        # cell-partition plans run the hierarchy mirror so the composed
        # global view's incremental maintenance is under oracle
        sim.enable_hierarchy(cells=hier_cells)
    seated = endpoint_slots(sim)
    restart_victims = sorted({
        seated[r.match.dst] for r in device_plan.rules
        if isinstance(r, RestartNodeRule) and r.match.dst in seated
    })
    if restart_victims:
        # restart-bearing plans run the durability mirror so each victim's
        # replay debt is billed on the virtual clock at recovery
        sim.enable_durability(replay_record_ms=1)

    rnd = random.Random(int(plan_spec.get("seed", 0)) * 2_000_003 + 29)
    keys = [b"sk-%02d" % i for i in range(spec.get("keys", 8))]
    history: List[ClientOp] = []

    def do_ops(count: int) -> None:
        for _ in range(count):
            key = keys[rnd.randrange(len(keys))]
            invoke = sim.virtual_ms
            if rnd.random() < 0.55:
                value = b"sv-%d" % len(history)
                ack = sim.serving_put(key, value)
                history.append(ClientOp(
                    "sim", "put", key, value, ack.version, ack.status,
                    invoke, sim.virtual_ms,
                ))
            else:
                ack = sim.serving_get(key)
                history.append(ClientOp(
                    "sim", "get", key, ack.value, ack.version, ack.status,
                    invoke, sim.virtual_ms,
                ))

    horizon = spec.get("horizon_ms", 20_000)
    ops = spec.get("ops", 30)
    slots = endpoint_slots(sim)
    round_ms = sim.config.fd_interval_ms // sim.config.rounds_per_interval
    info: dict = {"harness": "sim"}
    try:
        rules = _device_rules(device_plan, round_ms)
    except UnsupportedDeviceFault as exc:
        # a mutated plan can drift outside the device-compilable subset;
        # report it as an empty probe rather than crashing the hunt
        return ProbeResult(
            coverage=frozenset(), violations=(),
            info={**info, "unsupported": str(exc)},
        )
    do_ops(max(1, ops // 4))
    # segment loop (replay_on_simulator's shape) with workload interleaved
    # at every fault-schedule boundary
    epoch = sim.virtual_ms
    times = _boundaries(rules, horizon, round_ms)
    per_segment = max(1, ops // (2 * max(1, len(times) - 1)))
    for seg_start, seg_end in zip(times, times[1:]):
        apply_plan_at(sim, device_plan, seg_start, slots)
        do_ops(per_segment)
        target = epoch + seg_end
        while sim.virtual_ms < target:
            remaining = math.ceil((target - sim.virtual_ms) / round_ms)
            if sim.run_until_decision(
                max_rounds=remaining, batch=min(8, remaining)
            ) is None:
                break
        do_ops(per_segment)
    # heal and settle, then read back every acked key: the oracle reads
    # become history entries the linearizability checker judges
    sim.clear_link_faults()
    sim.run_until_decision(max_rounds=40, batch=8)
    if restart_victims:
        # the compiled down-windows have closed; now take each victim
        # through an actual crash-and-replay so the recovery path (and its
        # virtual-time bill) lands in the same probe history
        info["replayed_records"] = sum(
            sim.restart_slot(slot) for slot in restart_victims
        )
        sim.run_until_decision(max_rounds=8, batch=4)
    # everything from here on is post-heal, post-restart, post-settle: the
    # tail the metastable-recovery check holds to the pre-fault baseline
    healed_ms = sim.virtual_ms
    do_ops(max(1, ops // 4))
    for key in sorted(sim.serving_acked):
        invoke = sim.virtual_ms
        ack = sim.serving_get(key)
        history.append(ClientOp(
            "sim", "get", key, ack.value, ack.version, ack.status,
            invoke, sim.virtual_ms,
        ))

    stamped = sim.configuration_id()
    sim._config_id = None  # noqa: SLF001 -- drop the memo, force the fold
    sim._spec = None  # noqa: SLF001
    checks = [
        lambda: check_linearizable_history(history),
        lambda: check_config_parity(stamped, sim.configuration_id()),
    ]
    if sim.hierarchy_enabled:
        # the incrementally maintained composition must match a
        # from-scratch recompute over the surviving slots (and every cell
        # must name exactly one live leader)
        checks.append(
            lambda: check_hierarchy_agreement(_sim_hierarchy_digests(sim))
        )
    if restart_victims:
        acked_versions = {
            key: version for key, (version, _v) in sim.serving_acked.items()
        }
        checks.append(
            lambda: check_durability(
                acked_versions, _sim_durable_versions(sim)
            )
        )
    if not serving_specs:
        # with lossy Put replication a minority replica may legitimately
        # lag until the next reconcile; fingerprints must agree only when
        # every replication write went through
        checks.append(
            lambda: check_fingerprint_agreement(_sim_fingerprints(sim))
        )
    pure_gray, victims = _gray_plan_victims(device_plan)
    if pure_gray and victims is not None:
        # the sim probe never joins, so every cut entry is an eviction;
        # rule dsts map to slots through the same seated-identity table
        # apply_plan_at compiles the rules with
        victim_labels = {
            f"slot{slots[v]}" for v in victims if v in slots
        }
        evicted_labels = [
            f"slot{int(c)}"
            for rec in sim.view_changes
            for c in rec.cut.reshape(-1)
        ]
        checks.append(
            lambda: check_gray_collateral(victim_labels, evicted_labels)
        )
    spans = [
        span for span in (
            _plan_fault_span(device_plan),
            _plan_fault_span(serving_plan) if serving_plan is not None
            else None,
        ) if span is not None
    ]
    if spans and (serving_plan is None or len(spans) == 2):
        # every window across both plan halves is bounded: the post-heal
        # tail must see goodput back at the pre-fault baseline.
        # Serving-nemesis windows run on their own arm epoch (slightly
        # before the workload epoch), so folding them onto the workload
        # epoch only widens the baseline exclusion -- conservative.
        faulted_from = epoch + min(s[0] for s in spans)
        healed_at = max(healed_ms, epoch + max(s[1] for s in spans))
        checks.append(
            lambda: check_metastable_recovery(
                history,
                faulted_from_ms=faulted_from,
                healed_at_ms=healed_at,
            )
        )
    violations = _collect(checks)
    snapshot = {name: sim.metrics.get(name) for name in COVERAGE_METRICS}
    coverage = (
        coverage_from_journal(sim.recorder.tail(4096))
        | coverage_from_metrics(snapshot)
        | coverage_from_fault_actions(sim.metrics.snapshot())
    )
    acked = sum(
        1 for o in history
        if o.op == "put" and o.status == PutAck.STATUS_OK
    )
    info = {
        **info,
        "history": len(history),
        "acked_puts": acked,
        "virtual_ms": sim.virtual_ms,
        "view_changes": len(sim.view_changes),
    }
    if violations and spec.get("forensics"):
        info["bundle"] = _witness_bundle(
            "sim", sim.recorder.tail(4096), sim.metrics.snapshot(),
            violations, "sim", hlc=sim.hlc,
        )
    return ProbeResult(
        coverage=coverage,
        violations=tuple(violations),
        info=info,
    )


def _sim_durable_versions(sim) -> dict:
    """Per key, the highest version held in any live replica's store -- the
    sim-probe ground truth for the durability invariant."""
    from ..serving.kv import decode_kv

    assign = sim.placement.assign
    out: dict = {}
    for p in range(assign.shape[0]):
        for slot in assign[p]:
            slot = int(slot)
            if slot < 0 or not sim.alive[slot]:
                continue
            for key, (version, _value) in decode_kv(
                sim.handoff_stores[slot].get(p)
            ).items():
                if version > out.get(key, 0):
                    out[key] = version
    return out


def _sim_hierarchy_digests(sim) -> dict:
    """Two composition sources the hierarchy checker must see agree: the
    sim's incrementally maintained rows, and a from-scratch recompute over
    the live slots. Divergence means the incremental path dropped or
    misattributed a churn edge."""
    def digest() -> Tuple[Tuple[int, ...], Tuple[str, ...], int]:
        rows = sim.hierarchy_rows()
        return (
            tuple(r.cell for r in rows),
            tuple(r.leader for r in rows),
            sim.global_fingerprint(),
        )

    incremental = digest()
    for cell in range(sim._hier_n_cells):  # noqa: SLF001
        sim._hierarchy_recompute_cell(cell)  # noqa: SLF001
    return {"incremental": incremental, "recomputed": digest()}


def _sim_fingerprints(sim) -> List[Tuple[int, str, object]]:
    assign = sim.placement.assign
    out: List[Tuple[int, str, object]] = []
    for p in range(assign.shape[0]):
        for slot in assign[p]:
            if slot < 0:
                continue
            out.append((p, f"slot{int(slot)}", sim.handoff_stores[int(slot)].get(p)))
    return out
