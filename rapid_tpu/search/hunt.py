"""The budgeted hunter: generate -> probe -> cover -> shrink -> pin.

Each round the hunter either mutates a corpus member (guided mode, with
probability MUTATE_P once the corpus is non-empty) or samples a fresh
plan. A probe whose coverage contributes any unvisited signal joins the
mutation corpus -- that bias is the whole difference between guided and
unguided search, and the guided-beats-unguided transition-count test in
tests/test_search.py is the contract. The first probe violating each
invariant kind is handed to the shrinker; the minimized spec is what
gets pinned to the corpus directory.

Everything is deterministic per (seed, budget, harness): plan sampling,
probe execution, corpus growth, shrink order.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import FrozenSet, List, Optional

from .coverage import transitions
from .fabric import fabric_endpoints
from .generator import PlanGenerator
from .runner import run_probe
from .shrinker import shrink_spec

MUTATE_P = 0.6

# default probe shapes per harness (spread over a spec by _spec_for)
ENGINE_DEFAULTS = {
    "n": 5, "partitions": 16, "replicas": 3,
    "horizon_ms": 4000, "ops": 40, "keys": 6,
}
SIM_DEFAULTS = {
    "n": 4, "capacity": 5, "horizon_ms": 20_000, "ops": 30, "keys": 8,
}


def harness_endpoints(harness: str, probe_defaults: dict) -> List[str]:
    if harness == "engine":
        return [str(ep) for ep in fabric_endpoints(probe_defaults["n"])]
    # sim endpoints are the Simulator's synthesized identities
    # (VirtualCluster.synthesize: "10.a.b.c" hostname, port 5000 + slot);
    # they depend only on capacity, which is fixed per harness defaults
    return [
        f"10.{(i >> 16) & 0xFF}.{(i >> 8) & 0xFF}.{i & 0xFF}:{5000 + i % 1000}"
        for i in range(probe_defaults["capacity"])
    ]


@dataclass
class HuntReport:
    seed: int
    harness: str
    guided: bool
    budget: int
    probes: int = 0
    coverage: FrozenSet[tuple] = frozenset()
    corpus: List[dict] = field(default_factory=list)
    violations: List[dict] = field(default_factory=list)
    pinned: List[dict] = field(default_factory=list)

    def transition_count(self) -> int:
        return len(transitions(self.coverage))

    def to_json(self) -> dict:
        return {
            "seed": self.seed,
            "harness": self.harness,
            "guided": self.guided,
            "budget": self.budget,
            "probes": self.probes,
            "coverage_signals": len(self.coverage),
            "event_transitions": self.transition_count(),
            "corpus": len(self.corpus),
            "violations": self.violations,
            "pinned": self.pinned,
        }

    def report_text(self) -> str:
        lines = [
            f"hunt: seed={self.seed} harness={self.harness} "
            f"{'guided' if self.guided else 'unguided'} "
            f"budget={self.budget}",
            f"  probes run          {self.probes}",
            f"  coverage signals    {len(self.coverage)}",
            f"  event transitions   {self.transition_count()}",
            f"  corpus plans        {len(self.corpus)}",
            f"  violations          {len(self.violations)}",
        ]
        for entry in self.violations:
            kinds = sorted({v["invariant"] for v in entry["violations"]})
            lines.append(
                f"    probe {entry['probe']}: {', '.join(kinds)}"
            )
        for pin in self.pinned:
            lines.append(
                f"  pinned: {sorted(pin['kinds'])} with "
                f"{len(pin['spec']['plan']['rules'])} rule(s) "
                f"after {pin['shrink_probes']} shrink probes"
            )
        return "\n".join(lines)


class Hunter:
    def __init__(self, seed: int = 0, budget: int = 50,
                 harness: str = "engine", guided: bool = True,
                 shrink: bool = True, shrink_budget: int = 200,
                 probe_overrides: Optional[dict] = None,
                 forensics: bool = False) -> None:
        self.seed = int(seed)
        self.budget = int(budget)
        self.harness = harness
        self.guided = guided
        self.shrink = shrink
        self.shrink_budget = shrink_budget
        # forensics flag: probes run with the harness HLC mirror on, and
        # every shrunken witness is pinned WITH its evidence bundle (the
        # violating probe's journal + metrics under invariant_violation)
        self.forensics = bool(forensics)
        self.defaults = dict(
            ENGINE_DEFAULTS if harness == "engine" else SIM_DEFAULTS
        )
        if probe_overrides:
            self.defaults.update(probe_overrides)
        self.generator = PlanGenerator(
            seed,
            harness_endpoints(harness, self.defaults),
            self.defaults["horizon_ms"],
            harness,
        )

    def _spec_for(self, plan_json: dict) -> dict:
        spec = {"harness": self.harness, **self.defaults, "plan": plan_json}
        if self.forensics:
            # only stamped when on, so flag-off specs (and the corpus
            # artifacts pinned from them) are byte-identical to before
            spec["forensics"] = True
        return spec

    def run(self) -> HuntReport:
        report = HuntReport(
            seed=self.seed, harness=self.harness, guided=self.guided,
            budget=self.budget,
        )
        rnd = random.Random(self.seed * 9_176 + 1)
        coverage: set = set()
        seen_kinds: set = set()
        for i in range(self.budget):
            if (
                self.guided and report.corpus
                and rnd.random() < MUTATE_P
            ):
                base = report.corpus[rnd.randrange(len(report.corpus))]
                plan_json = self.generator.mutate(base["plan"], i)
            else:
                rnd.random()  # keep the decision stream aligned
                plan_json = self.generator.fresh(i)
            spec = self._spec_for(plan_json)
            result = run_probe(spec)
            report.probes += 1
            fresh_signals = result.coverage - coverage
            coverage |= result.coverage
            if fresh_signals:
                report.corpus.append({
                    "plan": plan_json,
                    "probe": i,
                    "new_signals": len(fresh_signals),
                })
            if result.violations:
                entry = {
                    "probe": i,
                    "spec": spec,
                    "violations": list(result.violations),
                }
                report.violations.append(entry)
                kinds = frozenset(
                    v["invariant"] for v in result.violations
                )
                if self.shrink and not kinds <= seen_kinds:
                    seen_kinds |= kinds
                    shrunk, spent = shrink_spec(
                        spec, target_kinds=kinds,
                        max_probes=self.shrink_budget,
                    )
                    pin = {
                        "kinds": sorted(kinds),
                        "spec": shrunk,
                        "shrink_probes": spent,
                    }
                    if self.forensics:
                        # one confirming replay of the minimized spec pins
                        # the witness WITH its forensic evidence bundle
                        witness = run_probe(shrunk)
                        bundle = witness.info.get("bundle")
                        if bundle is not None:
                            pin["bundle"] = bundle
                    report.pinned.append(pin)
        report.coverage = frozenset(coverage)
        return report


def pin_to_file(pin: dict, path: str, name: str, description: str) -> None:
    """Write one shrunk violation as a corpus artifact (the format
    scenarios/corpus/ files use). A pin carrying a forensic evidence
    bundle (forensics-flagged hunts) additionally writes the bundle as a
    ``.bundle.json`` sidecar next to the artifact, readable by
    ``tools/forensics.py report``."""
    artifact = {
        "name": name,
        "description": description,
        "expect": {"invariants": pin["kinds"]},
        **pin["spec"],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=2, sort_keys=True)
        fh.write("\n")
    bundle = pin.get("bundle")
    if bundle is not None:
        from ..forensics.bundle import write_bundle

        write_bundle(bundle, path + ".bundle.json")
