"""Invariant checkers for nemesis-search probes: pure data in, one typed
violation out.

Every checker takes run artifacts (operation histories, view tokens,
store fingerprints) as plain values and raises
:class:`InvariantViolation` on the first witness it finds, tagged with
which invariant fired -- the search keys its corpus and the shrinker
keys its "still reproduces?" predicate on that tag, never on message
text. A checker that passes returns ``None``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Mapping, Sequence, Tuple

from ..types import PutAck

# the closed set of invariant tags (violations are keyed on these)
INVARIANTS = (
    "linearizability",
    "view-agreement",
    "config-parity",
    "fingerprint-agreement",
    "gray-collateral",
    "durability",
    "metastable-recovery",
    "hierarchy-agreement",
)


class InvariantViolation(AssertionError):
    """One invariant, one witness. ``invariant`` is the INVARIANTS tag
    that fired; ``detail`` is the human-readable witness."""

    def __init__(self, invariant: str, detail: str) -> None:
        assert invariant in INVARIANTS, invariant
        super().__init__(f"{invariant}: {detail}")
        self.invariant = invariant
        self.detail = detail

    def to_json(self) -> dict:
        return {"invariant": self.invariant, "detail": self.detail}


@dataclass(frozen=True)
class ClientOp:
    """One completed client operation in a multi-client history. ``status``
    is a PutAck status; ops that never completed (sender timeout) carry no
    linearizability obligation and should not appear here."""

    client: str
    op: str                 # "put" | "get"
    key: bytes
    value: bytes
    version: int
    status: int
    invoke_ms: int
    complete_ms: int


def check_linearizable_single_client(history) -> None:
    """Per-key linearizability for a single sequential client (the seed of
    ROADMAP item 5's checker): acked-put versions strictly increase, and
    every successful read returns either the latest acked write or a newer
    version whose value matches a write the client attempted (a RETRY'd put
    that partially replicated is allowed to surface -- it is a concurrent
    write, not a corruption)."""
    acked: dict = {}
    attempted: dict = {}
    for op, key, value, version, status in history:
        if op == "put":
            attempted.setdefault(key, set()).add(value)
            if status == PutAck.STATUS_OK:
                prev = acked.get(key)
                assert prev is None or version > prev[0], (
                    f"acked version regressed on {key!r}"
                )
                acked[key] = (version, value)
        elif op == "get" and status == PutAck.STATUS_OK:
            prev = acked.get(key)
            if prev is None:
                assert value in attempted.get(key, set()), (
                    f"read of {key!r} returned a value never written"
                )
                continue
            assert version >= prev[0], (
                f"stale read on {key!r}: {version} < acked {prev[0]}"
            )
            if version == prev[0]:
                assert value == prev[1], f"torn read on {key!r}"
            else:
                assert value in attempted[key], (
                    f"read of {key!r} returned a value never written"
                )


def check_linearizable_history(history: Sequence[ClientOp]) -> None:
    """Multi-client per-key linearizability over a completed-op history,
    generalizing :func:`check_linearizable_single_client` to concurrent
    clients via real-time (invoke/complete) order:

    * acked-put versions on one key are unique (two OK acks for the same
      version is a double-leader / split-brain write);
    * acked puts respect real time (a put that completed before another
      began must carry the lower version);
    * a successful read invoked after an acked put completed sees at least
      that version (NOT_FOUND there is a lost acked write; a lower OK
      version is a stale read);
    * a read matching an acked version returns that write's bytes (torn
      read), and any unmatched value must be one some client attempted;
    * reads of one key do not travel backwards in real time.
    """
    by_key: Dict[bytes, List[ClientOp]] = {}
    for entry in history:
        by_key.setdefault(entry.key, []).append(entry)
    for key in sorted(by_key):
        ops = sorted(by_key[key], key=lambda o: (o.invoke_ms, o.complete_ms))
        _check_key_linearizable(key, ops)


def _check_key_linearizable(key: bytes, ops: Sequence[ClientOp]) -> None:
    acked = [o for o in ops if o.op == "put" and o.status == PutAck.STATUS_OK]
    attempted = {o.value for o in ops if o.op == "put"}
    by_version: Dict[int, ClientOp] = {}
    for put in acked:
        prior = by_version.get(put.version)
        if prior is not None:
            raise InvariantViolation(
                "linearizability",
                f"double-leader write on {key!r}: version {put.version} "
                f"acked for {prior.client} ({prior.value!r}) and "
                f"{put.client} ({put.value!r})",
            )
        by_version[put.version] = put
    for a in acked:
        for b in acked:
            if a is b:
                continue  # a 0-ms local-apply ack must not conflict with itself
            if a.complete_ms <= b.invoke_ms and a.version >= b.version:
                raise InvariantViolation(
                    "linearizability",
                    f"acked writes on {key!r} out of real-time order: "
                    f"version {a.version} completed at {a.complete_ms}ms "
                    f"but version {b.version} began at {b.invoke_ms}ms",
                )
    reads = [
        o for o in ops
        if o.op == "get" and o.status in (PutAck.STATUS_OK, PutAck.STATUS_NOT_FOUND)
    ]
    for read in reads:
        floor = max(
            (w.version for w in acked if w.complete_ms <= read.invoke_ms),
            default=0,
        )
        seen = read.version if read.status == PutAck.STATUS_OK else 0
        if seen < floor:
            kind = (
                "lost acked write" if read.status == PutAck.STATUS_NOT_FOUND
                else "stale read"
            )
            raise InvariantViolation(
                "linearizability",
                f"{kind} on {key!r}: client {read.client} saw version "
                f"{seen} after version {floor} was acked",
            )
        if read.status == PutAck.STATUS_OK:
            write = by_version.get(read.version)
            if write is not None and read.value != write.value:
                raise InvariantViolation(
                    "linearizability",
                    f"torn read on {key!r}: version {read.version} returned "
                    f"{read.value!r}, acked write was {write.value!r}",
                )
            if write is None and read.value not in attempted:
                raise InvariantViolation(
                    "linearizability",
                    f"read of {key!r} returned {read.value!r}, a value "
                    f"never written by any client",
                )
    for r1 in reads:
        for r2 in reads:
            if r1.complete_ms <= r2.invoke_ms:
                v1 = r1.version if r1.status == PutAck.STATUS_OK else 0
                v2 = r2.version if r2.status == PutAck.STATUS_OK else 0
                if v2 < v1:
                    raise InvariantViolation(
                        "linearizability",
                        f"non-monotonic reads on {key!r}: version {v1} then "
                        f"version {v2} later in real time",
                    )


def check_gray_collateral(
    faulted: Iterable[object], evicted: Iterable[object],
) -> None:
    """Pure gray plans (slow_node / lossy_link only) injure performance,
    never liveness, so the only defensible eviction is of a node the plan
    faulted. ``faulted`` is the label set of every gray rule's dst,
    ``evicted`` the labels of every node a view change removed; an evicted
    label outside ``faulted`` is a collateral eviction -- a healthy node
    paying for someone else's grayness, the failure mode the adaptive FD's
    tier-relative scoring exists to prevent. Callers must skip the check
    (vacuous) when any gray rule carries ``dst=None``: an unscoped rule
    faults every link, so every member is legitimately suspect."""
    faulted_set = {str(f) for f in faulted}
    collateral = sorted(
        {str(e) for e in evicted if str(e) not in faulted_set}
    )
    if collateral:
        raise InvariantViolation(
            "gray-collateral",
            f"healthy nodes evicted under a pure gray plan: "
            f"{', '.join(collateral)} (faulted: {sorted(faulted_set)})",
        )


def check_durability(
    acked: Mapping[bytes, int],
    durable: Mapping[bytes, int],
    recovery_replicas: Iterable[Tuple[int, str, object]] = (),
) -> None:
    """Restart-survival invariant (ISSUE PR 16): every acked write outlives
    every restart, and a recovered node converges with its replica row.

    ``acked`` maps key -> highest version any client received an OK ack
    for; ``durable`` maps key -> highest version found in stable storage
    across the live replicas after the run quiesces. A key whose durable
    version trails its acked version is a lost acked write. Optional
    ``recovery_replicas`` is ``(partition, node, fingerprint)`` restricted
    to rows holding a recovered node; any fingerprint split there means
    recovery replayed to a state the row does not agree with."""
    for key in sorted(acked):
        floor = int(acked[key])
        held = int(durable.get(key, 0))
        if held < floor:
            raise InvariantViolation(
                "durability",
                f"lost acked write on {key!r}: version {floor} was acked "
                f"but stable storage holds {held if held else 'nothing'}",
            )
    by_partition: Dict[int, Dict[object, List[str]]] = {}
    for partition, node, fingerprint in recovery_replicas:
        by_partition.setdefault(int(partition), {}).setdefault(
            fingerprint, []
        ).append(node)
    for partition in sorted(by_partition):
        holders = by_partition[partition]
        if len(holders) > 1:
            detail = "; ".join(
                f"{fp!r} on {', '.join(sorted(nodes))}"
                for fp, nodes in sorted(holders.items(), key=lambda kv: repr(kv[0]))
            )
            raise InvariantViolation(
                "durability",
                f"recovered replica row diverged on partition {partition}: "
                f"{detail}",
            )


def goodput_samples(
    history: Sequence[ClientOp], bucket_ms: int = 256,
) -> List[Tuple[int, int, int]]:
    """Fold a completed-op history into ``(bucket_start_ms, offered, good)``
    samples on a fixed-width time grid -- the goodput SLI derived from the
    probe's own client history (invoke time counts the op as offered; an
    OK completion, or NOT_FOUND for a read, counts it as good)."""
    buckets: Dict[int, List[int]] = {}
    for o in history:
        start = (int(o.invoke_ms) // int(bucket_ms)) * int(bucket_ms)
        row = buckets.setdefault(start, [0, 0])
        row[0] += 1
        if o.status == PutAck.STATUS_OK or (
            o.op == "get" and o.status == PutAck.STATUS_NOT_FOUND
        ):
            row[1] += 1
    return sorted((b, row[0], row[1]) for b, row in buckets.items())


def check_metastable_recovery(
    history: Sequence[ClientOp],
    *,
    faulted_from_ms: int,
    healed_at_ms: int,
    min_ops: int = 8,
    margin: float = 0.25,
    baseline_floor: float = 0.9,
) -> None:
    """Metastability invariant: once the injected faults have cleared and
    offered load is back to its baseline shape, the goodput SLI must
    return to (near) its pre-fault baseline. A system that stays degraded
    after the trigger is gone -- retry storms, stuck redirect loops, a
    leader map that never repoints -- is in a metastable failure state,
    the class of outage the SLO plane's burn alerts exist to catch.

    ``faulted_from_ms`` is when the first fault window opened (ops invoked
    strictly before it form the baseline); ``healed_at_ms`` is when the
    caller knows every fault had cleared AND recovery had a settle period
    (ops invoked at/after it form the tail). Conservative by design: with
    fewer than ``min_ops`` in either segment, or a baseline already below
    ``baseline_floor`` goodput, the check is vacuous -- it judges
    *recovery*, not the outage itself."""

    def ratio(ops: List[ClientOp]) -> float:
        good = sum(
            1 for o in ops
            if o.status == PutAck.STATUS_OK
            or (o.op == "get" and o.status == PutAck.STATUS_NOT_FOUND)
        )
        return good / len(ops)

    baseline = [o for o in history if o.invoke_ms < faulted_from_ms]
    tail = [o for o in history if o.invoke_ms >= healed_at_ms]
    if len(baseline) < min_ops or len(tail) < min_ops:
        return
    base_ratio = ratio(baseline)
    if base_ratio < baseline_floor:
        return
    tail_ratio = ratio(tail)
    if tail_ratio < base_ratio - margin:
        raise InvariantViolation(
            "metastable-recovery",
            f"goodput stuck at {tail_ratio:.3f} after faults cleared at "
            f"{healed_at_ms}ms (baseline {base_ratio:.3f} before "
            f"{faulted_from_ms}ms, margin {margin}): the system did not "
            f"recover once offered load returned to baseline",
        )


def check_view_agreement(views: Mapping[str, object]) -> None:
    """Every node must report the same view token (configuration id, map
    version, membership digest -- any comparable value)."""
    groups: Dict[str, List[str]] = {}
    for node in sorted(views):
        groups.setdefault(repr(views[node]), []).append(node)
    if len(groups) > 1:
        parts = "; ".join(
            f"{token} on {', '.join(nodes)}"
            for token, nodes in sorted(groups.items())
        )
        raise InvariantViolation(
            "view-agreement",
            f"{len(groups)} distinct views across {len(views)} nodes: "
            f"{parts}",
        )


def check_leader_agreement(
    digests: Mapping[str, Tuple[Sequence[int], Sequence[str]]],
) -> None:
    """``leader_digest()`` per node: any two members replicating the same
    partition must name the same leader (split-brain check)."""
    claims: Dict[int, Dict[str, str]] = {}
    for node in sorted(digests):
        partitions, leaders = digests[node]
        for p, leader in zip(partitions, leaders):
            claims.setdefault(int(p), {})[node] = leader
    for p in sorted(claims):
        named = sorted(set(claims[p].values()))
        if len(named) > 1:
            raise InvariantViolation(
                "view-agreement",
                f"split-brain on partition {p}: leaders {named} claimed "
                f"by {sorted(claims[p])}",
            )


def check_hierarchy_agreement(
    digests: Mapping[str, Tuple[Sequence[int], Sequence[str], int]],
) -> None:
    """``(global_cells, global_leaders, global_fingerprint)`` per node --
    the hierarchy plane's status digest (ClusterStatusResponse fields, or
    HierarchyPlane.status_fields in-process). Two invariants:

    * **composed-view convergence**: once quiesced, every member's
      composed global view folds to the same fingerprint over the same
      cell set (everyone adopted the parent decision);
    * **single live leader per cell**: no two members name different
      leaders for one cell -- a cell partition may stall the composition
      but must never split a cell's leadership (leader order is a pure
      function of the cell view, so disagreement means the views split).
    """
    fingerprints: Dict[int, List[str]] = {}
    claims: Dict[int, Dict[str, str]] = {}
    for node in sorted(digests):
        cells, leaders, fingerprint = digests[node]
        fingerprints.setdefault(int(fingerprint), []).append(node)
        for cell, leader in zip(cells, leaders):
            claims.setdefault(int(cell), {})[node] = leader
    for cell in sorted(claims):
        named = sorted(set(claims[cell].values()))
        if len(named) > 1:
            raise InvariantViolation(
                "hierarchy-agreement",
                f"two live leaders for cell {cell}: {named} claimed by "
                f"{sorted(claims[cell])}",
            )
    if len(fingerprints) > 1:
        parts = "; ".join(
            f"{fp} on {', '.join(nodes)}"
            for fp, nodes in sorted(fingerprints.items())
        )
        raise InvariantViolation(
            "hierarchy-agreement",
            f"composed global views diverged across "
            f"{len(digests)} members: {parts}",
        )


def check_config_parity(stamped: int, recomputed: int) -> None:
    """The configuration id a decision stamped must equal the id
    recomputed from the decided membership."""
    if int(stamped) != int(recomputed):
        raise InvariantViolation(
            "config-parity",
            f"decided configuration id {stamped} != recomputed "
            f"{recomputed}",
        )


def check_fingerprint_agreement(
    replicas: Iterable[Tuple[int, str, object]],
) -> None:
    """``(partition, node, fingerprint)`` triples: every replica of one
    partition must hold byte-identical content once the system quiesces."""
    by_partition: Dict[int, Dict[object, List[str]]] = {}
    for partition, node, fingerprint in replicas:
        by_partition.setdefault(int(partition), {}).setdefault(
            fingerprint, []
        ).append(node)
    for partition in sorted(by_partition):
        holders = by_partition[partition]
        if len(holders) > 1:
            detail = "; ".join(
                f"{fp!r} on {', '.join(sorted(nodes))}"
                for fp, nodes in sorted(holders.items(), key=lambda kv: repr(kv[0]))
            )
            raise InvariantViolation(
                "fingerprint-agreement",
                f"partition {partition} diverged across replicas: {detail}",
            )
