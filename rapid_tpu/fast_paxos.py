"""Leaderless Fast Paxos: one-step consensus by counting identical proposals.

Reference: FastPaxos.java. Every node broadcasts its cut proposal as a
fast-round phase2b vote; any node that observes >= N - F identical votes
(F = floor((N-1)/4), FastPaxos.java:145-150) decides in one step. A classic
Paxos round (round >= 2) is scheduled as fallback after a base delay plus an
exponentially distributed jitter with mean N seconds, so that cluster-wide
roughly one node per second starts a recovery round (FastPaxos.java:72-76,
200-203).
"""

from __future__ import annotations

import math
import random
from typing import Callable, Dict, List, Optional, Set

from .messaging.base import IBroadcaster, IMessagingClient
from .observability import Metrics, Tracer
from .paxos import Paxos, Proposal
from .runtime.scheduler import ScheduledTask, Scheduler
from .types import (
    ConsensusResponse,
    Endpoint,
    FastRoundPhase2bMessage,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
)

BASE_DELAY_MS = 1000


class FastPaxos:  # guarded-by: protocol-executor
    def __init__(
        self,
        my_addr: Endpoint,
        configuration_id: int,
        membership_size: int,
        client: IMessagingClient,
        broadcaster: IBroadcaster,
        scheduler: Scheduler,
        on_decide: Callable[[List[Endpoint]], None],
        consensus_fallback_base_delay_ms: int = BASE_DELAY_MS,
        rng: Optional[random.Random] = None,
        metrics: Optional[Metrics] = None,
        tracer: Optional[Tracer] = None,
        serialize: Optional[Callable[[Callable[[], None]], None]] = None,
    ) -> None:
        self._metrics = metrics
        self._tracer = tracer
        self._my_addr = my_addr
        self._configuration_id = configuration_id
        self._n = membership_size
        self._broadcaster = broadcaster
        self._scheduler = scheduler
        # Consensus state is protocol-executor confined; the classic-round
        # fallback timer fires on the scheduler thread in real deployments,
        # so it re-enters through this serializer (the service injects
        # protocol_executor.execute). Default: direct call, for the
        # single-threaded virtual plane and standalone tests.
        self._serialize = serialize if serialize is not None else (lambda fn: fn())
        self._base_delay_ms = consensus_fallback_base_delay_ms
        self._rng = rng if rng is not None else random.Random()
        # Mean of the expovariate jitter is N seconds => ~one classic-round
        # start per second cluster-wide (FastPaxos.java:72-76).
        self._jitter_rate = 1.0 / membership_size
        self._votes_per_proposal: Dict[Proposal, int] = {}
        self._votes_received: Set[Endpoint] = set()
        self._decided = False
        self._scheduled_classic_round: Optional[ScheduledTask] = None

        def on_decided_wrapped(hosts: List[Endpoint]) -> None:
            # A classic-round decision can arrive after a fast-round one (the
            # inner Paxos tracks its own decided flag); deliver only the first.
            if self._decided:
                return
            self._decided = True
            if self._scheduled_classic_round is not None:
                self._scheduled_classic_round.cancel()
            on_decide(hosts)

        self._on_decided_wrapped = on_decided_wrapped
        self._paxos = Paxos(
            my_addr, configuration_id, membership_size, client, broadcaster,
            on_decided_wrapped, metrics=metrics, tracer=tracer,
        )

    @property
    def decided(self) -> bool:
        return self._decided

    @property
    def votes_received(self) -> int:
        """Distinct fast-round voters tallied so far (introspection RPC)."""
        return len(self._votes_received)

    def propose(self, proposal: List[Endpoint], recovery_delay_ms: Optional[int] = None) -> None:
        """Vote for ``proposal`` in the fast round and schedule the classic-round
        fallback (FastPaxos.java:94-117)."""
        self._paxos.register_fast_round_vote(tuple(proposal))
        self._broadcaster.broadcast(
            FastRoundPhase2bMessage(
                sender=self._my_addr,
                configuration_id=self._configuration_id,
                endpoints=tuple(proposal),
            )
        )
        if recovery_delay_ms is None:
            recovery_delay_ms = self._random_delay_ms()
        self._scheduled_classic_round = self._scheduler.schedule(
            recovery_delay_ms, self._classic_round_fallback
        )

    def _handle_fast_round_proposal(self, msg: FastRoundPhase2bMessage) -> None:
        """Tally a fast-round vote; decide at the 3/4 supermajority
        (FastPaxos.java:125-156)."""
        if msg.configuration_id != self._configuration_id:
            return
        if msg.sender in self._votes_received:
            return
        if self._decided:
            return
        self._votes_received.add(msg.sender)
        if self._metrics is not None:
            self._metrics.incr("consensus.fast_round_votes")
        count = self._votes_per_proposal.get(msg.endpoints, 0) + 1
        self._votes_per_proposal[msg.endpoints] = count
        f = (self._n - 1) // 4  # Fast Paxos resiliency
        if len(self._votes_received) >= self._n - f:
            if count >= self._n - f:
                if self._metrics is not None:
                    self._metrics.incr("consensus.fast_decisions")
                if self._tracer is not None:
                    self._tracer.event("fast_decision", votes=count)
                self._on_decided_wrapped(list(msg.endpoints))
            # else: fast round may not succeed; fallback will recover

    def handle_messages(self, msg) -> ConsensusResponse:
        """Demux consensus messages (FastPaxos.java:163-184)."""
        if isinstance(msg, FastRoundPhase2bMessage):
            self._handle_fast_round_proposal(msg)
        elif isinstance(msg, Phase1aMessage):
            self._paxos.handle_phase1a(msg)
        elif isinstance(msg, Phase1bMessage):
            self._paxos.handle_phase1b(msg)
        elif isinstance(msg, Phase2aMessage):
            self._paxos.handle_phase2a(msg)
        elif isinstance(msg, Phase2bMessage):
            self._paxos.handle_phase2b(msg)
        else:
            raise TypeError(f"unexpected consensus message: {type(msg).__name__}")
        return ConsensusResponse()

    def _classic_round_fallback(self) -> None:
        # runs on the timer thread; hop back onto the protocol serializer
        # before touching consensus state
        self._serialize(self.start_classic_paxos_round)

    def start_classic_paxos_round(self) -> None:
        """Fallback entry: classic rounds start at round 2 (FastPaxos.java:189-195)."""
        if not self._decided:
            if self._metrics is not None:
                self._metrics.incr("consensus.classic_rounds_started")
            self._paxos.start_phase1a(2)

    def _random_delay_ms(self) -> int:
        """Base delay + Exp(jitter_rate) jitter in ms (FastPaxos.java:200-203)."""
        jitter = int(-1000 * math.log(1 - self._rng.random()) / self._jitter_rate)
        return jitter + self._base_delay_ms
