"""The membership protocol engine.

Reference: MembershipService.java -- the single dispatch point for all protocol
messages (:171-193), join gatekeeping (:200-286), alert batching (:602-626),
cut-detector driving (:297-348), view-change application (:379-433), failure
detector lifecycle (:686-703) and event subscriptions.

Threading model: every handler body hops onto the node's serialized protocol
executor, exactly like the reference's single-threaded protocolExecutor
(SharedResources.java:53, MembershipService.java:68-72). Under the virtual-time
scheduler this additionally makes whole-cluster runs deterministic.
"""

from __future__ import annotations

import logging
import random
from typing import Callable, Dict, List, Optional, Set, Tuple

from .cut_detector import MultiNodeCutDetector
from .events import ClusterEvents, NodeStatusChange
from .fast_paxos import FastPaxos
from .forensics.bundle import build_bundle, capture_local_evidence
from .forensics.hlc import HlcClock, hlc_of, stamp_hlc
from .handoff.engine import HandoffEngine
from .handoff.store import PartitionStore
from .hashing import endpoint_hash, to_signed
from .membership import MembershipView
from .messaging.base import IBroadcaster, IMessagingClient
from .messaging.unicast import UnicastToAllBroadcaster
from .metadata import FrozenMetadata, MetadataManager
from .monitoring.base import IEdgeFailureDetectorFactory
from .observability import (
    DEFAULT_JOURNAL_CAPACITY,
    PARTITIONS_MOVED_BUCKETS,
    FlightRecorder,
    Metrics,
    MetricsHistory,
    StableViewTimer,
    TraceContext,
    Tracer,
    global_metrics,
    global_tracer,
    stamp_trace_context,
    trace_context_of,
)
from .placement.engine import (
    PlacementConfig,
    PlacementDiff,
    PlacementEngine,
    PlacementMap,
    weight_of,
)
from .runtime.futures import Promise, successful_as_list
from .runtime.lockdep import make_lock
from .runtime.resources import SharedResources
from .runtime.scheduler import ScheduledTask
from .serving.engine import ServingEngine
from .settings import Settings
from .slo.burn import SloPlane
from .hierarchy.plane import HierarchyPlane
from .hierarchy.routing import CellRouter, ParentChannel
from .types import (
    AlertMessage,
    BatchedAlertMessage,
    CONSENSUS_MESSAGE_TYPES,
    CellDigestMessage,
    ClusterStatusRequest,
    ClusterStatusResponse,
    ConsensusResponse,
    EdgeStatus,
    Endpoint,
    FastRoundPhase2bMessage,
    FastRoundVoteBatch,
    Get,
    GlobalViewMessage,
    GossipEnvelope,
    HandoffAck,
    HandoffRequest,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    LeaveMessage,
    MessageBatch,
    NodeId,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    Put,
    PutAck,
    RapidMessage,
    Response,
)

LOG = logging.getLogger(__name__)

SubscriptionCallback = Callable[[int, List[NodeStatusChange]], None]


def address_comparator_key(endpoint: Endpoint) -> int:
    """Seed-0 ring order, used to canonicalize proposals before consensus
    (MembershipService.java:340-342)."""
    return to_signed(endpoint_hash(endpoint.hostname, endpoint.port, 0))


def _chain_promise(inner: Promise, outer: Promise) -> None:
    """Propagate a completed inner promise (result or exception) onto the
    outer one the transport is watching."""
    exc = inner.exception()
    if exc is not None:
        outer.try_set_exception(exc)
    else:
        outer.try_set_result(inner._result)  # noqa: SLF001


class MembershipService:
    def __init__(
        self,
        my_addr: Endpoint,
        cut_detector: MultiNodeCutDetector,
        membership_view: MembershipView,
        resources: SharedResources,
        settings: Settings,
        client: IMessagingClient,
        edge_failure_detector: IEdgeFailureDetectorFactory,
        metadata_map: Optional[Dict[Endpoint, FrozenMetadata]] = None,
        subscriptions: Optional[Dict[ClusterEvents, List[SubscriptionCallback]]] = None,
        rng: Optional[random.Random] = None,
        broadcaster: Optional[IBroadcaster] = None,
        metrics: Optional[Metrics] = None,
        tracer: Optional[Tracer] = None,
        recorder: Optional[FlightRecorder] = None,
        placement: Optional[PlacementConfig] = None,
        handoff_store: Optional[PartitionStore] = None,
        serving: bool = False,
        hlc: Optional[HlcClock] = None,
    ) -> None:
        self._my_addr = my_addr
        self._cut_detection = cut_detector
        self._view = membership_view
        self._resources = resources
        self._scheduler = resources.scheduler
        self._settings = settings
        self._client = client
        self._fd_factory = edge_failure_detector
        self._rng = rng if rng is not None else random.Random()
        self._metadata_manager = MetadataManager()
        if metadata_map:
            self._metadata_manager.add_metadata(metadata_map)
        self._broadcaster = (
            broadcaster
            if broadcaster is not None
            else UnicastToAllBroadcaster(
                client, rng=self._rng, settings=settings,
                scheduler=resources.scheduler, my_addr=my_addr,
            )
        )
        # Hierarchy plane: cell-filtered broadcasts plus the two-level
        # composition engine (settings.hierarchy is the kill switch; None
        # keeps the exact flat path -- no wrapper on the broadcaster, no
        # new message types on the wire). The router confines every
        # protocol broadcast -- alerts, votes -- to this member's cell; the
        # parent channel is the leader's batched leader-to-leader fabric.
        self._hierarchy: Optional[HierarchyPlane] = None
        if settings.hierarchy.enabled:
            self._broadcaster = CellRouter(
                self._broadcaster, my_addr, settings.hierarchy.cells
            )
            self._hierarchy = HierarchyPlane(
                my_addr,
                channel=ParentChannel(
                    client, my_addr, scheduler=resources.scheduler,
                    flush_ms=settings.hierarchy.parent_flush_ms,
                ),
                cells=settings.hierarchy.cells,
                leaders_per_cell=settings.hierarchy.leaders_per_cell,
                eviction_rounds=settings.hierarchy.eviction_rounds,
            )
        self._subscriptions: Dict[ClusterEvents, List[SubscriptionCallback]] = {
            event: [] for event in ClusterEvents
        }
        if subscriptions:
            for event, callbacks in subscriptions.items():
                self._subscriptions[event].extend(callbacks)

        # Per-node registry/tracer attached (weakly) to the process-global
        # plane so exporters see every node merged while per-instance
        # snapshot()/get() stay isolated (telemetry plane, ARCHITECTURE.md).
        self.metrics = (
            metrics
            if metrics is not None
            else Metrics(parent=global_metrics(), plane="protocol",
                         node=str(my_addr))
        )
        self.tracer = (
            tracer
            if tracer is not None
            else Tracer(parent=global_tracer(), plane="protocol",
                        track=str(my_addr))
        )
        # detection -> decision -> view-installed latency on the scheduler
        # clock (virtual ms under the test harness, wall ms on real deploys)
        self._stable_view = StableViewTimer(
            self.metrics, "protocol", clock=self._scheduler.now_ms
        )
        # forensics plane: this node's hybrid logical clock (None keeps the
        # pre-forensics path byte-for-byte; outbound stamping happens in the
        # HlcStampingClient wrapper the builder installs, inbound merging in
        # handle_message below)
        self._hlc = hlc
        # the latest evidence bundle captured by an automatic trigger
        # (slo_burn today); Cluster.capture_bundle / agent --bundle-out
        # read it so an operator can fetch what the alert pinned
        self.last_bundle: Optional[Dict[str, object]] = None
        # bounded black-box journal of membership-relevant events, served
        # via the status RPC and dumpable on crash/exit; journal entries are
        # HLC-stamped when the forensics plane is on
        self.recorder = (
            recorder
            if recorder is not None
            else FlightRecorder(
                node=str(my_addr), clock=self._scheduler.now_ms,
                capacity=(settings.forensics.journal_capacity
                          if settings.forensics.enabled
                          else DEFAULT_JOURNAL_CAPACITY),
                hlc=hlc, metrics=self.metrics,
            )
        )
        # profiling plane: a metric history ring over this node's registry,
        # snapshotted opportunistically from the status RPC and served as
        # ClusterStatusResponse.history (settings.profiling is the kill
        # switch; None keeps the response field empty for old goldens)
        self._history: Optional[MetricsHistory] = None
        if settings.profiling.enabled:
            self._history = MetricsHistory(
                self.metrics,
                interval_s=settings.profiling.history_interval_ms / 1000.0,
                capacity=settings.profiling.history_capacity,
            )
        # SLO plane: online SLIs + multi-window burn-rate alerts over the
        # serving path, fed from _handle_serving on the scheduler clock and
        # digested into the status RPC (settings.slo is the kill switch;
        # None reproduces the exact pre-SLO path)
        self._slo: Optional[SloPlane] = None
        if settings.slo.enabled:
            self._slo = SloPlane(
                settings.slo, metrics=self.metrics, recorder=self.recorder
            )
            if settings.forensics.enabled:
                # forensics trigger: a burn alert firing pins a local-only
                # evidence bundle at the moment of the transition
                self._slo.on_transition = self._on_slo_transitions
        # the trace context of the churn this node is currently working on:
        # minted by the local fd_signal root or adopted from the first
        # traced alert/vote, carried onto outgoing alerts and the eventual
        # view_change span, cleared when the view installs. One Optional --
        # duplicated or reordered deliveries re-adopt idempotently (same
        # trace id) and can never grow state.
        self._churn_ctx: Optional[TraceContext] = None
        self._cut_detection.bind_telemetry(self.metrics, self.tracer)
        self._joiners_to_respond_to: Dict[Endpoint, List[Promise]] = {}
        self._joiner_uuid: Dict[Endpoint, NodeId] = {}
        self._joiner_metadata: Dict[Endpoint, FrozenMetadata] = {}
        self._announced_proposal = False
        # a decided proposal refused for missing joiner identities (the UP
        # alerts lost a race against the quorum of votes); retried when the
        # alerts land -- see _decide_view_change / _handle_batched_alerts
        self._pending_decision: Optional[List[Endpoint]] = None
        self._alert_send_queue: List[AlertMessage] = []
        self._last_enqueue_ms = -1
        self._failure_detector_jobs: List[ScheduledTask] = []
        self._shut_down = False

        self._alert_batcher_job = self._scheduler.schedule_at_fixed_rate(
            0, settings.batching_window_ms, self._alert_batcher_tick
        )
        # parent heartbeat: leaders advance their parent round and
        # re-announce every period so a whole lost cell ages out of the
        # composed view even when the survivors see no churn of their own
        self._hierarchy_job: Optional[ScheduledTask] = None
        if self._hierarchy is not None and settings.hierarchy.parent_round_ms > 0:
            self._hierarchy_job = self._scheduler.schedule_at_fixed_rate(
                settings.hierarchy.parent_round_ms,
                settings.hierarchy.parent_round_ms,
                self._hierarchy_tick,
            )
        self._broadcaster.set_membership(self._view.get_ring(0))
        self._fast_paxos = self._new_fast_paxos()
        self._create_failure_detectors()

        # Placement plane: a deterministic shard map recomputed at every
        # view install from (config id, sorted view, metadata weights, seed)
        # -- pure function of state every member agrees on, so no messages.
        self._placement = PlacementEngine(placement) if placement else None

        # Handoff plane: moves the partition bytes the placement diffs
        # imply. Requires placement (sessions launch off its diffs); the
        # engine shares this node's telemetry so sessions join churn traces.
        self._handoff: Optional[HandoffEngine] = None
        if handoff_store is not None:
            if self._placement is None:
                raise ValueError("handoff requires placement to be configured")
            self._handoff = HandoffEngine(
                handoff_store, my_addr, client, self._scheduler,
                metrics=self.metrics, tracer=self.tracer,
                recorder=self.recorder,
            )
            # durability plane: a durable store recovered before this node
            # had telemetry -- attach the registries now so recovery's
            # replay/truncation counters and the "durability_recovered"
            # journal line land on this node's observability plane
            bind = getattr(handoff_store, "bind_telemetry", None)
            if bind is not None:
                bind(self.metrics, self.recorder)

        # Serving plane: a replicated Get/Put KV store routed by the
        # placement map, persisting into the handoff plane's store so
        # view-change state transfer moves serving data through verified
        # handoff sessions (serving/engine.py).
        self._serving: Optional[ServingEngine] = None
        if serving:
            if self._placement is None or self._handoff is None:
                raise ValueError(
                    "serving requires placement and handoff to be configured"
                )
            self._serving = ServingEngine(
                handoff_store, my_addr, client, self._scheduler,
                metrics=self.metrics, tracer=self.tracer,
                recorder=self.recorder,
            )

        # Initial VIEW_CHANGE callbacks: start/join completed
        # (MembershipService.java:162-165)
        configuration_id = self._view.get_current_configuration_id()
        initial = [
            NodeStatusChange(node, EdgeStatus.UP, self._metadata_manager.get(node))
            for node in self._view.get_ring(0)
        ]
        self._fire(ClusterEvents.VIEW_CHANGE, configuration_id, initial)
        self._update_placement(configuration_id)
        if self._hierarchy is not None:
            # the start/join view counts as an install: compute leadership
            # and (if leading) announce this cell's row to the parent
            self._hierarchy.on_view_installed(
                self._view.get_ring(0), configuration_id
            )

    # ------------------------------------------------------------------ #
    # Message dispatch (MembershipService.java:171-193)
    # ------------------------------------------------------------------ #

    def handle_message(self, msg: RapidMessage) -> Promise:
        name = type(msg).__name__
        if isinstance(msg, GossipEnvelope) and msg.kind != GossipEnvelope.KIND_PAYLOAD:
            # payload-free anti-entropy control frames (IHAVE/PULL) are
            # counted apart: the redundancy measurement in
            # experiments/message_load.py compares payload receptions
            name += ".control"
        self.metrics.incr(f"messages.{name}")
        if self._hlc is not None:
            # HLC receive rule: fold the sender's stamp into the local clock
            # before any handler records journal events for this message, so
            # effects are always HLC-after their cause across nodes
            stamp = hlc_of(msg)
            if stamp is not None:
                self._hlc.merge(stamp)
        if isinstance(msg, PreJoinMessage):
            return self._handle_pre_join(msg)
        if isinstance(msg, JoinMessage):
            return self._handle_join(msg)
        if isinstance(msg, BatchedAlertMessage):
            return self._handle_batched_alerts(msg)
        if isinstance(msg, ProbeMessage):
            return Promise.completed(ProbeResponse())
        if isinstance(msg, CONSENSUS_MESSAGE_TYPES):
            return self._handle_consensus(msg)
        if isinstance(msg, FastRoundVoteBatch):
            return self._handle_vote_batch(msg)
        if isinstance(msg, LeaveMessage):
            self._edge_failure_notification(
                msg.sender, self._view.get_current_configuration_id()
            )
            return Promise.completed(Response())
        if isinstance(msg, ClusterStatusRequest):
            return self._handle_cluster_status(msg)
        if isinstance(msg, GossipEnvelope):
            return self._handle_gossip(msg)
        if isinstance(msg, HandoffRequest):
            return self._handle_handoff_request(msg)
        if isinstance(msg, HandoffAck):
            return self._handle_handoff_ack(msg)
        if isinstance(msg, (Get, Put)):
            return self._handle_serving(msg)
        if isinstance(msg, (CellDigestMessage, GlobalViewMessage)):
            return self._handle_hierarchy(msg)
        if isinstance(msg, MessageBatch):
            return self._handle_message_batch(msg)
        raise TypeError(f"unidentified request type {type(msg).__name__}")

    def _handle_hierarchy(self, msg: RapidMessage) -> Promise:
        """Hierarchy-plane traffic (a peer leader's cell digest, or our own
        leader's composed global view): hop onto the protocol executor --
        the plane reads the view and may announce through the broadcaster
        seam -- and ack the frame. A member without the plane acks and
        drops (a hierarchical peer's stray frame cannot poison dispatch)."""
        future: Promise = Promise()
        if self._hierarchy is None:
            return Promise.completed(Response())

        def task() -> None:
            self._hierarchy.handle_message(msg)
            future.try_set_result(Response())

        self._resources.protocol_executor.execute(task)
        return future

    def _hierarchy_tick(self) -> None:
        """Parent heartbeat edge. Fires on the scheduler's timer thread in
        real deployments; the plane is guarded by the protocol executor,
        so the tick body hops there (same discipline as the alert
        batcher)."""
        if self._hierarchy is None or self._shut_down:
            return
        self._resources.protocol_executor.execute(self._hierarchy.tick)

    def _handle_message_batch(self, batch: MessageBatch) -> Promise:
        """Unpack a transport batch envelope (a broadcaster's flush window,
        messaging/unicast.py BatchingSink): dispatch each inner message
        exactly as if it had arrived alone, ack the envelope. Inner
        responses are dropped -- batched sends are fire-and-forget
        broadcasts. The native codec carries only the envelope's trace
        context, so inners that lost their own stamp adopt it (the gossip
        receive() discipline)."""
        ctx = trace_context_of(batch)
        hlc_stamp = hlc_of(batch)
        for inner in batch.messages:
            if ctx is not None and trace_context_of(inner) is None:
                stamp_trace_context(inner, ctx)
            if hlc_stamp is not None and hlc_of(inner) is None:
                # the native codec carries only the envelope's HLC stamp;
                # inners adopt it exactly like the trace context above
                stamp_hlc(inner, hlc_stamp)
            try:
                self.handle_message(inner)
            except Exception:  # noqa: BLE001 -- one poisoned inner message
                # must not sink the rest of the batch (the unbatched
                # equivalent fails one frame, not a window's traffic)
                LOG.exception("batched message dispatch failed")
        return Promise.completed(Response())

    def _handle_serving(self, msg: RapidMessage) -> Promise:
        """Serving-plane Get/Put: hop onto the protocol executor (leader
        checks read placement state the view-change path mutates) and chain
        the engine's -- possibly asynchronous, e.g. a quorum read or a
        replication fan-out -- answer onto the transport's promise."""
        if self._serving is None:
            # a member without the serving plane tells the client to retry
            # elsewhere rather than hanging its request
            key = getattr(msg, "key", b"")
            return Promise.completed(PutAck(
                sender=self._my_addr, status=PutAck.STATUS_RETRY, key=key,
                request_id=getattr(msg, "request_id", 0),
            ))
        future: Promise = Promise()
        if self._slo is not None:
            # offered load counts at arrival; the good/latency sample lands
            # when the (possibly asynchronous) answer completes, measured on
            # the same scheduler clock so queueing delay is included
            start_ms = self._scheduler.now_ms()
            self._slo.record_offered(start_ms)
            is_get = isinstance(msg, Get)

            def observe(p: Promise) -> None:
                now_ms = self._scheduler.now_ms()
                ack = None if p.exception() is not None else p.result()
                status = getattr(ack, "status", None)
                if is_get:
                    ok = status in (PutAck.STATUS_OK, PutAck.STATUS_NOT_FOUND)
                else:
                    ok = status == PutAck.STATUS_OK
                self._slo.record(now_ms, ok, float(now_ms - start_ms))

            future.add_callback(observe)

        def task() -> None:
            if isinstance(msg, Get):
                inner = self._serving.handle_get(msg)
            else:
                inner = self._serving.handle_put(msg)
            inner.add_callback(lambda p: _chain_promise(p, future))

        self._resources.protocol_executor.execute(task)
        return future

    def _handle_handoff_request(self, msg: HandoffRequest) -> Promise:
        """Serve one chunk of a partition to a pulling new owner. The slice
        itself is stateless (handoff/engine.py), but it runs on the protocol
        executor so reads are serialized against releases from acks."""
        if self._handoff is None:
            # no handoff plane here: an empty Response makes the recipient
            # fail over to its next source rather than hang
            return Promise.completed(Response())
        future: Promise = Promise()

        def task() -> None:
            future.set_result(self._handoff.handle_request(msg))

        self._resources.protocol_executor.execute(task)
        return future

    def _handle_handoff_ack(self, msg: HandoffAck) -> Promise:
        """A new owner verified its copy; release ours unless the current
        map still assigns this member a replica of the partition."""
        future: Promise = Promise()

        def task() -> None:
            if self._handoff is not None:
                pmap = self.placement_map()
                still_replica = (
                    pmap is not None
                    and 0 <= msg.partition < len(pmap.assignments)
                    and self._my_addr in pmap.assignments[msg.partition]
                )
                self._handoff.handle_ack(msg, still_replica)
            future.set_result(Response())

        self._resources.protocol_executor.execute(task)
        return future

    def _handle_cluster_status(self, msg: ClusterStatusRequest) -> Promise:
        """Introspection RPC: snapshot protocol state on the protocol
        executor (the one thread that mutates it), so the answer is a
        consistent cut even while consensus is in flight."""
        future: Promise = Promise()

        def task() -> None:
            self.recorder.record("status_served", requester=str(msg.sender))
            future.set_result(
                self.cluster_status(include_history=msg.include_history)
            )

        self._resources.protocol_executor.execute(task)
        return future

    def cluster_status(self, include_history: int = 0) -> ClusterStatusResponse:
        """The local introspection snapshot (also reachable without the RPC:
        Cluster.get_cluster_status). Only call on the protocol executor or
        from a quiesced cluster. ``include_history`` bounds how many metric
        history-ring snapshots ride along (0 = none)."""
        occupancy = self._cut_detection.occupancy()
        digest = sorted(self.metrics.snapshot().items())
        # transport-plane digest (per-peer outbound queue depths) rides the
        # same metric_names/metric_values streams, so statusz renders it
        # with zero schema changes
        transport_digest = getattr(self._client, "transport_digest", None)
        if transport_digest is not None:
            digest.extend(sorted(transport_digest().items()))
        pmap = self.placement_map()
        handoff_in_flight = handoff_completed = handoff_failed = 0
        handoff_partitions: Tuple[int, ...] = ()
        handoff_fingerprints: Tuple[int, ...] = ()
        if self._handoff is not None:
            handoff_in_flight, handoff_completed, handoff_failed = (
                self._handoff.status()
            )
            store_digest = getattr(self._handoff.store, "digest", None)
            if store_digest is not None:
                handoff_partitions, handoff_fingerprints = store_digest()
            else:
                handoff_partitions = self._handoff.store.partitions()
                handoff_fingerprints = tuple(
                    fp if fp is not None else 0
                    for fp in map(
                        self._handoff.store.fingerprint, handoff_partitions
                    )
                )
        # durability plane digest (all zero on an in-memory store): the
        # restart-health numbers statusz renders next to the fingerprint
        # cross-check
        durability_segments = durability_snapshot_version = 0
        durability_replayed = 0
        if self._handoff is not None:
            durability_stats = getattr(
                self._handoff.store, "durability_stats", None
            )
            if durability_stats is not None:
                stats = durability_stats()
                durability_segments = int(stats["segments"])
                durability_snapshot_version = int(stats["snapshot_version"])
                durability_replayed = int(stats["replayed_records"])
        serving_gets = serving_puts = serving_put_acks = 0
        serving_partitions: Tuple[int, ...] = ()
        serving_leaders: Tuple[str, ...] = ()
        if self._serving is not None:
            serving_gets, serving_puts, serving_put_acks = (
                self._serving.status()
            )
            serving_partitions, serving_leaders = (
                self._serving.leader_digest()
            )
        # failure-detector plane: per-edge RTT/suspicion digest (worst
        # first) and, when the adaptive factory is active, the derived
        # per-tier parameters. Integer micro/milli units: the wire schema
        # has no float scalar.
        fd_subjects: Tuple[str, ...] = ()
        fd_rtt_micros: Tuple[int, ...] = ()
        fd_suspicion_milli: Tuple[int, ...] = ()
        fd_tiers: Tuple[str, ...] = ()
        fd_tier_interval_ms: Tuple[int, ...] = ()
        fd_tier_threshold: Tuple[int, ...] = ()
        fd_tier_flush_ms: Tuple[int, ...] = ()
        edge_digest = getattr(self._fd_factory, "edge_digest", None)
        if edge_digest is not None:
            rows = edge_digest()
            fd_subjects = tuple(r[0] for r in rows)
            fd_rtt_micros = tuple(
                int(round((r[1] if r[1] is not None else 0.0) * 1000))
                for r in rows
            )
            fd_suspicion_milli = tuple(
                int(round(r[2] * 1000)) for r in rows
            )
        # profiling plane: every status call opportunistically ticks the
        # history ring (scrape cadence IS the snapshot cadence, rate-limited
        # by the ring's own interval), then ships the requested tail
        history: Tuple[str, ...] = ()
        if self._history is not None:
            self._history.maybe_snapshot(self._scheduler.now_ms() / 1000.0)
            if include_history > 0:
                history = self._history.to_wire(include_history)
        tier_params = getattr(self._fd_factory, "tier_params", None)
        if tier_params is not None:
            tiers = tier_params()
            fd_tiers = tuple(t[0] for t in tiers)
            fd_tier_interval_ms = tuple(int(t[1]) for t in tiers)
            fd_tier_threshold = tuple(int(t[2]) for t in tiers)
            fd_tier_flush_ms = tuple(int(t[3]) for t in tiers)
        # SLO plane digest: the status scrape doubles as an alert-evaluation
        # tick (forced past the rate limit so a quiet node still clears),
        # and firing alerts are attributed against this node's own journal
        slo_names: Tuple[str, ...] = ()
        slo_burn_milli: Tuple[int, ...] = ()
        slo_firing: Tuple[int, ...] = ()
        slo_attributed_trace: Tuple[int, ...] = ()
        if self._slo is not None:
            self._slo.tick(self._scheduler.now_ms(), force=True)
            self._slo.attribute(self.recorder.tail(64))
            (slo_names, slo_burn_milli, slo_firing,
             slo_attributed_trace) = self._slo.status_digest()
        # forensics plane: journal truncation counters plus this node's
        # current HLC coordinate (all zero pre-forensics -- old peers and
        # goldens see their exact old shape)
        hlc_physical_ms = hlc_logical = hlc_incarnation = 0
        if self._hlc is not None:
            hlc_stamp = self._hlc.peek()
            hlc_physical_ms = hlc_stamp.physical_ms
            hlc_logical = hlc_stamp.logical
            hlc_incarnation = hlc_stamp.incarnation
        # hierarchy plane digest: the member's cell coordinates plus the
        # composed global view as parallel per-cell rows (all empty/zero
        # pre-hierarchy -- old peers and goldens see their exact old shape)
        hierarchy_fields: Dict[str, object] = {}
        if self._hierarchy is not None:
            hierarchy_fields = self._hierarchy.status_fields()
        return ClusterStatusResponse(
            sender=self._my_addr,
            configuration_id=self._view.get_current_configuration_id(),
            membership_size=self._view.membership_size,
            reports_tracked=occupancy["reports_tracked"],
            pre_proposal_size=occupancy["pre_proposal_size"],
            proposal_size=occupancy["proposal_size"],
            updates_in_progress=occupancy["updates_in_progress"],
            consensus_decided=self._fast_paxos.decided,
            consensus_votes=self._fast_paxos.votes_received,
            metric_names=tuple(name for name, _ in digest),
            metric_values=tuple(value for _, value in digest),
            journal=self.recorder.to_wire(32),
            placement_version=pmap.version if pmap is not None else 0,
            placement_partitions=(
                pmap.config.partitions if pmap is not None else 0
            ),
            placement_owned=(
                len(pmap.owned(self._my_addr)) if pmap is not None else 0
            ),
            handoff_in_flight=handoff_in_flight,
            handoff_completed=handoff_completed,
            handoff_failed=handoff_failed,
            handoff_partitions=handoff_partitions,
            handoff_fingerprints=handoff_fingerprints,
            serving_gets=serving_gets,
            serving_puts=serving_puts,
            serving_put_acks=serving_put_acks,
            serving_partitions=serving_partitions,
            serving_leaders=serving_leaders,
            fd_subjects=fd_subjects,
            fd_rtt_micros=fd_rtt_micros,
            fd_suspicion_milli=fd_suspicion_milli,
            fd_tiers=fd_tiers,
            fd_tier_interval_ms=fd_tier_interval_ms,
            fd_tier_threshold=fd_tier_threshold,
            fd_tier_flush_ms=fd_tier_flush_ms,
            history=history,
            durability_segments=durability_segments,
            durability_snapshot_version=durability_snapshot_version,
            durability_replayed=durability_replayed,
            slo_names=slo_names,
            slo_burn_milli=slo_burn_milli,
            slo_firing=slo_firing,
            slo_attributed_trace=slo_attributed_trace,
            journal_dropped=int(getattr(self.recorder, "dropped", 0)),
            journal_capacity=int(getattr(self.recorder, "capacity", 0)),
            hlc_physical_ms=hlc_physical_ms,
            hlc_logical=hlc_logical,
            hlc_incarnation=hlc_incarnation,
            **hierarchy_fields,
        )

    @property
    def hierarchy(self) -> Optional[HierarchyPlane]:
        """The hierarchy plane, or None when ``settings.hierarchy`` is off
        (harnesses use it to seed parent bootstrap hints and to read the
        composed global view directly)."""
        return self._hierarchy

    # ------------------------------------------------------------------ #
    # Forensics plane (forensics/, tools/forensics.py)
    # ------------------------------------------------------------------ #

    def _durability_dict(self) -> Optional[Dict[str, int]]:
        if self._handoff is None:
            return None
        stats_fn = getattr(self._handoff.store, "durability_stats", None)
        if stats_fn is None:
            return None
        try:
            stats = stats_fn()
            return {
                "segments": int(stats["segments"]),
                "snapshot_version": int(stats["snapshot_version"]),
                "replayed": int(stats["replayed_records"]),
            }
        except Exception:  # noqa: BLE001 -- evidence capture degrades
            return None

    def _local_record(self) -> Dict[str, object]:
        """This node's member record, assembled straight from the plane
        objects -- never via the status RPC, so a capture triggered from
        inside the SLO/status path cannot recurse. Safe on any thread (the
        recorder locks; everything else is a snapshot read)."""
        return capture_local_evidence(
            node=str(self._my_addr),
            recorder=self.recorder,
            metrics=self.metrics,
            tracer=self.tracer,
            slo=self._slo,
            hlc=self._hlc,
            configuration_id=self._view.get_current_configuration_id(),
            membership_size=self._view.membership_size,
            durability=self._durability_dict(),
            history=self._history,
            journal_tail=self._settings.forensics.bundle_journal_tail,
            history_tail=self._settings.forensics.bundle_history_tail,
        )

    def local_evidence(self, trigger: str = "explicit",
                       detail: Optional[Dict[str, object]] = None,
                       ) -> Dict[str, object]:
        """A local-only evidence bundle (the automatic-trigger form)."""
        return build_bundle(trigger, self._local_record(), detail=detail)

    def capture_cluster_bundle_async(
        self, trigger: str = "explicit",
        detail: Optional[Dict[str, object]] = None,
    ) -> Promise:
        """Cluster-wide evidence capture: the local record plus a status-RPC
        sweep of every other member. A callback state machine (never blocks,
        so it works under virtual time exactly like ``join_async``): the
        bundle completes when every member answered or the scheduler-clock
        deadline (``forensics.bundle_member_timeout_ms``) fires, whichever
        is first -- members still pending at the deadline are recorded as
        unreachable, so a partitioned cluster still yields a bundle naming
        who was missing."""
        from .forensics.bundle import status_to_record, unreachable_record

        local = self._local_record()
        result: Promise = Promise()
        futures: List[Tuple[Endpoint, Promise]] = []
        for member in self._view.get_ring(0):
            if member == self._my_addr:
                continue
            request = ClusterStatusRequest(
                sender=self._my_addr,
                include_history=self._settings.forensics.bundle_history_tail,
            )
            futures.append(
                (member, self._client.send_message(member, request))
            )
        state = {"remaining": len(futures), "finished": False}
        lock = make_lock("MembershipService.capture_bundle.lock")

        def finish() -> None:
            members: List[Dict[str, object]] = []
            for member, future in futures:
                if not future.done():
                    members.append(unreachable_record(
                        str(member), "status deadline exceeded"
                    ))
                elif future.exception() is not None:
                    members.append(unreachable_record(
                        str(member), str(future.exception())
                    ))
                else:
                    status = future.peek()
                    if isinstance(status, ClusterStatusResponse):
                        members.append(status_to_record(status))
                    else:
                        members.append(unreachable_record(
                            str(member),
                            f"unexpected response {type(status).__name__}",
                        ))
            bundle = build_bundle(
                trigger, local, members=members, detail=detail
            )
            self.last_bundle = bundle
            self.recorder.record(
                "bundle_captured", trigger=trigger,
                fingerprint=str(bundle["manifest"]["fingerprint"])[:12],  # type: ignore[index]
                events=int(bundle["manifest"]["events"]),  # type: ignore[index]
            )
            result.set_result(bundle)

        def maybe_finish(last: bool) -> None:
            with lock:
                if state["finished"]:
                    return
                if last:
                    state["remaining"] -= 1
                    if state["remaining"] > 0:
                        return
                state["finished"] = True
            finish()

        for _member, future in futures:
            future.add_callback(lambda _p: maybe_finish(True))
        self._scheduler.schedule(
            self._settings.forensics.bundle_member_timeout_ms,
            lambda: maybe_finish(False),
        )
        if not futures:
            maybe_finish(False)
        return result

    def capture_cluster_bundle(self, trigger: str = "explicit",
                               detail: Optional[Dict[str, object]] = None,
                               timeout: float = 60.0) -> Dict[str, object]:
        """Blocking wrapper for real-time mode (virtual-time callers drive
        the async form). Never call on the protocol executor: the member
        responses complete there."""
        return self.capture_cluster_bundle_async(trigger, detail).result(
            timeout
        )

    def _on_slo_transitions(self, transitions) -> None:
        """Burn-alert forensics trigger: the first "fired" transition in a
        tick captures a local-only bundle and journals the capture, so the
        evidence window is pinned at the moment the alert fired rather than
        whenever an operator notices."""
        fired = [alert for kind, alert in transitions if kind == "fired"]
        if not fired:
            return
        bundle = self.local_evidence(
            "slo_burn", detail={"alerts": [a.name for a in fired]},
        )
        self.last_bundle = bundle
        self.recorder.record(
            "bundle_captured", trigger="slo_burn",
            fingerprint=str(bundle["manifest"]["fingerprint"])[:12],  # type: ignore[index]
            events=int(bundle["manifest"]["events"]),  # type: ignore[index]
        )

    # ------------------------------------------------------------------ #
    # Placement plane (placement/engine.py)
    # ------------------------------------------------------------------ #

    def placement_map(self) -> Optional[PlacementMap]:
        """The current deterministic shard map (None unless placement was
        configured); identical on every member of a configuration."""
        return self._placement.map if self._placement is not None else None

    def placement_diff(self) -> Optional[PlacementDiff]:
        """The rebalance plan produced by the latest view change."""
        return self._placement.last_diff if self._placement is not None else None

    def handoff_engine(self) -> Optional[HandoffEngine]:
        """The live handoff engine (None unless use_handoff configured)."""
        return self._handoff

    def serving_engine(self) -> Optional[ServingEngine]:
        """The live serving engine (None unless use_serving configured)."""
        return self._serving

    def serving_put(self, key: bytes, value: bytes) -> Promise:
        """Write through the serving plane (routing, replication and
        retries happen inside the engine); completes with the final
        PutAck."""
        if self._serving is None:
            raise RuntimeError("serving is not enabled on this member")
        return self._serving.client_put(key, value)

    def serving_get(self, key: bytes) -> Promise:
        """Read through the serving plane; completes with a PutAck."""
        if self._serving is None:
            raise RuntimeError("serving is not enabled on this member")
        return self._serving.client_get(key)

    def _update_placement(self, configuration_id: int) -> None:
        """Recompute the shard map for the just-installed configuration.

        Runs on the protocol executor inside the view-change path (and once
        at construction), so the map versions advance in lockstep with
        configuration ids on every member. The rebalance span parents under
        the ambient view_change span and therefore joins the churn trace."""
        if self._placement is None:
            return
        members = self._view.get_ring(0)
        cfg = self._placement.config
        weights = {
            node: weight_of(
                self._metadata_manager.get(node), cfg.weight_key,
                cfg.default_weight,
            )
            for node in members
        }
        old_map = self._placement.map
        with self.tracer.span(
            "placement_rebalance", virtual_ms=self._scheduler.now_ms(),
            size=len(members),
        ) as span:
            pmap, diff = self._placement.update(
                configuration_id, members, weights
            )
            span.attrs["version"] = pmap.version
            if diff is not None:
                span.attrs["moved"] = diff.moved
            if self._handoff is not None:
                # launched inside the rebalance span, so every
                # handoff_session span joins this churn's trace. The first
                # map has no predecessor diff (a joiner builds its service
                # at the post-join view), so it bootstraps instead: pull
                # whatever the map assigns us that the store lacks.
                if old_map is None:
                    launched = self._handoff.bootstrap_sessions(pmap)
                elif diff is not None and diff.handoffs:
                    launched = self._handoff.start_sessions(old_map, pmap)
                else:
                    launched = 0
                if launched:
                    span.attrs["handoff_sessions"] = launched
                    self.recorder.record(
                        "handoff_started",
                        configuration_id=configuration_id,
                        sessions=launched, version=pmap.version,
                    )
            if self._serving is not None:
                # after the handoff sessions launch, so the cache
                # invalidation in update_map sees the same acquisition set
                # the sessions will fill; promote-time snapshot syncs join
                # this churn's trace
                self._serving.update_map(pmap)
        self.metrics.incr("placement.rebuilds")
        self.metrics.set_gauge("placement.imbalance", pmap.imbalance())
        self.metrics.set_gauge(
            "placement.partitions_owned", len(pmap.owned(self._my_addr))
        )
        if diff is not None:
            self.metrics.observe(
                "placement.partitions_moved", diff.moved,
                buckets=PARTITIONS_MOVED_BUCKETS,
            )
            self.recorder.record(
                "placement_rebalance", configuration_id=configuration_id,
                moved=diff.moved, version=pmap.version,
                handoffs=len(diff.handoffs),
            )

    def _handle_gossip(self, env: GossipEnvelope) -> Promise:
        """Epidemic relay plane: hand the envelope to a gossip-aware
        broadcaster (dedup + re-relay), then dispatch a first-seen payload
        like any directly-received message. Nodes running a non-gossip
        broadcaster acknowledge and drop -- mixed clusters degrade to the
        origin's direct fanout. Serialized on the protocol executor like
        every other substantive handler: the broadcaster's sighting counter
        and rng are not thread-safe, and transport threads deliver
        concurrently."""
        receive = getattr(self._broadcaster, "receive", None)
        if receive is None:
            return Promise.completed(Response())
        future: Promise = Promise()

        def task() -> None:
            payload = receive(env)
            if payload is not None:
                self.handle_message(payload)
            future.set_result(Response())

        self._resources.protocol_executor.execute(task)
        return future

    # ------------------------------------------------------------------ #
    # Join protocol, server side
    # ------------------------------------------------------------------ #

    def _handle_pre_join(self, msg: PreJoinMessage) -> Promise:
        """Phase-1 gatekeeping at a seed (MembershipService.java:200-221)."""
        future: Promise = Promise()

        def task() -> None:
            status = self._view.is_safe_to_join(msg.sender, msg.node_id)
            endpoints: Tuple[Endpoint, ...] = ()
            if status in (
                JoinStatusCode.SAFE_TO_JOIN,
                JoinStatusCode.HOSTNAME_ALREADY_IN_RING,
            ):
                endpoints = tuple(self._view.get_expected_observers_of(msg.sender))
            future.set_result(
                JoinResponse(
                    sender=self._my_addr,
                    status_code=status,
                    configuration_id=self._view.get_current_configuration_id(),
                    endpoints=endpoints,
                )
            )

        self._resources.protocol_executor.execute(task)
        return future

    def _handle_join(self, msg: JoinMessage) -> Promise:
        """Phase-2 at an observer: park the response until the view change
        commits (MembershipService.java:229-286)."""
        future: Promise = Promise()

        def task() -> None:
            current_configuration = self._view.get_current_configuration_id()
            if current_configuration == msg.configuration_id:
                self._joiners_to_respond_to.setdefault(msg.sender, []).append(future)
                alert = AlertMessage(
                    edge_src=self._my_addr,
                    edge_dst=msg.sender,
                    edge_status=EdgeStatus.UP,
                    configuration_id=current_configuration,
                    ring_numbers=msg.ring_numbers,
                    node_id=msg.node_id,
                    metadata=msg.metadata,
                )
                self._enqueue_alert(alert)
            else:
                # Configuration changed between join phases 1 and 2.
                config = self._view.get_configuration()
                if self._view.is_host_present(msg.sender) and self._view.is_identifier_present(
                    msg.node_id
                ):
                    # The cut already admitted this joiner; stream the config.
                    future.set_result(self._make_join_response(JoinStatusCode.SAFE_TO_JOIN))
                else:
                    future.set_result(
                        JoinResponse(
                            sender=self._my_addr,
                            status_code=JoinStatusCode.CONFIG_CHANGED,
                            configuration_id=config.configuration_id,
                        )
                    )

        self._resources.protocol_executor.execute(task)
        return future

    def _make_join_response(self, status: JoinStatusCode) -> JoinResponse:
        config = self._view.get_configuration()
        return JoinResponse(
            sender=self._my_addr,
            status_code=status,
            configuration_id=config.configuration_id,
            endpoints=config.endpoints,
            identifiers=config.node_ids,
            metadata=tuple(self._metadata_manager.get_all_metadata().items()),
        )

    # ------------------------------------------------------------------ #
    # Alerts -> cut detection -> consensus (MembershipService.java:297-348)
    # ------------------------------------------------------------------ #

    def _handle_batched_alerts(self, batch: BatchedAlertMessage) -> Promise:
        future: Promise = Promise()
        ctx = trace_context_of(batch)

        def task() -> None:
            if (
                ctx is not None
                and self._churn_ctx is None
                and any(
                    m.configuration_id
                    == self._view.get_current_configuration_id()
                    for m in batch.messages
                )
            ):
                # adopt the sender's churn trace so this node's own alerts,
                # votes, and eventual view_change carry the same trace id.
                # Idempotent under nemesis duplication/reordering, and gated
                # on a current-configuration alert so a stale duplicate
                # delivered AFTER the install cannot re-arm a completed
                # trace onto the next churn.
                self._churn_ctx = ctx
            self.recorder.record(
                "alert_in", sender=str(batch.sender),
                alerts=len(batch.messages),
            )
            with self.tracer.remote_span(
                "alert_batch", ctx=ctx, virtual_ms=self._scheduler.now_ms(),
                alerts=len(batch.messages),
            ):
                self._handle_batched_alerts_task(batch)
            future.set_result(Response())

        self._resources.protocol_executor.execute(task)
        return future

    def _handle_batched_alerts_task(self, batch: BatchedAlertMessage) -> None:
        current_configuration_id = self._view.get_current_configuration_id()
        membership_size = self._view.membership_size
        valid_alerts = [
            self._extract_joiner_details(msg)
            for msg in batch.messages
            if self._filter_alert(msg, membership_size, current_configuration_id)
        ]
        if valid_alerts:
            # first admissible evidence of membership churn in this
            # configuration starts the time-to-stable-view clock
            self._stable_view.detection()
        pending = self._pending_decision
        if pending is not None and all(
            self._view.is_host_present(node) or node in self._joiner_uuid
            for node in pending
        ):
            # the refused decision's missing joiner identities have now
            # arrived: apply the parked view change
            LOG.info(
                "%s: joiner identities arrived; applying the parked "
                "view change", self._my_addr,
            )
            self._pending_decision = None
            self._decide_view_change(pending)
            return
        if self._announced_proposal:
            # We already initiated consensus and cannot go back on it.
            return
        proposal: Set[Endpoint] = set()
        for alert in valid_alerts:
            proposal.update(self._cut_detection.aggregate_for_proposal(alert))
        proposal.update(self._cut_detection.invalidate_failing_edges(self._view))
        if proposal:
            self._announced_proposal = True
            self.metrics.incr("proposals")
            self.tracer.event(
                "proposal", virtual_ms=self._scheduler.now_ms(),
                size=len(proposal),
                configuration_id=current_configuration_id,
            )
            self.recorder.record(
                "proposal", size=len(proposal),
                configuration_id=current_configuration_id,
            )
            changes = self._node_status_changes(proposal)
            self._fire(
                ClusterEvents.VIEW_CHANGE_PROPOSAL, current_configuration_id, changes
            )
            self._fast_paxos.propose(sorted(proposal, key=address_comparator_key))

    def _filter_alert(
        self, alert: AlertMessage, membership_size: int, current_configuration_id: int
    ) -> bool:
        """Drop stale/invariant-violating alerts (MembershipService.java:633-664)."""
        if alert.configuration_id != current_configuration_id:
            if alert.edge_status == EdgeStatus.UP:
                LOG.debug(
                    "%s: dropping stale UP alert for %s (alert config %d, "
                    "current %d)",
                    self._my_addr, alert.edge_dst, alert.configuration_id,
                    current_configuration_id,
                )
            return False
        if alert.edge_status == EdgeStatus.UP and self._view.is_host_present(alert.edge_dst):
            LOG.debug(
                "%s: dropping UP alert for already-present %s",
                self._my_addr, alert.edge_dst,
            )
            return False
        if alert.edge_status == EdgeStatus.DOWN and not self._view.is_host_present(
            alert.edge_dst
        ):
            return False
        return True

    def _extract_joiner_details(self, alert: AlertMessage) -> AlertMessage:
        """Stash joiner UUID/metadata for the eventual ringAdd
        (MembershipService.java:666-674)."""
        if alert.edge_status == EdgeStatus.UP:
            assert alert.node_id is not None
            self._joiner_uuid[alert.edge_dst] = alert.node_id
            self._joiner_metadata[alert.edge_dst] = alert.metadata
        return alert

    def _adopt_churn_ctx(self, msg: RapidMessage) -> None:
        """Adopt an incoming message's trace context as this node's churn
        trace if it has none yet (a node can learn of churn from a quorum of
        votes before -- or instead of -- any alert). Messages from another
        configuration never adopt: a reordered or duplicated vote surfacing
        after the install must not tag the next churn with a finished
        trace."""
        if self._churn_ctx is None:
            config = getattr(
                msg, "configuration_id",
                self._view.get_current_configuration_id(),
            )
            if config != self._view.get_current_configuration_id():
                return
            ctx = trace_context_of(msg)
            if ctx is not None:
                self._churn_ctx = ctx

    def _handle_consensus(self, msg: RapidMessage) -> Promise:
        future: Promise = Promise()

        def task() -> None:
            self._adopt_churn_ctx(msg)
            future.set_result(self._fast_paxos.handle_messages(msg))

        self._resources.protocol_executor.execute(task)
        return future

    def _handle_vote_batch(self, batch: FastRoundVoteBatch) -> Promise:
        """Unpack a transport-batched quorum of identical-value votes into
        the per-sender tally, in ONE protocol task (posting thousands of
        single-vote tasks would serialize through the executor queue)."""
        future: Promise = Promise()

        def task() -> None:
            self._adopt_churn_ctx(batch)
            for sender in batch.senders:
                self._fast_paxos.handle_messages(
                    FastRoundPhase2bMessage(
                        sender=sender,
                        configuration_id=batch.configuration_id,
                        endpoints=batch.endpoints,
                    )
                )
            future.set_result(ConsensusResponse())

        self._resources.protocol_executor.execute(task)
        return future

    # ------------------------------------------------------------------ #
    # View-change application (MembershipService.java:379-433)
    # ------------------------------------------------------------------ #

    def _decide_view_change(self, proposal: List[Endpoint]) -> None:
        self.recorder.record("decision", size=len(proposal))
        # the view_change span joins the churn's cross-node trace: same
        # trace id as the fd_signal on whichever node detected the failure
        # (ctx=None -- untraced churn -- degrades to a local root span)
        with self.tracer.remote_span(
            "view_change", ctx=self._churn_ctx,
            virtual_ms=self._scheduler.now_ms(),
            size=len(proposal),
        ):
            self._decide_view_change_locked(proposal)

    def _decide_view_change_locked(self, proposal: List[Endpoint]) -> None:
        self._stable_view.decision()
        # A decided proposal can reference a joiner whose UUID-carrying UP
        # alerts this node never processed (every alert delivery is
        # best-effort; the quorum of votes can arrive anyway). Applying a
        # partial view change would silently fork this node's configuration
        # id; the reference would NPE here (its assert at
        # MembershipService.java:396 is disabled at runtime and
        # joinerUuid.remove returns null). Instead: refuse the whole view
        # change and stay on the current configuration -- Rapid's answer to
        # a node that falls behind is removal and rejoin, and the stale
        # traffic this node keeps emitting triggers exactly that repair.
        missing = [
            node for node in proposal
            if not self._view.is_host_present(node)
            and node not in self._joiner_uuid
        ]
        if missing:
            self.metrics.incr("view_changes_refused_missing_identity")
            self.recorder.record(
                "view_refused", missing=[str(node) for node in missing],
            )
            LOG.error(
                "%s: refusing view change at config %d: no joiner identity "
                "for %s (UP alerts lost); parked until the alerts land, "
                "else removal+rejoin",
                self._my_addr, self._view.get_current_configuration_id(),
                [str(node) for node in missing],
            )
            # park, don't drop: this configuration's FastPaxos has decided
            # and will never re-fire, so if the UUID-carrying alerts arrive
            # a moment after the quorum of votes (every delivery is
            # best-effort and independently ordered), only this parked
            # proposal can still apply the view change
            # (_handle_batched_alerts retries it once identities are known)
            self._pending_decision = list(proposal)
            return
        self._pending_decision = None
        self._cancel_failure_detectors()
        status_changes: List[NodeStatusChange] = []
        for node in proposal:
            if self._view.is_host_present(node):
                self._view.ring_delete(node)
                status_changes.append(
                    NodeStatusChange(node, EdgeStatus.DOWN, self._metadata_manager.get(node))
                )
                self._metadata_manager.remove_node(node)
            else:
                node_id = self._joiner_uuid.pop(node)
                self._view.ring_add(node, node_id)
                metadata = self._joiner_metadata.pop(node, ())
                if metadata:
                    self._metadata_manager.add_metadata({node: metadata})
                status_changes.append(NodeStatusChange(node, EdgeStatus.UP, metadata))

        configuration_id = self._view.get_current_configuration_id()
        self.metrics.incr("view_changes")
        self.recorder.record(
            "view_install", configuration_id=configuration_id,
            size=self._view.membership_size,
        )
        # restart-aware rejoin seam: persist the installed configuration id
        # so a returning node knows which configuration it last belonged to
        if self._handoff is not None:
            persist_config = getattr(self._handoff.store, "set_config_id", None)
            if persist_config is not None:
                persist_config(configuration_id)
        self._fire(ClusterEvents.VIEW_CHANGE, configuration_id, status_changes)
        self._update_placement(configuration_id)
        self._stable_view.view_installed()

        self._cut_detection.clear()
        self._announced_proposal = False
        self._churn_ctx = None  # this churn's trace is complete
        self._fast_paxos = self._new_fast_paxos()
        self._broadcaster.set_membership(self._view.get_ring(0))
        if self._hierarchy is not None:
            # ordinary view install doubles as the hierarchy edge: leaders
            # recompute deterministically from the new view (a leader
            # eviction silently promotes the next member in leader order)
            # and announce the cell's new row/epoch to the parent
            self._hierarchy.on_view_installed(
                self._view.get_ring(0), configuration_id
            )

        if self._view.is_host_present(self._my_addr):
            self._create_failure_detectors()
        else:
            # We were removed: gracefully self-evict.
            self.recorder.record("kicked", configuration_id=configuration_id)
            self._fire(ClusterEvents.KICKED, configuration_id, status_changes)

        self._respond_to_joiners(proposal)

    def _new_fast_paxos(self) -> FastPaxos:
        return FastPaxos(
            self._my_addr,
            self._view.get_current_configuration_id(),
            self._view.membership_size,
            self._client,
            self._broadcaster,
            self._scheduler,
            self._on_consensus_decide,
            consensus_fallback_base_delay_ms=self._settings.consensus_fallback_base_delay_ms,
            rng=self._rng,
            metrics=self.metrics,
            tracer=self.tracer,
            serialize=self._resources.protocol_executor.execute,
        )

    def _on_consensus_decide(self, proposal: List[Endpoint]) -> None:
        # Decisions may surface from within a protocol task (message handling)
        # -- re-serialize onto the protocol executor.
        self._resources.protocol_executor.execute(
            lambda: self._decide_view_change(proposal)
        )

    def _respond_to_joiners(self, proposal: List[Endpoint]) -> None:
        """Unblock parked phase-2 join futures with the new configuration
        (MembershipService.java:708-733)."""
        response = self._make_join_response(JoinStatusCode.SAFE_TO_JOIN)
        for node in proposal:
            futures = self._joiners_to_respond_to.pop(node, None)
            if futures:
                for future in futures:
                    self._scheduler.execute(
                        lambda f=future: f.try_set_result(response)
                    )

    # ------------------------------------------------------------------ #
    # Failure detection (MembershipService.java:461-484, 686-703)
    # ------------------------------------------------------------------ #

    def _edge_failure_notification(self, subject: Endpoint, configuration_id: int) -> None:
        def task() -> None:
            if configuration_id != self._view.get_current_configuration_id():
                return  # stale notification from an old configuration
            if not self._view.is_host_present(subject):
                return
            self.metrics.incr("fd.edge_failures")
            signal = self.tracer.event(
                "fd_signal", virtual_ms=self._scheduler.now_ms(),
                subject=str(subject),
            )
            self.recorder.record("fd_signal", subject=str(subject))
            if self._churn_ctx is None:
                # this node detected the churn: its fd_signal roots the
                # cross-node trace every downstream alert/vote/view_change
                # will carry
                self._churn_ctx = TraceContext(
                    trace_id=signal.trace_id or signal.span_id,
                    parent_span_id=signal.span_id,
                    origin=str(self._my_addr),
                )
            self._stable_view.detection()
            alert = AlertMessage(
                edge_src=self._my_addr,
                edge_dst=subject,
                edge_status=EdgeStatus.DOWN,
                configuration_id=configuration_id,
                ring_numbers=tuple(self._view.get_ring_numbers(self._my_addr, subject)),
            )
            self._enqueue_alert(alert)

        self._resources.protocol_executor.execute(task)

    def _create_failure_detectors(self) -> None:
        try:
            subjects = self._view.get_subjects_of(self._my_addr)
        except Exception:  # not in the ring (shouldn't happen; be safe)
            subjects = []
        begin = getattr(self._fd_factory, "begin_configuration", None)
        if begin is not None:
            begin(tuple(subjects))
        interval_for = getattr(self._fd_factory, "interval_ms_for", None)
        for subject in subjects:
            config_id = self._view.get_current_configuration_id()
            notifier = (
                lambda s=subject, c=config_id: self._edge_failure_notification(s, c)
            )
            runnable = self._fd_factory.create_instance(subject, notifier)
            interval_ms = self._settings.failure_detector_interval_ms
            if interval_for is not None:
                # adaptive factories probe per-tier: LAN edges faster than
                # the static default, WAN edges slower (monitoring/adaptive)
                interval_ms = interval_for(subject, interval_ms)
            job = self._scheduler.schedule_at_fixed_rate(
                0, interval_ms, runnable
            )
            self._failure_detector_jobs.append(job)

    def _cancel_failure_detectors(self) -> None:
        for job in self._failure_detector_jobs:
            job.cancel()
        self._failure_detector_jobs.clear()

    # ------------------------------------------------------------------ #
    # Alert batching (MembershipService.java:561-626)
    # ------------------------------------------------------------------ #

    def _enqueue_alert(self, msg: AlertMessage) -> None:
        self.metrics.incr("alerts_enqueued")
        self._last_enqueue_ms = self._scheduler.now_ms()
        self.tracer.event(
            "alert_enqueued", virtual_ms=self._last_enqueue_ms,
            dst=str(msg.edge_dst), status=msg.edge_status.name,
        )
        stamp_trace_context(msg, self._churn_ctx)
        self._alert_send_queue.append(msg)

    def _alert_batcher_tick(self) -> None:
        """Quiescence-based flush: only send once a full batching window has
        passed since the last enqueue (MembershipService.java:602-626).

        The tick fires on the scheduler's timer thread in real deployments
        while _enqueue_alert appends on the protocol executor; the
        check-and-flush body hops onto the executor so the queue is only
        ever touched from one context."""
        self._resources.protocol_executor.execute(self._alert_batcher_flush)

    def _alert_batcher_flush(self) -> None:
        if not self._alert_send_queue or self._last_enqueue_ms < 0:
            return
        window_ms = self._settings.batching_window_ms
        flush_for = getattr(self._fd_factory, "flush_window_ms", None)
        if flush_for is not None:
            # adaptive factories shrink the window while a gray alert is
            # pending so the cut detector hears about it promptly
            window_ms = flush_for(window_ms)
        if self._scheduler.now_ms() - self._last_enqueue_ms <= window_ms:
            return
        messages = tuple(self._alert_send_queue)
        self._alert_send_queue.clear()
        batch = BatchedAlertMessage(sender=self._my_addr, messages=messages)
        # the flush runs on a timer tick with no ambient span, so the batch
        # carries the churn trace explicitly (falling back to whatever the
        # first traced alert carried)
        ctx = self._churn_ctx
        if ctx is None:
            ctx = next(
                (c for c in map(trace_context_of, messages) if c is not None),
                None,
            )
        stamp_trace_context(batch, ctx)
        self.recorder.record("alert_out", alerts=len(messages))
        self._broadcaster.broadcast(batch)

    # ------------------------------------------------------------------ #
    # Public surface
    # ------------------------------------------------------------------ #

    def get_membership_view(self) -> List[Endpoint]:
        return self._view.get_ring(0)

    @property
    def membership_size(self) -> int:
        return self._view.membership_size

    def get_metadata(self) -> Dict[Endpoint, FrozenMetadata]:
        return self._metadata_manager.get_all_metadata()

    def get_current_configuration_id(self) -> int:
        return self._view.get_current_configuration_id()

    def register_subscription(
        self, event: ClusterEvents, callback: SubscriptionCallback
    ) -> None:
        self._subscriptions[event].append(callback)

    def leave_async(self) -> Promise:
        """Proactively trigger DOWN alerts at our observers
        (MembershipService.java:534-554); completes when observers answered
        or the leave timeout passed."""
        done: Promise = Promise()
        try:
            observers = self._view.get_observers_of(self._my_addr)
        except Exception:  # already removed: nothing to announce
            done.set_result(None)
            return done
        leave = LeaveMessage(sender=self._my_addr)
        responses = successful_as_list(
            [self._client.send_message_best_effort(obs, leave) for obs in observers]
        )
        responses.add_callback(lambda _: done.try_set_result(None))
        self._scheduler.schedule(
            self._settings.leave_message_timeout_ms,
            lambda: done.try_set_result(None),
        )
        return done

    def shutdown(self) -> None:
        if self._shut_down:
            return
        self._shut_down = True
        self._alert_batcher_job.cancel()
        if self._hierarchy_job is not None:
            self._hierarchy_job.cancel()
        # _failure_detector_jobs is only ever touched on the protocol
        # executor (_create_failure_detectors runs there); keep shutdown's
        # cancel on the same context instead of racing it from the caller's
        # thread. SharedResources.shutdown drains the executor afterwards.
        self._resources.protocol_executor.execute(self._cancel_failure_detectors)
        self._client.shutdown()

    # ------------------------------------------------------------------ #

    def _node_status_changes(self, proposal) -> List[NodeStatusChange]:
        return [
            NodeStatusChange(
                node,
                EdgeStatus.DOWN if self._view.is_host_present(node) else EdgeStatus.UP,
                self._metadata_manager.get(node),
            )
            for node in sorted(proposal, key=address_comparator_key)
        ]

    def _fire(
        self, event: ClusterEvents, configuration_id: int, changes: List[NodeStatusChange]
    ) -> None:
        for callback in self._subscriptions[event]:
            callback(configuration_id, changes)
