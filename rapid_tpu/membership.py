"""K-ring expander membership view.

Reference: MembershipView.java. The reference maintains K TreeSets of
endpoints, each ordered by a seeded-xxHash comparator (MembershipView.java:58-90
with Utils.AddressComparator, Utils.java:205-235). Every node *observes* its K
successors (one per ring, MembershipView.java:235-258) and is observed by its K
predecessors (its *subjects* are its predecessors, MembershipView.java:309-323).

This implementation keeps each ring as a Python list of (signed-hash, Endpoint)
kept sorted with bisect -- same ordering domain as the reference (signed int64
compare of the seeded hash, Utils.java:216-221). A hash collision between two
distinct endpoints on a ring raises, where the reference TreeSet would silently
treat them as the same element; collisions are a ~2^-64 event and failing loudly
is strictly safer.

Configuration identity is the chained xx(0) hash over (sorted identifiers,
ring-0 order endpoints) (MembershipView.java:531-547) and is bit-compatible
with the JVM.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .hashing import configuration_id, endpoint_hash, to_signed
from .types import Endpoint, JoinStatusCode, NodeId


class NodeAlreadyInRingError(RuntimeError):
    pass


class NodeNotInRingError(RuntimeError):
    pass


class UUIDAlreadySeenError(RuntimeError):
    pass


@dataclass(frozen=True)
class Configuration:
    """Snapshot sufficient to bootstrap an identical view
    (MembershipView.Configuration, MembershipView.java:517-548)."""

    node_ids: Tuple[NodeId, ...]
    endpoints: Tuple[Endpoint, ...]

    @property
    def configuration_id(self) -> int:
        return configuration_id(
            ((nid.high, nid.low) for nid in self.node_ids),
            ((ep.hostname, ep.port) for ep in self.endpoints),
        )


class MembershipView:
    """K pseudo-random ring orderings of the member list."""

    def __init__(
        self,
        k: int,
        node_ids: Sequence[NodeId] = (),
        endpoints: Sequence[Endpoint] = (),
    ) -> None:
        if k <= 0:
            raise ValueError("K must be > 0")
        self.k = k
        # ring[i] is a sorted list of (signed_hash, endpoint)
        self._rings: List[List[Tuple[int, Endpoint]]] = [[] for _ in range(k)]
        self._hash_cache: List[Dict[Endpoint, int]] = [{} for _ in range(k)]
        self._all_nodes: Set[Endpoint] = set()
        # identifiersSeen, ordered by NodeId (high, low) signed compare
        self._identifiers: List[NodeId] = []
        self._identifier_set: Set[NodeId] = set()
        self._config_dirty = True
        self._current_config: Optional[Configuration] = None
        self._current_config_id = -1
        if len(endpoints) > 256:
            # bulk bootstrap (a joiner rebuilding a large view from a
            # JoinResponse): vectorized ring keys + one sort per ring
            # instead of per-endpoint sorted-list inserts, which are
            # O(K * N^2) list memmoves -- minutes at 100k members
            self._bulk_insert(list(endpoints))
        else:
            for ep in endpoints:
                self._insert(ep)
        for nid in node_ids:
            if nid not in self._identifier_set:
                bisect.insort(self._identifiers, nid)
                self._identifier_set.add(nid)

    # -- internal ring maintenance ------------------------------------------

    def _ring_key(self, endpoint: Endpoint, ring: int) -> int:
        cache = self._hash_cache[ring]
        h = cache.get(endpoint)
        if h is None:
            h = to_signed(endpoint_hash(endpoint.hostname, endpoint.port, ring))
            cache[endpoint] = h
        return h

    def _insert(self, endpoint: Endpoint) -> None:
        for ring in range(self.k):
            entry = (self._ring_key(endpoint, ring), endpoint)
            lst = self._rings[ring]
            pos = bisect.bisect_left(lst, entry[0], key=lambda e: e[0])
            if pos < len(lst) and lst[pos][0] == entry[0] and lst[pos][1] != endpoint:
                raise RuntimeError(
                    f"ring hash collision on ring {ring}: {lst[pos][1]} vs {endpoint}"
                )
            lst.insert(pos, entry)
        self._all_nodes.add(endpoint)

    def _bulk_insert(self, endpoints: List[Endpoint]) -> None:
        """Construct all K rings at once: batched xxHash64 over the endpoint
        matrix and one stable argsort per ring. Produces bit-identical ring
        contents, hash caches, and collision errors to sequential
        ``_insert`` calls (keys are distinct signed int64s, so sorted order
        is unique)."""
        import numpy as np

        from . import native
        from .hashing import endpoint_hash_batch, pack_hostnames

        data, lengths = pack_hostnames([ep.hostname for ep in endpoints])
        ports = np.array([ep.port for ep in endpoints], dtype=np.int64)
        # all K rings in one native call where the library loads (the same
        # dispatch sim/topology.py uses for cluster synthesis)
        all_keys = native.ring_hashes(data, lengths, ports, self.k)
        for ring in range(self.k):
            keys = (
                all_keys[ring]
                if all_keys is not None
                else endpoint_hash_batch(data, lengths, ports, ring)
            ).view(np.int64)
            order = np.argsort(keys, kind="stable")
            sorted_keys = keys[order]
            for d in np.flatnonzero(sorted_keys[1:] == sorted_keys[:-1]):
                a, b = endpoints[order[d]], endpoints[order[d + 1]]
                if a != b:
                    raise RuntimeError(
                        f"ring hash collision on ring {ring}: {a} vs {b}"
                    )
            self._rings[ring] = [
                (int(sorted_keys[i]), endpoints[order[i]])
                for i in range(len(endpoints))
            ]
            self._hash_cache[ring] = {
                ep: int(key) for key, ep in self._rings[ring]
            }
        self._all_nodes.update(endpoints)

    def _remove(self, endpoint: Endpoint) -> None:
        for ring in range(self.k):
            key = self._ring_key(endpoint, ring)
            lst = self._rings[ring]
            pos = bisect.bisect_left(lst, key, key=lambda e: e[0])
            assert pos < len(lst) and lst[pos][1] == endpoint
            lst.pop(pos)
            # Reference drops the hash cache entry on delete (Utils.java:232-234)
            self._hash_cache[ring].pop(endpoint, None)
        self._all_nodes.discard(endpoint)

    # -- public protocol surface --------------------------------------------

    def is_safe_to_join(self, node: Endpoint, node_id: NodeId) -> JoinStatusCode:
        """MembershipView.java:101-116."""
        if node in self._all_nodes:
            return JoinStatusCode.HOSTNAME_ALREADY_IN_RING
        if node_id in self._identifier_set:
            return JoinStatusCode.UUID_ALREADY_IN_RING
        return JoinStatusCode.SAFE_TO_JOIN

    def ring_add(self, node: Endpoint, node_id: NodeId) -> None:
        """MembershipView.java:124-161."""
        if node_id in self._identifier_set:
            raise UUIDAlreadySeenError(f"{node} with identifier already seen {node_id}")
        if node in self._all_nodes:
            raise NodeAlreadyInRingError(str(node))
        self._insert(node)
        bisect.insort(self._identifiers, node_id)
        self._identifier_set.add(node_id)
        self._config_dirty = True

    def ring_delete(self, node: Endpoint) -> None:
        """MembershipView.java:168-202."""
        if node not in self._all_nodes:
            raise NodeNotInRingError(str(node))
        self._remove(node)
        self._config_dirty = True

    def get_observers_of(self, node: Endpoint) -> List[Endpoint]:
        """The K successors of ``node`` (MembershipView.java:211-258)."""
        if node not in self._all_nodes:
            raise NodeNotInRingError(str(node))
        if len(self._rings[0]) <= 1:
            return []
        return [self._successor(ring, node) for ring in range(self.k)]

    def get_subjects_of(self, node: Endpoint) -> List[Endpoint]:
        """The K predecessors of ``node`` (MembershipView.java:268-283)."""
        if node not in self._all_nodes:
            raise NodeNotInRingError(str(node))
        if len(self._rings[0]) <= 1:
            return []
        return [self._predecessor(ring, node) for ring in range(self.k)]

    def get_expected_observers_of(self, node: Endpoint) -> List[Endpoint]:
        """Observers a *joining* (absent) node would have
        (MembershipView.java:293-304): its predecessors on each ring."""
        if not self._rings[0]:
            return []
        return [self._predecessor(ring, node) for ring in range(self.k)]

    def _successor(self, ring: int, node: Endpoint) -> Endpoint:
        lst = self._rings[ring]
        key = self._ring_key(ring=ring, endpoint=node)
        pos = bisect.bisect_right(lst, key, key=lambda e: e[0])
        if pos == len(lst):
            return lst[0][1]
        return lst[pos][1]

    def _predecessor(self, ring: int, node: Endpoint) -> Endpoint:
        lst = self._rings[ring]
        key = self._ring_key(ring=ring, endpoint=node)
        pos = bisect.bisect_left(lst, key, key=lambda e: e[0])
        if pos == 0:
            return lst[-1][1]
        return lst[pos - 1][1]

    def get_ring_numbers(self, observer: Endpoint, subject: Endpoint) -> List[int]:
        """Rings on which ``subject`` is ``observer``'s subject
        (MembershipView.java:398-419)."""
        subjects = self.get_subjects_of(observer)
        return [ring for ring, node in enumerate(subjects) if node == subject]

    def is_host_present(self, address: Endpoint) -> bool:
        return address in self._all_nodes

    def is_identifier_present(self, identifier: NodeId) -> bool:
        return identifier in self._identifier_set

    def get_ring(self, ring: int) -> List[Endpoint]:
        return [ep for _, ep in self._rings[ring]]

    @property
    def membership_size(self) -> int:
        return len(self._rings[0])

    def get_current_configuration_id(self) -> int:
        self.get_configuration()  # refresh if dirty
        return self._current_config_id

    def get_configuration(self) -> Configuration:
        if self._config_dirty or self._current_config is None:
            self._current_config = Configuration(
                node_ids=tuple(self._identifiers),
                endpoints=tuple(ep for _, ep in self._rings[0]),
            )
            self._current_config_id = self._current_config.configuration_id
            self._config_dirty = False
        return self._current_config
