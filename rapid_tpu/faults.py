"""Transport-agnostic deterministic fault injection: the nemesis plane.

Rapid's claim (PAPER.md, atc-2018 section 7) is stability under *messy*
failures -- one-way link loss, flip-flopping links, partial packet drops --
yet each transport historically had its own incompatible fault seam: the
in-process fabric's filters, the sim plane's mask arrays, nothing at all for
sockets. This module unifies them:

- :class:`FaultPlan`: a seeded, declarative schedule of per-link faults --
  probabilistic drops, one-way partitions with open/heal windows, flip-flop
  schedules, delay distributions, duplication and reordering. The plan is
  pure data; it carries no clocks or counters, so one plan replays across
  runs and transports.
- :class:`Nemesis`: one *armed* instance of a plan for one run: it sources
  time from the :class:`~.runtime.scheduler.Scheduler` seam (virtual-time
  runs stay discrete-event deterministic), derives every probabilistic
  decision from ``(plan seed, rule, link, per-link sequence number)`` via a
  keyed hash -- never from shared RNG state -- and counts injected faults
  into :mod:`~.observability` (``nemesis_*``).
- :class:`NemesisClient` / :class:`NemesisServer`: decorators over the
  ``IMessagingClient`` / ``IMessagingServer`` seams (messaging/base.py), so
  the same plan wraps the in-process, TCP and gRPC transports unchanged.
  The client additionally hardens ``send_message``: retries with the
  settings backoff policy and the per-message-type overall deadline
  (``Settings.deadline_for``), enforced uniformly at this layer whatever the
  wrapped transport does.
- :func:`replay_on_simulator`: compiles the device-plane-expressible subset
  of the same plan onto a :class:`~.sim.driver.Simulator`'s fault-schedule
  arrays segment by segment, so one seeded plan replays on both planes and
  parity tests can assert identical cuts and configuration ids.

Beyond the crash-adjacent battery, the plane models *gray* failures -- the
class where a component works by every binary check but is useless in
practice: :class:`SlowNodeRule` (alive, answering, too late),
:class:`LossyLinkRule` (connected, leaking), :class:`ClockSkewRule` (running,
on the wrong time), :class:`WireVersionRule` (speaking, in a different wire
dialect) -- and WAN latency structure via
:class:`~.sim.topology.LatencyTopology` attached with
``FaultPlan.with_topology``. ``RULE_CATALOG`` pins each rule's device-plane
story; tools/check.py keeps it exhaustive.

Egress rules (``at="egress"``, the default) are applied by the client
decorator at the sender; ingress rules by the server decorator at the
receiver. A rule is applied exactly once either way, so wrapping both halves
of every node (the normal setup) never double-applies a fault.
"""

from __future__ import annotations

import hashlib
import math
import random
import struct
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .hierarchy.cells import cell_of as _hier_cell_of
from .runtime.lockdep import make_lock
from .messaging.base import IMessagingClient, IMessagingServer
from .messaging.retries import call_with_retries
from .observability import Metrics, global_metrics
from .runtime.futures import Promise
from .runtime.scheduler import Scheduler
from .settings import Settings
from .types import Endpoint, ProbeMessage, RapidMessage

EGRESS = "egress"
INGRESS = "ingress"

# (start_ms, end_ms) relative to the nemesis arm epoch; end None = forever
Window = Tuple[int, Optional[int]]
_ALWAYS: Tuple[Window, ...] = ((0, None),)


def _u01(seed: int, *parts) -> float:
    """Deterministic uniform in [0, 1) keyed on ``(seed, parts)``.

    blake2b, not ``hash()``: decisions must not depend on per-process hash
    salting, and must not depend on draw interleaving across links -- each
    (rule, link, sequence-number) tuple owns its value outright.
    """
    h = hashlib.blake2b(digest_size=8)
    h.update(struct.pack("<q", seed))
    for part in parts:
        h.update(str(part).encode())
        h.update(b"\x1f")
    return int.from_bytes(h.digest(), "little") / 2.0**64


@dataclass(frozen=True)
class LinkMatch:
    """Which (src, dst, message type) triples a rule applies to; None = any."""

    src: Optional[Endpoint] = None
    dst: Optional[Endpoint] = None
    msg_types: Optional[Tuple[type, ...]] = None

    def matches(self, src: Optional[Endpoint], dst: Optional[Endpoint],
                msg: RapidMessage) -> bool:
        if self.src is not None and src != self.src:
            return False
        if self.dst is not None and dst != self.dst:
            return False
        if self.msg_types is not None and not isinstance(msg, self.msg_types):
            return False
        return True


@dataclass(frozen=True)
class Rule:
    """Base: a link selector, an application side, and open/heal windows."""

    match: LinkMatch = LinkMatch()
    at: str = EGRESS
    windows: Tuple[Window, ...] = _ALWAYS

    def active_at(self, t_ms: int) -> bool:
        return any(
            start <= t_ms and (end is None or t_ms < end)
            for start, end in self.windows
        )


@dataclass(frozen=True)
class DropRule(Rule):
    """Drop each matching message independently with ``probability``."""

    probability: float = 1.0


@dataclass(frozen=True)
class PartitionRule(Rule):
    """Deterministic one-way cut while a window is open (iptables INPUT)."""


@dataclass(frozen=True)
class CellPartitionRule(Rule):
    """Hierarchy-plane fault: cut every link CROSSING cell ``cell``'s
    boundary while a window is open, leaving intra-cell traffic alone --
    the cell keeps running Rapid internally but its leader can no longer
    reach peer leaders (and vice versa). ``cells`` is the rendezvous cell
    count (hierarchy/cells.py); with a plan topology the zone is the cell,
    matching the engine's assignment discipline."""

    cell: int = 0
    cells: int = 2


@dataclass(frozen=True)
class FlipFlopRule(Rule):
    """The paper's flip-flop failure: the link alternates cut/healed every
    half ``period_ms``, starting cut at ``start_ms`` (within the windows)."""

    period_ms: int = 2000
    start_ms: int = 0

    def active_at(self, t_ms: int) -> bool:
        if t_ms < self.start_ms or not super().active_at(t_ms):
            return False
        half = max(1, self.period_ms // 2)
        return ((t_ms - self.start_ms) // half) % 2 == 0


@dataclass(frozen=True)
class DelayRule(Rule):
    """Extra one-way latency: ``base_ms`` plus uniform [0, jitter_ms]."""

    base_ms: int = 0
    jitter_ms: int = 0


@dataclass(frozen=True)
class DuplicateRule(Rule):
    """Deliver a second copy of each matching message with ``probability``."""

    probability: float = 0.0


@dataclass(frozen=True)
class ReorderRule(Rule):
    """Hold back each matching message with ``probability`` by a uniform
    [1, max_extra_ms] extra delay, letting later traffic overtake it."""

    probability: float = 0.0
    max_extra_ms: int = 100


@dataclass(frozen=True)
class LossyLinkRule(DropRule):
    """Gray failure: the link stays *connected* but drops a sustained
    ``probability`` of traffic -- below the one-way-cut threshold a
    PartitionRule models. A distinct class (not just a DropRule with small
    p) so plans, telemetry and the device catalog name the failure mode the
    paper's flip-flop battery gestures at but never isolates."""


@dataclass(frozen=True)
class SlowNodeRule(Rule):
    """Gray failure: the matched destination answers *every* message, just
    ``response_delay_ms`` late. When that exceeds the sender's per-message
    timeout the sender observes a timeout -- exactly what a gray node looks
    like from an FD's perspective -- while the node itself keeps receiving
    and processing traffic (it is alive, voting, and will answer probes it
    receives; only its answers come back too late to matter)."""

    response_delay_ms: int = 0


@dataclass(frozen=True)
class ClockSkewRule(Rule):
    """Gray failure: the matched *source* node's clock runs at ``rate``×
    real time, offset by ``offset_ms``. Consulted through
    :meth:`Nemesis.scheduler_for`, not the message path: the skewed node's
    timers (FD probe intervals, retry backoff, message deadlines) all fire
    early or late by the drift while every other node keeps true time."""

    offset_ms: int = 0
    rate: float = 1.0


@dataclass(frozen=True)
class WireVersionRule(Rule):
    """Rolling upgrade: the matched *source* node encodes every egress
    message at wire ``version`` -- round-tripped through the real codec with
    that version's reserved ``__``-prefixed extension keys injected (newer
    peer) or optional defaulted fields thinned (older peer) -- proving the
    mixed-version cluster converges on bytes a same-version cluster never
    exercises. See messaging/codec.py:wire_roundtrip."""

    version: int = 2


@dataclass(frozen=True)
class RestartNodeRule(Rule):
    """Process restart: the matched destination is dead for the span of
    each window (killed at its start, restarted -- with WAL recovery --
    at its end). The windows ARE the down periods, so they must all be
    closed: an open-ended window is a crash-stop, which PartitionRule and
    the fabric's eviction machinery already model. While down the node
    neither answers nor sends; at the window's end the harness recovers
    its durable store (log-over-snapshot) and re-pulls whatever it missed
    through verified handoff catch-up."""


@dataclass(frozen=True)
class TornWriteRule(Rule):
    """Storage fault: the matched destination's WAL tail is torn while it
    is down -- ``drop_bytes`` truncated off the last segment, or
    (``corrupt``) a byte inside the final record flipped so its CRC fails
    -- modeling a crash mid-append or a half-flushed page. Applied by the
    recovery harness at restart (the message plane is untouched): recovery
    must truncate at the first bad record and converge via catch-up."""

    drop_bytes: int = 3
    corrupt: bool = False


@dataclass(frozen=True)
class DiskStallRule(Rule):
    """Gray storage failure: every fsync on the matched destination takes
    ``stall_ms`` extra -- a dying disk, a saturated EBS volume. The rule
    matches the ``Put`` wire (builder-enforced) so the serving plane's
    quorum writes feel it while probes stay unaffected: the node looks
    healthy to every FD while its write path quietly drags."""

    stall_ms: int = 0


# Device-plane behavior of every Rule subclass; tools/check.py lints that
# this catalog and the set of Rule subclasses in this module stay in sync.
#   compiled  -- mapped onto the Simulator's fault arrays by apply_plan_at
#   absorbed  -- invisible to the round model within a documented bound,
#                outside which _device_rules raises UnsupportedDeviceFault
RULE_CATALOG = {
    "DropRule": "compiled",        # -> Simulator.ingress_loss
    "PartitionRule": "compiled",   # -> Simulator.one_way_ingress_partition
    "CellPartitionRule": "compiled",  # cell slots -> ingress partition
    "FlipFlopRule": "compiled",    # -> partition toggled at phase edges
    "LossyLinkRule": "compiled",   # -> Simulator.ingress_loss
    "SlowNodeRule": "compiled",    # >= one round -> partition-equivalent
    "DelayRule": "absorbed",       # sub-round latency only
    "DuplicateRule": "absorbed",   # probe exchanges are idempotent
    "ReorderRule": "absorbed",     # intra-round reordering only
    "ClockSkewRule": "absorbed",   # bounded drift never flips a round
    "WireVersionRule": "absorbed", # wire bytes are not modeled on device
    "RestartNodeRule": "compiled", # down window -> partition-equivalent cut
    "TornWriteRule": "absorbed",   # storage-level; no device storage model
    "DiskStallRule": "absorbed",   # Put-path latency; probes unaffected
}


class FaultPlan:
    """A seeded, declarative fault schedule (pure data, reusable across runs).

    Builder methods append immutable rules and return ``self``::

        plan = (FaultPlan(seed=7)
                .partition_one_way(dst=victim)                  # from t=0 on
                .flip_flop(period_ms=4000, dst=other)
                .drop(0.2, msg_types=(ProbeMessage,))
                .delay(base_ms=10, jitter_ms=5, src=a, dst=b))
    """

    def __init__(self, seed: int = 0) -> None:
        self.seed = int(seed)
        self.rules: List[Rule] = []
        # optional WAN latency structure (sim/topology.py LatencyTopology):
        # every egress decision adds the topology's one-way latency for the
        # (src, dst) pair; topology_slots maps protocol-plane endpoints to
        # topology indices (device-plane slots ARE indices)
        self.topology = None
        self.topology_slots: Dict[Endpoint, int] = {}

    def with_topology(self, topology,
                      slots: Optional[Dict[Endpoint, int]] = None) -> "FaultPlan":
        """Attach a :class:`~.sim.topology.LatencyTopology`. ``slots`` maps
        each protocol-plane endpoint to its topology index (omit on the
        device plane, where slot == index)."""
        self.topology = topology
        self.topology_slots = dict(slots) if slots else {}
        return self

    @staticmethod
    def _check_windows(windows: Tuple[Window, ...]) -> None:
        """Reject windows that could never fire (a silent no-op fault plan
        is a test that asserts nothing)."""
        for start, end in windows:
            if start < 0:
                raise ValueError(f"window start {start} < 0")
            if end is not None and end <= start:
                raise ValueError(
                    f"window ({start}, {end}) can never fire: end <= start"
                )

    @staticmethod
    def _overlap(a: Tuple[Window, ...], b: Tuple[Window, ...]) -> bool:
        return any(
            (e2 is None or s1 < e2) and (e1 is None or s2 < e1)
            for s1, e1 in a
            for s2, e2 in b
        )

    def _check_partition_conflicts(self, rule: Rule) -> None:
        """A PartitionRule and a FlipFlopRule (or two schedule-bearing
        partition rules) on the SAME link with overlapping windows
        contradict each other -- the plain cut masks the flip-flop's healed
        phases, so the plan silently tests less than it claims."""
        if not isinstance(rule, (PartitionRule, FlipFlopRule)):
            return
        for prior in self.rules:
            if not isinstance(prior, (PartitionRule, FlipFlopRule)):
                continue
            if (prior.match.src, prior.match.dst, prior.at) != (
                rule.match.src, rule.match.dst, rule.at
            ):
                continue
            if self._overlap(prior.windows, rule.windows):
                raise ValueError(
                    f"contradictory partition rules on the same link "
                    f"{rule.match.src} -> {rule.match.dst}: "
                    f"{type(prior).__name__}{prior.windows} overlaps "
                    f"{type(rule).__name__}{rule.windows}"
                )

    def _add(self, rule: Rule) -> "FaultPlan":
        assert rule.at in (EGRESS, INGRESS), rule.at
        self._check_windows(rule.windows)
        self._check_partition_conflicts(rule)
        self.rules.append(rule)
        return self

    @staticmethod
    def _match(src, dst, msg_types) -> LinkMatch:
        return LinkMatch(
            src=src, dst=dst,
            msg_types=tuple(msg_types) if msg_types is not None else None,
        )

    def drop(self, probability: float, src: Optional[Endpoint] = None,
             dst: Optional[Endpoint] = None, msg_types=None,
             windows: Tuple[Window, ...] = _ALWAYS,
             at: str = EGRESS) -> "FaultPlan":
        assert 0.0 <= probability <= 1.0, probability
        return self._add(DropRule(
            match=self._match(src, dst, msg_types), at=at, windows=windows,
            probability=probability,
        ))

    def partition_one_way(self, src: Optional[Endpoint] = None,
                          dst: Optional[Endpoint] = None,
                          windows: Tuple[Window, ...] = _ALWAYS,
                          at: str = EGRESS) -> "FaultPlan":
        return self._add(PartitionRule(
            match=self._match(src, dst, None), at=at, windows=windows,
        ))

    def cell_partition(self, cell: int, cells: int,
                       windows: Tuple[Window, ...] = _ALWAYS,
                       at: str = EGRESS) -> "FaultPlan":
        """Isolate hierarchy cell ``cell`` (of ``cells``) from every other
        cell while a window is open: cross-boundary messages drop in both
        directions, intra-cell traffic is untouched."""
        if cells < 2:
            raise ValueError(
                f"a cell partition needs >= 2 cells, got {cells}"
            )
        if not 0 <= cell < cells:
            raise ValueError(f"cell {cell} outside [0, {cells})")
        return self._add(CellPartitionRule(
            match=self._match(None, None, None), at=at, windows=windows,
            cell=cell, cells=cells,
        ))

    def flip_flop(self, period_ms: int, src: Optional[Endpoint] = None,
                  dst: Optional[Endpoint] = None, start_ms: int = 0,
                  windows: Tuple[Window, ...] = _ALWAYS,
                  at: str = EGRESS) -> "FaultPlan":
        assert period_ms >= 2, period_ms
        return self._add(FlipFlopRule(
            match=self._match(src, dst, None), at=at, windows=windows,
            period_ms=period_ms, start_ms=start_ms,
        ))

    def delay(self, base_ms: int, jitter_ms: int = 0,
              src: Optional[Endpoint] = None, dst: Optional[Endpoint] = None,
              msg_types=None, windows: Tuple[Window, ...] = _ALWAYS,
              at: str = EGRESS) -> "FaultPlan":
        assert base_ms >= 0 and jitter_ms >= 0
        return self._add(DelayRule(
            match=self._match(src, dst, msg_types), at=at, windows=windows,
            base_ms=base_ms, jitter_ms=jitter_ms,
        ))

    def duplicate(self, probability: float, src: Optional[Endpoint] = None,
                  dst: Optional[Endpoint] = None, msg_types=None,
                  windows: Tuple[Window, ...] = _ALWAYS,
                  at: str = EGRESS) -> "FaultPlan":
        assert 0.0 <= probability <= 1.0, probability
        return self._add(DuplicateRule(
            match=self._match(src, dst, msg_types), at=at, windows=windows,
            probability=probability,
        ))

    def reorder(self, probability: float, max_extra_ms: int = 100,
                src: Optional[Endpoint] = None,
                dst: Optional[Endpoint] = None, msg_types=None,
                windows: Tuple[Window, ...] = _ALWAYS,
                at: str = EGRESS) -> "FaultPlan":
        assert 0.0 <= probability <= 1.0, probability
        assert max_extra_ms >= 1
        return self._add(ReorderRule(
            match=self._match(src, dst, msg_types), at=at, windows=windows,
            probability=probability, max_extra_ms=max_extra_ms,
        ))

    def lossy_link(self, probability: float, src: Optional[Endpoint] = None,
                   dst: Optional[Endpoint] = None, msg_types=None,
                   windows: Tuple[Window, ...] = _ALWAYS,
                   at: str = EGRESS) -> "FaultPlan":
        if not 0.0 < probability < 1.0:
            raise ValueError(
                f"a lossy link drops some but not all traffic; p="
                f"{probability} is a {'partition' if probability == 1.0 else 'no-op'}"
            )
        return self._add(LossyLinkRule(
            match=self._match(src, dst, msg_types), at=at, windows=windows,
            probability=probability,
        ))

    def slow_node(self, node: Endpoint, response_delay_ms: int,
                  windows: Tuple[Window, ...] = _ALWAYS) -> "FaultPlan":
        assert response_delay_ms >= 1, response_delay_ms
        return self._add(SlowNodeRule(
            match=self._match(None, node, None), at=EGRESS, windows=windows,
            response_delay_ms=response_delay_ms,
        ))

    def clock_skew(self, node: Endpoint, offset_ms: int = 0,
                   rate: float = 1.0) -> "FaultPlan":
        if rate <= 0.0:
            raise ValueError(f"clock rate must be positive, got {rate}")
        # no windows: a clock that jumps mid-run would retroactively reorder
        # already-scheduled timers, which no real skewed clock does
        return self._add(ClockSkewRule(
            match=self._match(node, None, None), at=EGRESS, windows=_ALWAYS,
            offset_ms=offset_ms, rate=rate,
        ))

    def wire_version(self, node: Endpoint, version: int,
                     windows: Tuple[Window, ...] = _ALWAYS) -> "FaultPlan":
        return self._add(WireVersionRule(
            match=self._match(node, None, None), at=EGRESS, windows=windows,
            version=version,
        ))

    def restart_node(self, node: Endpoint,
                     windows: Tuple[Window, ...]) -> "FaultPlan":
        """Kill ``node`` at each window's start and restart it (with
        recovery) at its end. Windows must be closed -- an open-ended one
        is a crash-stop, which partition_one_way already models."""
        if not windows:
            raise ValueError("restart_node needs at least one down window")
        if any(end is None for _start, end in windows):
            raise ValueError(
                "restart_node windows must be closed (a restart implies a "
                "return); use partition_one_way for a crash-stop"
            )
        return self._add(RestartNodeRule(
            match=self._match(None, node, None), at=EGRESS, windows=windows,
        ))

    def torn_write(self, node: Endpoint,
                   windows: Tuple[Window, ...] = _ALWAYS,
                   drop_bytes: int = 3, corrupt: bool = False) -> "FaultPlan":
        """Tear ``node``'s WAL tail during recovery from any restart that
        overlaps a window: truncate ``drop_bytes`` off the last segment,
        or flip a byte in its final record when ``corrupt``."""
        if drop_bytes < 1:
            raise ValueError(f"drop_bytes must be >= 1, got {drop_bytes}")
        return self._add(TornWriteRule(
            match=self._match(None, node, None), at=EGRESS, windows=windows,
            drop_bytes=drop_bytes, corrupt=bool(corrupt),
        ))

    def disk_stall(self, node: Endpoint, stall_ms: int,
                   windows: Tuple[Window, ...] = _ALWAYS) -> "FaultPlan":
        """Every fsync on ``node`` takes ``stall_ms`` extra; surfaces on
        the Put wire (quorum writes drag) while probes stay healthy."""
        from .types import Put

        if stall_ms < 1:
            raise ValueError(f"stall_ms must be >= 1, got {stall_ms}")
        return self._add(DiskStallRule(
            match=self._match(None, node, (Put,)), at=EGRESS,
            windows=windows, stall_ms=stall_ms,
        ))

    def to_json(self) -> dict:
        """JSON-able dict of the whole plan: rules (with windows and link
        matches), seed, topology + endpoint slots. ``from_json`` is the
        inverse; the pair is what lets the nemesis search pin shrunk plans
        as corpus files (scenarios/corpus/)."""
        data: dict = {
            "seed": self.seed,
            "rules": [_rule_to_json(rule) for rule in self.rules],
        }
        if self.topology is not None:
            data["topology"] = {
                name: int(getattr(self.topology, name))
                for name in _TOPOLOGY_FIELDS
            }
        if self.topology_slots:
            data["topology_slots"] = {
                str(ep): int(slot)
                for ep, slot in sorted(self.topology_slots.items())
            }
        return data

    @staticmethod
    def from_json(data: dict) -> "FaultPlan":
        """Rebuild a plan from ``to_json`` output by re-invoking the builder
        methods, so every construction-time check (window sanity, partition
        conflicts, parameter ranges) re-runs on load -- a corpus file cannot
        smuggle in a plan the builders would have rejected. Raises
        ValueError on unknown rule/message/topology fields and whatever the
        builders raise on invalid parameters."""
        if not isinstance(data, dict):
            raise ValueError(
                f"fault plan must be a JSON object, got {type(data).__name__}"
            )
        plan = FaultPlan(seed=int(data.get("seed", 0)))
        for spec in data.get("rules", ()):
            _build_rule(plan, spec)
        topo = data.get("topology")
        slots_raw = data.get("topology_slots") or {}
        if topo is not None:
            from .sim.topology import LatencyTopology

            unknown = set(topo) - set(_TOPOLOGY_FIELDS)
            if unknown:
                raise ValueError(f"unknown topology fields {sorted(unknown)}")
            slots = {
                Endpoint.from_string(ep): int(slot)
                for ep, slot in slots_raw.items()
            }
            plan.with_topology(
                LatencyTopology(**{k: int(v) for k, v in topo.items()}),
                slots or None,
            )
        elif slots_raw:
            raise ValueError("topology_slots without a topology")
        return plan


# LatencyTopology's full constructor surface, in declaration order
_TOPOLOGY_FIELDS = (
    "racks", "zones", "regions", "rack_rtt_ms", "zone_rtt_ms",
    "region_rtt_ms", "inter_region_rtt_ms",
)


def _msg_type(name: str) -> type:
    from . import types as _types

    cls = getattr(_types, name, None)
    if not isinstance(cls, type):
        raise ValueError(f"unknown message type {name!r} in rapid_tpu.types")
    return cls


def _rule_to_json(rule: Rule) -> dict:
    msg_types = None
    if rule.match.msg_types is not None:
        for cls in rule.match.msg_types:
            if _msg_type(cls.__name__) is not cls:
                raise ValueError(
                    f"message type {cls!r} is not addressable by name "
                    f"in rapid_tpu.types; the plan cannot round-trip"
                )
        msg_types = [cls.__name__ for cls in rule.match.msg_types]
    spec: dict = {
        "type": type(rule).__name__,
        "at": rule.at,
        "windows": [[start, end] for start, end in rule.windows],
        "src": None if rule.match.src is None else str(rule.match.src),
        "dst": None if rule.match.dst is None else str(rule.match.dst),
        "msg_types": msg_types,
    }
    if isinstance(rule, FlipFlopRule):
        spec["period_ms"] = rule.period_ms
        spec["start_ms"] = rule.start_ms
    elif isinstance(rule, CellPartitionRule):
        spec["cell"] = rule.cell
        spec["cells"] = rule.cells
    elif isinstance(rule, DropRule):  # includes LossyLinkRule
        spec["probability"] = rule.probability
    elif isinstance(rule, DelayRule):
        spec["base_ms"] = rule.base_ms
        spec["jitter_ms"] = rule.jitter_ms
    elif isinstance(rule, DuplicateRule):
        spec["probability"] = rule.probability
    elif isinstance(rule, ReorderRule):
        spec["probability"] = rule.probability
        spec["max_extra_ms"] = rule.max_extra_ms
    elif isinstance(rule, SlowNodeRule):
        spec["response_delay_ms"] = rule.response_delay_ms
    elif isinstance(rule, ClockSkewRule):
        spec["offset_ms"] = rule.offset_ms
        spec["rate"] = rule.rate
    elif isinstance(rule, WireVersionRule):
        spec["version"] = rule.version
    elif isinstance(rule, TornWriteRule):
        spec["drop_bytes"] = rule.drop_bytes
        spec["corrupt"] = rule.corrupt
    elif isinstance(rule, DiskStallRule):
        spec["stall_ms"] = rule.stall_ms
    return spec


def _build_rule(plan: FaultPlan, spec: dict) -> None:
    if not isinstance(spec, dict):
        raise ValueError(
            f"rule spec must be a JSON object, got {type(spec).__name__}"
        )
    kind = spec.get("type")
    windows = tuple(
        (int(start), None if end is None else int(end))
        for start, end in (spec.get("windows") or _ALWAYS)
    )
    src = spec.get("src")
    src = None if src is None else Endpoint.from_string(src)
    dst = spec.get("dst")
    dst = None if dst is None else Endpoint.from_string(dst)
    raw_types = spec.get("msg_types")
    msg_types = (
        None if raw_types is None
        else tuple(_msg_type(name) for name in raw_types)
    )
    at = spec.get("at", EGRESS)
    common = dict(src=src, dst=dst, msg_types=msg_types, windows=windows,
                  at=at)
    if kind == "DropRule":
        plan.drop(float(spec["probability"]), **common)
    elif kind == "PartitionRule":
        plan.partition_one_way(src=src, dst=dst, windows=windows, at=at)
    elif kind == "CellPartitionRule":
        plan.cell_partition(int(spec["cell"]), int(spec["cells"]),
                            windows=windows, at=at)
    elif kind == "FlipFlopRule":
        plan.flip_flop(int(spec["period_ms"]), src=src, dst=dst,
                       start_ms=int(spec.get("start_ms", 0)),
                       windows=windows, at=at)
    elif kind == "DelayRule":
        plan.delay(int(spec["base_ms"]), int(spec.get("jitter_ms", 0)),
                   **common)
    elif kind == "DuplicateRule":
        plan.duplicate(float(spec["probability"]), **common)
    elif kind == "ReorderRule":
        plan.reorder(float(spec["probability"]),
                     int(spec.get("max_extra_ms", 100)), **common)
    elif kind == "LossyLinkRule":
        plan.lossy_link(float(spec["probability"]), **common)
    elif kind == "SlowNodeRule":
        if dst is None:
            raise ValueError("SlowNodeRule needs a dst node")
        plan.slow_node(dst, int(spec["response_delay_ms"]), windows=windows)
    elif kind == "ClockSkewRule":
        if src is None:
            raise ValueError("ClockSkewRule needs a src node")
        plan.clock_skew(src, offset_ms=int(spec.get("offset_ms", 0)),
                        rate=float(spec.get("rate", 1.0)))
    elif kind == "WireVersionRule":
        if src is None:
            raise ValueError("WireVersionRule needs a src node")
        plan.wire_version(src, int(spec["version"]), windows=windows)
    elif kind == "RestartNodeRule":
        if dst is None:
            raise ValueError("RestartNodeRule needs a dst node")
        plan.restart_node(dst, windows=windows)
    elif kind == "TornWriteRule":
        if dst is None:
            raise ValueError("TornWriteRule needs a dst node")
        plan.torn_write(dst, windows=windows,
                        drop_bytes=int(spec.get("drop_bytes", 3)),
                        corrupt=bool(spec.get("corrupt", False)))
    elif kind == "DiskStallRule":
        if dst is None:
            raise ValueError("DiskStallRule needs a dst node")
        plan.disk_stall(dst, int(spec["stall_ms"]), windows=windows)
    else:
        raise ValueError(f"unknown rule type {kind!r}")


@dataclass
class Decision:
    """What the plane does to one message."""

    drop: bool = False
    delay_ms: int = 0
    duplicates: int = 0
    reordered: bool = False
    # gray-failure extensions: slow_ms is the destination's response latency
    # (sender sees a timeout when it exceeds the message deadline, but the
    # message is still delivered); wire_version re-encodes the message
    # through the versioned codec round-trip
    slow_ms: int = 0
    wire_version: Optional[int] = None


class SkewedScheduler(Scheduler):
    """A node's drifted view of the shared clock (ClockSkewRule).

    ``now_ms`` reads ``rate * true + offset_ms``; a delay the node asks for
    in its own time costs ``delay / rate`` of true time (a fast clock fires
    its timers early). Purely arithmetic over the wrapped scheduler, so
    virtual-time determinism is untouched -- the skewed node's events still
    land at exact integer virtual times."""

    def __init__(self, inner: Scheduler, offset_ms: int = 0,
                 rate: float = 1.0) -> None:
        assert rate > 0.0, rate
        self.inner = inner
        self.offset_ms = int(offset_ms)
        self.rate = float(rate)

    def now_ms(self) -> int:
        return int(self.inner.now_ms() * self.rate) + self.offset_ms

    def _true_delay(self, delay_ms: int) -> int:
        return max(0, int(round(delay_ms / self.rate)))

    def schedule(self, delay_ms, fn):
        return self.inner.schedule(self._true_delay(delay_ms), fn)

    def schedule_at_fixed_rate(self, initial_delay_ms, period_ms, fn):
        return self.inner.schedule_at_fixed_rate(
            self._true_delay(initial_delay_ms),
            max(1, self._true_delay(period_ms)), fn,
        )

    def execute(self, fn) -> None:
        self.inner.execute(fn)

    def shutdown(self) -> None:
        pass  # the true scheduler is shared; its owner shuts it down


class Nemesis:
    """One armed instance of a plan for one run: epoch, decision streams,
    counters. Create one per cluster run; mint decorators from it."""

    def __init__(self, plan: FaultPlan, scheduler: Scheduler,
                 metrics: Optional[Metrics] = None) -> None:
        self.plan = plan
        self.scheduler = scheduler
        self.metrics = metrics if metrics is not None else global_metrics()
        self._epoch: Optional[int] = None
        # (rule index, src str, dst str) -> decisions drawn so far
        self._seq: Dict[Tuple[int, str, str], int] = {}
        self._lock = make_lock("Nemesis._lock")
        # one skewed clock per ClockSkewRule'd node, cached so every consumer
        # of a node's clock (client deadlines, FD intervals, retry backoff)
        # shares the same drifted view
        self._skewed: Dict[Endpoint, Scheduler] = {}

    # -- clock ---------------------------------------------------------------

    def arm(self, epoch_ms: Optional[int] = None) -> "Nemesis":
        """Pin plan-time zero (default: now). Windows are relative to this;
        re-arming after bootstrap starts the schedule from a healthy view."""
        self._epoch = (
            epoch_ms if epoch_ms is not None else self.scheduler.now_ms()
        )
        return self

    def plan_now_ms(self) -> int:
        if self._epoch is None:
            self.arm()
        return self.scheduler.now_ms() - self._epoch

    # -- decorators ----------------------------------------------------------

    def client(self, inner: IMessagingClient, address: Optional[Endpoint] = None,
               settings: Optional[Settings] = None) -> "NemesisClient":
        return NemesisClient(inner, self, address=address, settings=settings)

    def server(self, inner: IMessagingServer,
               address: Endpoint) -> "NemesisServer":
        return NemesisServer(inner, self, address)

    # -- decisions -----------------------------------------------------------

    def _draw(self, rule_idx: int, src: str, dst: str) -> float:
        key = (rule_idx, src, dst)
        with self._lock:
            n = self._seq.get(key, 0)
            self._seq[key] = n + 1
        return _u01(self.plan.seed, rule_idx, src, dst, n)

    def retry_rng(self, address: Optional[Endpoint]) -> random.Random:
        """Per-sender seeded rng for backoff jitter draws."""
        tag = str(address).encode() if address is not None else b"?"
        return random.Random(self.plan.seed ^ zlib.crc32(tag))

    def scheduler_for(self, address: Optional[Endpoint]) -> Scheduler:
        """The clock ``address`` lives by: the shared scheduler, or its
        drifted wrapper when a ClockSkewRule names the node. Harnesses build
        each node's timers against this seam, so one skewed node perturbs
        its own FD deadlines and retry backoff while the rest of the cluster
        keeps true time."""
        if address is None:
            return self.scheduler
        cached = self._skewed.get(address)
        if cached is not None:
            return cached
        for rule in self.plan.rules:
            if isinstance(rule, ClockSkewRule) and rule.match.src == address:
                skewed = SkewedScheduler(
                    self.scheduler, offset_ms=rule.offset_ms, rate=rule.rate
                )
                self._skewed[address] = skewed
                return skewed
        self._skewed[address] = self.scheduler
        return self.scheduler

    def decide(self, src: Optional[Endpoint], dst: Optional[Endpoint],
               msg: RapidMessage, at: str) -> Decision:
        t = self.plan_now_ms()
        out = Decision()
        src_s, dst_s = str(src), str(dst)
        for idx, rule in enumerate(self.plan.rules):
            if rule.at != at or not rule.match.matches(src, dst, msg):
                continue
            if not rule.active_at(t):
                continue
            if isinstance(rule, CellPartitionRule):
                # cross-boundary cut: drop iff exactly one end is inside
                # the partitioned cell (intra-cell traffic untouched)
                if src is not None and dst is not None:
                    in_src = _hier_cell_of(
                        src, rule.cells, topology=self.plan.topology,
                        slots=self.plan.topology_slots or None,
                    ) == rule.cell
                    in_dst = _hier_cell_of(
                        dst, rule.cells, topology=self.plan.topology,
                        slots=self.plan.topology_slots or None,
                    ) == rule.cell
                    if in_src != in_dst:
                        out.drop = True
            elif isinstance(rule, (PartitionRule, FlipFlopRule,
                                   RestartNodeRule)):
                # a down-window restart victim is, to the message plane, a
                # one-way cut; its recovery semantics live in the harness
                out.drop = True
            elif isinstance(rule, DropRule):
                if self._draw(idx, src_s, dst_s) < rule.probability:
                    out.drop = True
            elif isinstance(rule, DelayRule):
                jitter = (
                    int(self._draw(idx, src_s, dst_s) * (rule.jitter_ms + 1))
                    if rule.jitter_ms > 0 else 0
                )
                out.delay_ms += rule.base_ms + jitter
            elif isinstance(rule, DuplicateRule):
                if self._draw(idx, src_s, dst_s) < rule.probability:
                    out.duplicates += 1
            elif isinstance(rule, ReorderRule):
                if self._draw(idx, src_s, dst_s) < rule.probability:
                    held = 1 + int(
                        self._draw(idx, src_s, dst_s) * rule.max_extra_ms
                    )
                    out.delay_ms += min(held, rule.max_extra_ms)
                    out.reordered = True
            elif isinstance(rule, SlowNodeRule):
                out.slow_ms = max(out.slow_ms, rule.response_delay_ms)
            elif isinstance(rule, DiskStallRule):
                # the match restricts this to the Put wire: the stalled
                # fsync surfaces as a late quorum-write answer
                out.slow_ms = max(out.slow_ms, rule.stall_ms)
            elif isinstance(rule, WireVersionRule):
                out.wire_version = rule.version
            # ClockSkewRule is consulted via scheduler_for, not per message
        topo = self.plan.topology
        if topo is not None and at == EGRESS:
            # WAN latency structure: the topology's one-way delay applies to
            # every message whose endpoints are placed (egress only, so
            # wrapping both halves of a node never doubles the RTT)
            si = self.plan.topology_slots.get(src)
            di = self.plan.topology_slots.get(dst)
            if si is not None and di is not None:
                out.delay_ms += topo.one_way_ms(si, di)
        return out


def _pipe(src: Promise, dst: Promise) -> None:
    if dst.done():
        return
    exc = src.exception()
    if exc is not None:
        dst.try_set_exception(exc)
    else:
        dst.try_set_result(src._result)  # noqa: SLF001 -- promise-internal copy


class NemesisClient(IMessagingClient):
    """Egress fault application + uniformly hardened send_message.

    ``send_message`` re-homes the retry loop at this layer: every attempt
    traverses the fault plane once, attempts are spaced by the settings
    backoff policy, and the whole exchange is bounded by the per-message-type
    deadline (``Settings.deadline_for``) on the scheduler's clock --
    identical semantics over every wrapped transport.
    """

    def __init__(self, inner: IMessagingClient, nemesis: Nemesis,
                 address: Optional[Endpoint] = None,
                 settings: Optional[Settings] = None) -> None:
        self.inner = inner
        self.address = (
            address if address is not None else getattr(inner, "address", None)
        )
        self._nem = nemesis
        inherited = getattr(inner, "_settings", None)
        self._settings = (
            settings if settings is not None
            else inherited if inherited is not None else Settings()
        )
        # the clock this node lives by: drifted when a ClockSkewRule names
        # it, so its timeouts/backoff/deadlines all skew together
        self._sched = nemesis.scheduler_for(self.address)

    def send_message(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        return call_with_retries(
            lambda: self._attempt(remote, msg),
            self._settings.message_retries,
            scheduler=self._sched,
            policy=self._settings.retry_policy(),
            deadline_ms=self._settings.deadline_for(msg),
            rng=self._nem.retry_rng(self.address),
            metrics=self._nem.metrics,
        )

    def send_message_best_effort(self, remote: Endpoint,
                                 msg: RapidMessage) -> Promise:
        return self._attempt(remote, msg)

    def _attempt(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        d = self._nem.decide(self.address, remote, msg, EGRESS)
        metrics = self._nem.metrics
        # labeled by fault application point and message type; unlabeled
        # reads (metrics.get("nemesis_dropped")) sum across the label sets
        kind = type(msg).__name__
        if d.wire_version is not None:
            from .messaging.codec import wire_roundtrip

            metrics.incr("nemesis_wire_versioned", at="egress", msg=kind)
            msg = wire_roundtrip(msg, d.wire_version)
        if d.drop:
            metrics.incr("nemesis_dropped", at="egress", msg=kind)
            # dropped on the wire: the sender only ever sees its per-message
            # deadline expire, exactly like the in-process fabric's filters
            out: Promise = Promise()
            timeout = self._settings.timeout_for(msg)
            self._sched.schedule(
                timeout,
                lambda: out.try_set_exception(TimeoutError(
                    f"nemesis dropped {type(msg).__name__} to {remote}"
                )),
            )
            return out
        for _ in range(d.duplicates):
            metrics.incr("nemesis_duplicated", at="egress", msg=kind)
            self.inner.send_message_best_effort(remote, msg)
        if d.slow_ms > 0:
            # gray node: the message IS delivered (and answered) slow_ms
            # late; the sender's own deadline decides whether that answer
            # still counts. Past the timeout this is indistinguishable from
            # a drop at the sender -- which is the whole failure mode.
            metrics.incr("nemesis_slowed", at="egress", msg=kind)
            out = Promise()
            total = d.slow_ms + d.delay_ms
            self._nem.scheduler.schedule(
                total,
                lambda: self.inner.send_message_best_effort(
                    remote, msg
                ).add_callback(lambda p: _pipe(p, out)),
            )
            timeout = self._settings.timeout_for(msg)
            if total >= timeout:
                self._sched.schedule(
                    timeout,
                    lambda: out.try_set_exception(TimeoutError(
                        f"{remote} answered {total} ms late "
                        f"(> {timeout} ms timeout)"
                    )),
                )
            return out
        if d.delay_ms > 0:
            metrics.incr(
                "nemesis_reordered" if d.reordered else "nemesis_delayed",
                at="egress", msg=kind,
            )
            out = Promise()
            self._nem.scheduler.schedule(
                d.delay_ms,
                lambda: self.inner.send_message_best_effort(
                    remote, msg
                ).add_callback(lambda p: _pipe(p, out)),
            )
            return out
        metrics.incr("nemesis_passed", at="egress", msg=kind)
        return self.inner.send_message_best_effort(remote, msg)

    def shutdown(self) -> None:
        self.inner.shutdown()


class _NemesisServiceFilter:
    """Ingress fault application, inserted between the real server and its
    MembershipService: ``handle_message`` is the one dispatch seam every
    transport shares, so wrapping the service faults them all identically."""

    def __init__(self, service, nemesis: Nemesis, address: Endpoint) -> None:
        self._service = service
        self._nem = nemesis
        self._address = address

    def handle_message(self, msg: RapidMessage) -> Promise:
        src = getattr(msg, "sender", None)
        d = self._nem.decide(src, self._address, msg, INGRESS)
        metrics = self._nem.metrics
        kind = type(msg).__name__
        if d.drop:
            metrics.incr("nemesis_dropped", at="ingress", msg=kind)
            return Promise()  # never completes -> the sender times out
        for _ in range(d.duplicates):
            metrics.incr("nemesis_duplicated", at="ingress", msg=kind)
            self._service.handle_message(msg)
        if d.delay_ms > 0:
            metrics.incr(
                "nemesis_reordered" if d.reordered else "nemesis_delayed",
                at="ingress", msg=kind,
            )
            out: Promise = Promise()
            self._nem.scheduler.schedule(
                d.delay_ms,
                lambda: self._service.handle_message(msg).add_callback(
                    lambda p: _pipe(p, out)
                ),
            )
            return out
        metrics.incr("nemesis_passed", at="ingress", msg=kind)
        return self._service.handle_message(msg)

    def __getattr__(self, name):
        return getattr(self._service, name)


class NemesisServer(IMessagingServer):
    """Server-side decorator: passes lifecycle through and interposes the
    ingress fault filter in front of the MembershipService."""

    def __init__(self, inner: IMessagingServer, nemesis: Nemesis,
                 address: Endpoint) -> None:
        self.inner = inner
        self.address = address
        self._nem = nemesis

    def start(self) -> None:
        self.inner.start()

    def shutdown(self) -> None:
        self.inner.shutdown()

    def set_membership_service(self, service) -> None:
        self.inner.set_membership_service(
            _NemesisServiceFilter(service, self._nem, self.address)
        )


# --------------------------------------------------------------------------
# Device-plane compilation
# --------------------------------------------------------------------------


class UnsupportedDeviceFault(ValueError):
    """The rule has no device-plane analogue (see replay_on_simulator)."""


def _device_rules(plan: FaultPlan, round_ms: int) -> List[Tuple[int, Rule]]:
    """The device-compilable subset, validated.

    The device plane models the FD probe fabric: one-way ingress cuts
    (``one_way_ingress_partition``), lossy ingress (``ingress_loss``) and
    their schedules. Delays shorter than one round, duplicates and
    reorderings are absorbed by the round abstraction (a probe exchange is
    idempotent and completes within its round), so those compile to no-ops;
    anything the round model cannot absorb raises, loudly, instead of
    silently diverging from the protocol plane.
    """
    out: List[Tuple[int, Rule]] = []
    for idx, rule in enumerate(plan.rules):
        if isinstance(rule, (DuplicateRule, ReorderRule, WireVersionRule)):
            # idempotent / intra-round / byte-level: invisible to the round
            # model (the device plane never serializes wire frames)
            continue
        if isinstance(rule, (TornWriteRule, DiskStallRule)):
            # storage-level faults: the device plane models the probe
            # fabric, not stable storage -- torn tails and fsync stalls are
            # applied by the recovery harness / serving mirror instead
            continue
        if isinstance(rule, ClockSkewRule):
            if not 0.5 <= rule.rate <= 2.0:
                raise UnsupportedDeviceFault(
                    f"clock-skew rule {idx}: rate {rule.rate} outside "
                    "[0.5, 2.0] -- drift that extreme can flip round "
                    "outcomes, which the global-clock round model cannot "
                    "express"
                )
            continue  # bounded drift shifts timings, never round outcomes
        if isinstance(rule, DelayRule):
            if rule.base_ms + rule.jitter_ms >= round_ms:
                raise UnsupportedDeviceFault(
                    f"delay rule {idx} exceeds one device round ({round_ms} "
                    "ms); use Simulator.delay_broadcasts for round-scale "
                    "latency"
                )
            continue  # sub-round latency is absorbed by the round model
        if isinstance(rule, SlowNodeRule) and rule.response_delay_ms < round_ms:
            continue  # answers within the round: the probe still succeeds
        if rule.match.src is not None:
            raise UnsupportedDeviceFault(
                f"rule {idx}: per-source link faults have no device "
                "analogue (the probe mask is per destination)"
            )
        if rule.match.msg_types is not None and not any(
            issubclass(ProbeMessage, t) for t in rule.match.msg_types
        ):
            raise UnsupportedDeviceFault(
                f"rule {idx}: only probe-affecting faults compile to the "
                "device probe mask (dissemination loss is "
                "Simulator.drop_broadcasts)"
            )
        out.append((idx, rule))
    return out


def _boundaries(rules: List[Tuple[int, Rule]], horizon_ms: int,
                round_ms: int) -> List[int]:
    """Plan times (relative, within the horizon) where the active fault set
    can change: window edges plus flip-flop phase edges."""
    edges = {0, horizon_ms}
    for _, rule in rules:
        for start, end in rule.windows:
            if start < horizon_ms:
                edges.add(max(0, start))
            if end is not None and end < horizon_ms:
                edges.add(end)
        if isinstance(rule, FlipFlopRule):
            half = max(1, rule.period_ms // 2)
            t = rule.start_ms
            while t < horizon_ms:
                if t >= 0:
                    edges.add(t)
                t += half
    return sorted(edges)


def endpoint_slots(sim) -> Dict[Endpoint, int]:
    """Endpoint -> slot for every seated identity of a Simulator."""
    cluster = sim.cluster
    return {
        Endpoint(
            bytes(cluster.hostnames[i, : cluster.host_lengths[i]]),
            int(cluster.ports[i]),
        ): i
        for i in range(sim.config.capacity)
    }


def _slot_cell(sim, plan: FaultPlan, slot: int, cells: int) -> int:
    """Hierarchy cell of a device slot: topology zone when the plan carries
    one (slots ARE topology indices), rendezvous over the slot's seated
    endpoint otherwise -- the same precedence hierarchy/cells.py applies."""
    if plan.topology is not None:
        return plan.topology.zone_of(slot)
    host, port = sim.endpoint_of(slot)
    return _hier_cell_of(Endpoint(hostname=host, port=port), cells)


def apply_plan_at(sim, plan: FaultPlan, t_ms: int,
                  slots: Optional[Dict[Endpoint, int]] = None) -> None:
    """Set the simulator's fault arrays to the plan's state at plan-time
    ``t_ms``: partitions/flip-flops -> probe-drop targets, probabilistic
    drops -> per-destination ingress loss."""
    import numpy as np

    slots = slots if slots is not None else endpoint_slots(sim)
    round_ms = sim.config.fd_interval_ms // sim.config.rounds_per_interval
    sim.clear_link_faults()
    if plan.topology is not None:
        _apply_topology_delays(sim, plan.topology)
    cut: List[int] = []
    for idx, rule in _device_rules(plan, round_ms):
        if not rule.active_at(t_ms):
            continue
        if isinstance(rule, CellPartitionRule):
            # cell -> slot expansion: to the probe fabric outside the
            # boundary, every member of the isolated cell is probe-dead
            # (one-way ingress cut) -- the cell's internal traffic is not
            # modeled per-link on device, so the compilation captures the
            # externally visible outcome (the cell ages out of the
            # composed view)
            cut.extend(
                s for s in range(sim.config.capacity)
                if sim.active[s]
                and _slot_cell(sim, plan, s, rule.cells) == rule.cell
            )
            continue
        if rule.match.dst is not None:
            targets = [slots[rule.match.dst]]
        else:
            targets = [s for s in range(sim.config.capacity) if sim.active[s]]
        if isinstance(rule, (PartitionRule, FlipFlopRule, SlowNodeRule,
                             RestartNodeRule)):
            # a node answering slower than the probe deadline is, to every
            # observer, a node whose probes all fail: partition-equivalent
            # (a restart victim's down window reads the same way)
            cut.extend(targets)
        elif isinstance(rule, DropRule):  # incl. LossyLinkRule
            sim.ingress_loss(np.asarray(targets), rule.probability)
    if cut:
        sim.one_way_ingress_partition(np.asarray(sorted(set(cut))))


def apply_topology(sim, topology) -> None:
    """Compile a :class:`~.sim.topology.LatencyTopology` onto a Simulator:
    zones become delivery groups, and inter-zone one-way latency >= one
    round becomes ``delay_broadcasts`` rounds (sub-round latency is absorbed
    by the round model, the same rule DelayRule compilation follows).
    Requires ``sim.config.groups >= zones`` and ``max_delivery_delay`` large
    enough for the widest tier."""
    import numpy as np

    round_ms = sim.config.fd_interval_ms // sim.config.rounds_per_interval
    groups = topology.group_assignment(sim.config.capacity)
    n_zones = int(groups.max()) + 1
    if sim.config.groups < n_zones:
        raise UnsupportedDeviceFault(
            f"topology has {n_zones} zones but sim.config.groups="
            f"{sim.config.groups}"
        )
    sim.set_delivery_groups(groups)
    _apply_topology_delays(sim, topology)


def _apply_topology_delays(sim, topology) -> None:
    """Re-arm the inter-zone broadcast delays (clear_link_faults wipes the
    delay arrays, so apply_plan_at re-applies these each schedule segment)."""
    import numpy as np

    round_ms = sim.config.fd_interval_ms // sim.config.rounds_per_interval
    groups = topology.group_assignment(sim.config.capacity)
    n_zones = int(groups.max()) + 1
    slots = np.arange(sim.config.capacity)
    for receiver in range(n_zones):
        for sender in range(n_zones):
            if receiver == sender:
                continue
            rounds = topology.delay_rounds(sender, receiver, round_ms)
            if rounds > 0:
                sim.delay_broadcasts(receiver, slots[groups == sender], rounds)


def replay_on_simulator(sim, plan: FaultPlan, duration_ms: int,
                        decision_batch: int = 8) -> list:
    """Replay ``plan`` on the device plane for ``duration_ms`` of protocol
    time (plan-time zero = the simulator's current ``virtual_ms``), driving
    the fault arrays through every schedule boundary. Returns the
    ViewChangeRecords decided within the horizon."""
    slots = endpoint_slots(sim)
    round_ms = sim.config.fd_interval_ms // sim.config.rounds_per_interval
    rules = _device_rules(plan, round_ms)
    if plan.topology is not None:
        apply_topology(sim, plan.topology)
    epoch = sim.virtual_ms
    prior_changes = len(sim.view_changes)
    times = _boundaries(rules, duration_ms, round_ms)
    for seg_start, seg_end in zip(times, times[1:]):
        apply_plan_at(sim, plan, seg_start, slots)
        target = epoch + seg_end
        while sim.virtual_ms < target:
            remaining = math.ceil((target - sim.virtual_ms) / round_ms)
            rec = sim.run_until_decision(
                max_rounds=remaining, batch=min(decision_batch, remaining)
            )
            if rec is None:
                break  # budget burned with no decision; next segment
    return sim.view_changes[prior_changes:]
