"""Transport-agnostic deterministic fault injection: the nemesis plane.

Rapid's claim (PAPER.md, atc-2018 section 7) is stability under *messy*
failures -- one-way link loss, flip-flopping links, partial packet drops --
yet each transport historically had its own incompatible fault seam: the
in-process fabric's filters, the sim plane's mask arrays, nothing at all for
sockets. This module unifies them:

- :class:`FaultPlan`: a seeded, declarative schedule of per-link faults --
  probabilistic drops, one-way partitions with open/heal windows, flip-flop
  schedules, delay distributions, duplication and reordering. The plan is
  pure data; it carries no clocks or counters, so one plan replays across
  runs and transports.
- :class:`Nemesis`: one *armed* instance of a plan for one run: it sources
  time from the :class:`~.runtime.scheduler.Scheduler` seam (virtual-time
  runs stay discrete-event deterministic), derives every probabilistic
  decision from ``(plan seed, rule, link, per-link sequence number)`` via a
  keyed hash -- never from shared RNG state -- and counts injected faults
  into :mod:`~.observability` (``nemesis_*``).
- :class:`NemesisClient` / :class:`NemesisServer`: decorators over the
  ``IMessagingClient`` / ``IMessagingServer`` seams (messaging/base.py), so
  the same plan wraps the in-process, TCP and gRPC transports unchanged.
  The client additionally hardens ``send_message``: retries with the
  settings backoff policy and the per-message-type overall deadline
  (``Settings.deadline_for``), enforced uniformly at this layer whatever the
  wrapped transport does.
- :func:`replay_on_simulator`: compiles the device-plane-expressible subset
  of the same plan onto a :class:`~.sim.driver.Simulator`'s fault-schedule
  arrays segment by segment, so one seeded plan replays on both planes and
  parity tests can assert identical cuts and configuration ids.

Egress rules (``at="egress"``, the default) are applied by the client
decorator at the sender; ingress rules by the server decorator at the
receiver. A rule is applied exactly once either way, so wrapping both halves
of every node (the normal setup) never double-applies a fault.
"""

from __future__ import annotations

import hashlib
import math
import random
import struct
import threading
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .messaging.base import IMessagingClient, IMessagingServer
from .messaging.retries import call_with_retries
from .observability import Metrics, global_metrics
from .runtime.futures import Promise
from .runtime.scheduler import Scheduler
from .settings import Settings
from .types import Endpoint, ProbeMessage, RapidMessage

EGRESS = "egress"
INGRESS = "ingress"

# (start_ms, end_ms) relative to the nemesis arm epoch; end None = forever
Window = Tuple[int, Optional[int]]
_ALWAYS: Tuple[Window, ...] = ((0, None),)


def _u01(seed: int, *parts) -> float:
    """Deterministic uniform in [0, 1) keyed on ``(seed, parts)``.

    blake2b, not ``hash()``: decisions must not depend on per-process hash
    salting, and must not depend on draw interleaving across links -- each
    (rule, link, sequence-number) tuple owns its value outright.
    """
    h = hashlib.blake2b(digest_size=8)
    h.update(struct.pack("<q", seed))
    for part in parts:
        h.update(str(part).encode())
        h.update(b"\x1f")
    return int.from_bytes(h.digest(), "little") / 2.0**64


@dataclass(frozen=True)
class LinkMatch:
    """Which (src, dst, message type) triples a rule applies to; None = any."""

    src: Optional[Endpoint] = None
    dst: Optional[Endpoint] = None
    msg_types: Optional[Tuple[type, ...]] = None

    def matches(self, src: Optional[Endpoint], dst: Optional[Endpoint],
                msg: RapidMessage) -> bool:
        if self.src is not None and src != self.src:
            return False
        if self.dst is not None and dst != self.dst:
            return False
        if self.msg_types is not None and not isinstance(msg, self.msg_types):
            return False
        return True


@dataclass(frozen=True)
class Rule:
    """Base: a link selector, an application side, and open/heal windows."""

    match: LinkMatch = LinkMatch()
    at: str = EGRESS
    windows: Tuple[Window, ...] = _ALWAYS

    def active_at(self, t_ms: int) -> bool:
        return any(
            start <= t_ms and (end is None or t_ms < end)
            for start, end in self.windows
        )


@dataclass(frozen=True)
class DropRule(Rule):
    """Drop each matching message independently with ``probability``."""

    probability: float = 1.0


@dataclass(frozen=True)
class PartitionRule(Rule):
    """Deterministic one-way cut while a window is open (iptables INPUT)."""


@dataclass(frozen=True)
class FlipFlopRule(Rule):
    """The paper's flip-flop failure: the link alternates cut/healed every
    half ``period_ms``, starting cut at ``start_ms`` (within the windows)."""

    period_ms: int = 2000
    start_ms: int = 0

    def active_at(self, t_ms: int) -> bool:
        if t_ms < self.start_ms or not super().active_at(t_ms):
            return False
        half = max(1, self.period_ms // 2)
        return ((t_ms - self.start_ms) // half) % 2 == 0


@dataclass(frozen=True)
class DelayRule(Rule):
    """Extra one-way latency: ``base_ms`` plus uniform [0, jitter_ms]."""

    base_ms: int = 0
    jitter_ms: int = 0


@dataclass(frozen=True)
class DuplicateRule(Rule):
    """Deliver a second copy of each matching message with ``probability``."""

    probability: float = 0.0


@dataclass(frozen=True)
class ReorderRule(Rule):
    """Hold back each matching message with ``probability`` by a uniform
    [1, max_extra_ms] extra delay, letting later traffic overtake it."""

    probability: float = 0.0
    max_extra_ms: int = 100


class FaultPlan:
    """A seeded, declarative fault schedule (pure data, reusable across runs).

    Builder methods append immutable rules and return ``self``::

        plan = (FaultPlan(seed=7)
                .partition_one_way(dst=victim)                  # from t=0 on
                .flip_flop(period_ms=4000, dst=other)
                .drop(0.2, msg_types=(ProbeMessage,))
                .delay(base_ms=10, jitter_ms=5, src=a, dst=b))
    """

    def __init__(self, seed: int = 0) -> None:
        self.seed = int(seed)
        self.rules: List[Rule] = []

    def _add(self, rule: Rule) -> "FaultPlan":
        assert rule.at in (EGRESS, INGRESS), rule.at
        self.rules.append(rule)
        return self

    @staticmethod
    def _match(src, dst, msg_types) -> LinkMatch:
        return LinkMatch(
            src=src, dst=dst,
            msg_types=tuple(msg_types) if msg_types is not None else None,
        )

    def drop(self, probability: float, src: Optional[Endpoint] = None,
             dst: Optional[Endpoint] = None, msg_types=None,
             windows: Tuple[Window, ...] = _ALWAYS,
             at: str = EGRESS) -> "FaultPlan":
        assert 0.0 <= probability <= 1.0, probability
        return self._add(DropRule(
            match=self._match(src, dst, msg_types), at=at, windows=windows,
            probability=probability,
        ))

    def partition_one_way(self, src: Optional[Endpoint] = None,
                          dst: Optional[Endpoint] = None,
                          windows: Tuple[Window, ...] = _ALWAYS,
                          at: str = EGRESS) -> "FaultPlan":
        return self._add(PartitionRule(
            match=self._match(src, dst, None), at=at, windows=windows,
        ))

    def flip_flop(self, period_ms: int, src: Optional[Endpoint] = None,
                  dst: Optional[Endpoint] = None, start_ms: int = 0,
                  windows: Tuple[Window, ...] = _ALWAYS,
                  at: str = EGRESS) -> "FaultPlan":
        assert period_ms >= 2, period_ms
        return self._add(FlipFlopRule(
            match=self._match(src, dst, None), at=at, windows=windows,
            period_ms=period_ms, start_ms=start_ms,
        ))

    def delay(self, base_ms: int, jitter_ms: int = 0,
              src: Optional[Endpoint] = None, dst: Optional[Endpoint] = None,
              msg_types=None, windows: Tuple[Window, ...] = _ALWAYS,
              at: str = EGRESS) -> "FaultPlan":
        assert base_ms >= 0 and jitter_ms >= 0
        return self._add(DelayRule(
            match=self._match(src, dst, msg_types), at=at, windows=windows,
            base_ms=base_ms, jitter_ms=jitter_ms,
        ))

    def duplicate(self, probability: float, src: Optional[Endpoint] = None,
                  dst: Optional[Endpoint] = None, msg_types=None,
                  windows: Tuple[Window, ...] = _ALWAYS,
                  at: str = EGRESS) -> "FaultPlan":
        assert 0.0 <= probability <= 1.0, probability
        return self._add(DuplicateRule(
            match=self._match(src, dst, msg_types), at=at, windows=windows,
            probability=probability,
        ))

    def reorder(self, probability: float, max_extra_ms: int = 100,
                src: Optional[Endpoint] = None,
                dst: Optional[Endpoint] = None, msg_types=None,
                windows: Tuple[Window, ...] = _ALWAYS,
                at: str = EGRESS) -> "FaultPlan":
        assert 0.0 <= probability <= 1.0, probability
        assert max_extra_ms >= 1
        return self._add(ReorderRule(
            match=self._match(src, dst, msg_types), at=at, windows=windows,
            probability=probability, max_extra_ms=max_extra_ms,
        ))


@dataclass
class Decision:
    """What the plane does to one message."""

    drop: bool = False
    delay_ms: int = 0
    duplicates: int = 0
    reordered: bool = False


class Nemesis:
    """One armed instance of a plan for one run: epoch, decision streams,
    counters. Create one per cluster run; mint decorators from it."""

    def __init__(self, plan: FaultPlan, scheduler: Scheduler,
                 metrics: Optional[Metrics] = None) -> None:
        self.plan = plan
        self.scheduler = scheduler
        self.metrics = metrics if metrics is not None else global_metrics()
        self._epoch: Optional[int] = None
        # (rule index, src str, dst str) -> decisions drawn so far
        self._seq: Dict[Tuple[int, str, str], int] = {}
        self._lock = threading.Lock()

    # -- clock ---------------------------------------------------------------

    def arm(self, epoch_ms: Optional[int] = None) -> "Nemesis":
        """Pin plan-time zero (default: now). Windows are relative to this;
        re-arming after bootstrap starts the schedule from a healthy view."""
        self._epoch = (
            epoch_ms if epoch_ms is not None else self.scheduler.now_ms()
        )
        return self

    def plan_now_ms(self) -> int:
        if self._epoch is None:
            self.arm()
        return self.scheduler.now_ms() - self._epoch

    # -- decorators ----------------------------------------------------------

    def client(self, inner: IMessagingClient, address: Optional[Endpoint] = None,
               settings: Optional[Settings] = None) -> "NemesisClient":
        return NemesisClient(inner, self, address=address, settings=settings)

    def server(self, inner: IMessagingServer,
               address: Endpoint) -> "NemesisServer":
        return NemesisServer(inner, self, address)

    # -- decisions -----------------------------------------------------------

    def _draw(self, rule_idx: int, src: str, dst: str) -> float:
        key = (rule_idx, src, dst)
        with self._lock:
            n = self._seq.get(key, 0)
            self._seq[key] = n + 1
        return _u01(self.plan.seed, rule_idx, src, dst, n)

    def retry_rng(self, address: Optional[Endpoint]) -> random.Random:
        """Per-sender seeded rng for backoff jitter draws."""
        tag = str(address).encode() if address is not None else b"?"
        return random.Random(self.plan.seed ^ zlib.crc32(tag))

    def decide(self, src: Optional[Endpoint], dst: Optional[Endpoint],
               msg: RapidMessage, at: str) -> Decision:
        t = self.plan_now_ms()
        out = Decision()
        src_s, dst_s = str(src), str(dst)
        for idx, rule in enumerate(self.plan.rules):
            if rule.at != at or not rule.match.matches(src, dst, msg):
                continue
            if not rule.active_at(t):
                continue
            if isinstance(rule, (PartitionRule, FlipFlopRule)):
                out.drop = True
            elif isinstance(rule, DropRule):
                if self._draw(idx, src_s, dst_s) < rule.probability:
                    out.drop = True
            elif isinstance(rule, DelayRule):
                jitter = (
                    int(self._draw(idx, src_s, dst_s) * (rule.jitter_ms + 1))
                    if rule.jitter_ms > 0 else 0
                )
                out.delay_ms += rule.base_ms + jitter
            elif isinstance(rule, DuplicateRule):
                if self._draw(idx, src_s, dst_s) < rule.probability:
                    out.duplicates += 1
            elif isinstance(rule, ReorderRule):
                if self._draw(idx, src_s, dst_s) < rule.probability:
                    held = 1 + int(
                        self._draw(idx, src_s, dst_s) * rule.max_extra_ms
                    )
                    out.delay_ms += min(held, rule.max_extra_ms)
                    out.reordered = True
        return out


def _pipe(src: Promise, dst: Promise) -> None:
    if dst.done():
        return
    exc = src.exception()
    if exc is not None:
        dst.try_set_exception(exc)
    else:
        dst.try_set_result(src._result)  # noqa: SLF001 -- promise-internal copy


class NemesisClient(IMessagingClient):
    """Egress fault application + uniformly hardened send_message.

    ``send_message`` re-homes the retry loop at this layer: every attempt
    traverses the fault plane once, attempts are spaced by the settings
    backoff policy, and the whole exchange is bounded by the per-message-type
    deadline (``Settings.deadline_for``) on the scheduler's clock --
    identical semantics over every wrapped transport.
    """

    def __init__(self, inner: IMessagingClient, nemesis: Nemesis,
                 address: Optional[Endpoint] = None,
                 settings: Optional[Settings] = None) -> None:
        self.inner = inner
        self.address = (
            address if address is not None else getattr(inner, "address", None)
        )
        self._nem = nemesis
        inherited = getattr(inner, "_settings", None)
        self._settings = (
            settings if settings is not None
            else inherited if inherited is not None else Settings()
        )

    def send_message(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        return call_with_retries(
            lambda: self._attempt(remote, msg),
            self._settings.message_retries,
            scheduler=self._nem.scheduler,
            policy=self._settings.retry_policy(),
            deadline_ms=self._settings.deadline_for(msg),
            rng=self._nem.retry_rng(self.address),
            metrics=self._nem.metrics,
        )

    def send_message_best_effort(self, remote: Endpoint,
                                 msg: RapidMessage) -> Promise:
        return self._attempt(remote, msg)

    def _attempt(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        d = self._nem.decide(self.address, remote, msg, EGRESS)
        metrics = self._nem.metrics
        # labeled by fault application point and message type; unlabeled
        # reads (metrics.get("nemesis_dropped")) sum across the label sets
        kind = type(msg).__name__
        if d.drop:
            metrics.incr("nemesis_dropped", at="egress", msg=kind)
            # dropped on the wire: the sender only ever sees its per-message
            # deadline expire, exactly like the in-process fabric's filters
            out: Promise = Promise()
            timeout = self._settings.timeout_for(msg)
            self._nem.scheduler.schedule(
                timeout,
                lambda: out.try_set_exception(TimeoutError(
                    f"nemesis dropped {type(msg).__name__} to {remote}"
                )),
            )
            return out
        for _ in range(d.duplicates):
            metrics.incr("nemesis_duplicated", at="egress", msg=kind)
            self.inner.send_message_best_effort(remote, msg)
        if d.delay_ms > 0:
            metrics.incr(
                "nemesis_reordered" if d.reordered else "nemesis_delayed",
                at="egress", msg=kind,
            )
            out = Promise()
            self._nem.scheduler.schedule(
                d.delay_ms,
                lambda: self.inner.send_message_best_effort(
                    remote, msg
                ).add_callback(lambda p: _pipe(p, out)),
            )
            return out
        metrics.incr("nemesis_passed", at="egress", msg=kind)
        return self.inner.send_message_best_effort(remote, msg)

    def shutdown(self) -> None:
        self.inner.shutdown()


class _NemesisServiceFilter:
    """Ingress fault application, inserted between the real server and its
    MembershipService: ``handle_message`` is the one dispatch seam every
    transport shares, so wrapping the service faults them all identically."""

    def __init__(self, service, nemesis: Nemesis, address: Endpoint) -> None:
        self._service = service
        self._nem = nemesis
        self._address = address

    def handle_message(self, msg: RapidMessage) -> Promise:
        src = getattr(msg, "sender", None)
        d = self._nem.decide(src, self._address, msg, INGRESS)
        metrics = self._nem.metrics
        kind = type(msg).__name__
        if d.drop:
            metrics.incr("nemesis_dropped", at="ingress", msg=kind)
            return Promise()  # never completes -> the sender times out
        for _ in range(d.duplicates):
            metrics.incr("nemesis_duplicated", at="ingress", msg=kind)
            self._service.handle_message(msg)
        if d.delay_ms > 0:
            metrics.incr(
                "nemesis_reordered" if d.reordered else "nemesis_delayed",
                at="ingress", msg=kind,
            )
            out: Promise = Promise()
            self._nem.scheduler.schedule(
                d.delay_ms,
                lambda: self._service.handle_message(msg).add_callback(
                    lambda p: _pipe(p, out)
                ),
            )
            return out
        metrics.incr("nemesis_passed", at="ingress", msg=kind)
        return self._service.handle_message(msg)

    def __getattr__(self, name):
        return getattr(self._service, name)


class NemesisServer(IMessagingServer):
    """Server-side decorator: passes lifecycle through and interposes the
    ingress fault filter in front of the MembershipService."""

    def __init__(self, inner: IMessagingServer, nemesis: Nemesis,
                 address: Endpoint) -> None:
        self.inner = inner
        self.address = address
        self._nem = nemesis

    def start(self) -> None:
        self.inner.start()

    def shutdown(self) -> None:
        self.inner.shutdown()

    def set_membership_service(self, service) -> None:
        self.inner.set_membership_service(
            _NemesisServiceFilter(service, self._nem, self.address)
        )


# --------------------------------------------------------------------------
# Device-plane compilation
# --------------------------------------------------------------------------


class UnsupportedDeviceFault(ValueError):
    """The rule has no device-plane analogue (see replay_on_simulator)."""


def _device_rules(plan: FaultPlan, round_ms: int) -> List[Tuple[int, Rule]]:
    """The device-compilable subset, validated.

    The device plane models the FD probe fabric: one-way ingress cuts
    (``one_way_ingress_partition``), lossy ingress (``ingress_loss``) and
    their schedules. Delays shorter than one round, duplicates and
    reorderings are absorbed by the round abstraction (a probe exchange is
    idempotent and completes within its round), so those compile to no-ops;
    anything the round model cannot absorb raises, loudly, instead of
    silently diverging from the protocol plane.
    """
    out: List[Tuple[int, Rule]] = []
    for idx, rule in enumerate(plan.rules):
        if isinstance(rule, (DuplicateRule, ReorderRule)):
            continue  # idempotent / intra-round: invisible to the round model
        if isinstance(rule, DelayRule):
            if rule.base_ms + rule.jitter_ms >= round_ms:
                raise UnsupportedDeviceFault(
                    f"delay rule {idx} exceeds one device round ({round_ms} "
                    "ms); use Simulator.delay_broadcasts for round-scale "
                    "latency"
                )
            continue  # sub-round latency is absorbed by the round model
        if rule.match.src is not None:
            raise UnsupportedDeviceFault(
                f"rule {idx}: per-source link faults have no device "
                "analogue (the probe mask is per destination)"
            )
        if rule.match.msg_types is not None and not any(
            issubclass(ProbeMessage, t) for t in rule.match.msg_types
        ):
            raise UnsupportedDeviceFault(
                f"rule {idx}: only probe-affecting faults compile to the "
                "device probe mask (dissemination loss is "
                "Simulator.drop_broadcasts)"
            )
        out.append((idx, rule))
    return out


def _boundaries(rules: List[Tuple[int, Rule]], horizon_ms: int,
                round_ms: int) -> List[int]:
    """Plan times (relative, within the horizon) where the active fault set
    can change: window edges plus flip-flop phase edges."""
    edges = {0, horizon_ms}
    for _, rule in rules:
        for start, end in rule.windows:
            if start < horizon_ms:
                edges.add(max(0, start))
            if end is not None and end < horizon_ms:
                edges.add(end)
        if isinstance(rule, FlipFlopRule):
            half = max(1, rule.period_ms // 2)
            t = rule.start_ms
            while t < horizon_ms:
                if t >= 0:
                    edges.add(t)
                t += half
    return sorted(edges)


def endpoint_slots(sim) -> Dict[Endpoint, int]:
    """Endpoint -> slot for every seated identity of a Simulator."""
    cluster = sim.cluster
    return {
        Endpoint(
            bytes(cluster.hostnames[i, : cluster.host_lengths[i]]),
            int(cluster.ports[i]),
        ): i
        for i in range(sim.config.capacity)
    }


def apply_plan_at(sim, plan: FaultPlan, t_ms: int,
                  slots: Optional[Dict[Endpoint, int]] = None) -> None:
    """Set the simulator's fault arrays to the plan's state at plan-time
    ``t_ms``: partitions/flip-flops -> probe-drop targets, probabilistic
    drops -> per-destination ingress loss."""
    import numpy as np

    slots = slots if slots is not None else endpoint_slots(sim)
    round_ms = sim.config.fd_interval_ms // sim.config.rounds_per_interval
    sim.clear_link_faults()
    cut: List[int] = []
    for idx, rule in _device_rules(plan, round_ms):
        if not rule.active_at(t_ms):
            continue
        if rule.match.dst is not None:
            targets = [slots[rule.match.dst]]
        else:
            targets = [s for s in range(sim.config.capacity) if sim.active[s]]
        if isinstance(rule, (PartitionRule, FlipFlopRule)):
            cut.extend(targets)
        elif isinstance(rule, DropRule):
            sim.ingress_loss(np.asarray(targets), rule.probability)
    if cut:
        sim.one_way_ingress_partition(np.asarray(sorted(set(cut))))


def replay_on_simulator(sim, plan: FaultPlan, duration_ms: int,
                        decision_batch: int = 8) -> list:
    """Replay ``plan`` on the device plane for ``duration_ms`` of protocol
    time (plan-time zero = the simulator's current ``virtual_ms``), driving
    the fault arrays through every schedule boundary. Returns the
    ViewChangeRecords decided within the horizon."""
    slots = endpoint_slots(sim)
    round_ms = sim.config.fd_interval_ms // sim.config.rounds_per_interval
    rules = _device_rules(plan, round_ms)
    epoch = sim.virtual_ms
    prior_changes = len(sim.view_changes)
    times = _boundaries(rules, duration_ms, round_ms)
    for seg_start, seg_end in zip(times, times[1:]):
        apply_plan_at(sim, plan, seg_start, slots)
        target = epoch + seg_end
        while sim.virtual_ms < target:
            remaining = math.ceil((target - sim.virtual_ms) / round_ms)
            rec = sim.run_until_decision(
                max_rounds=remaining, batch=min(decision_batch, remaining)
            )
            if rec is None:
                break  # budget burned with no decision; next segment
    return sim.view_changes[prior_changes:]
