"""Multi-node cut detection with H/L watermarks.

Reference: MultiNodeCutDetector.java. A view-change proposal about a node is
emitted only once H of its K observer reports have arrived AND no other node
sits in the unstable (L, H) report band -- this filter is what yields
almost-everywhere agreement on the cut before consensus runs.

Semantics preserved exactly:
- one report per (destination, ring) counts; duplicates ignored
  (MultiNodeCutDetector.java:97-101)
- L-th report moves the destination into the pre-proposal set and bumps
  ``updates_in_progress`` (:104-107)
- H-th report moves it into the proposal set; the proposal is emitted only when
  ``updates_in_progress`` drains to zero (:109-124)
- implicit detection: edges between failing nodes are invalidated so a report
  from an observer that is itself failing does not wedge the cut (:137-164)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, TYPE_CHECKING

from .types import AlertMessage, EdgeStatus, Endpoint

if TYPE_CHECKING:  # pragma: no cover
    from .membership import MembershipView
    from .observability import Metrics, Tracer

K_MIN = 3


class MultiNodeCutDetector:
    def __init__(self, k: int, h: int, l: int) -> None:
        if h > k or l > h or k < K_MIN or l <= 0 or h <= 0:
            raise ValueError(
                f"arguments do not satisfy K >= H >= L > 0, K >= {K_MIN}: K={k} H={h} L={l}"
            )
        self.k = k
        self.h = h
        self.l = l
        # telemetry plane (optional): bound by the owning MembershipService
        self._metrics: Optional["Metrics"] = None
        self._tracer: Optional["Tracer"] = None
        self._proposal_count = 0
        self._updates_in_progress = 0
        self._reports_per_host: Dict[Endpoint, Dict[int, Endpoint]] = {}
        self._proposal: Set[Endpoint] = set()
        self._pre_proposal: Set[Endpoint] = set()
        self._seen_link_down_events = False

    def bind_telemetry(self, metrics: "Metrics", tracer: "Tracer") -> None:
        self._metrics = metrics
        self._tracer = tracer

    @property
    def num_proposals(self) -> int:
        return self._proposal_count

    def occupancy(self) -> Dict[str, int]:
        """Watermark occupancy for the introspection RPC: how many subjects
        have reports at all, how many crossed L (unstable band), how many
        crossed H (stable, awaiting the band to drain), and the in-progress
        count that gates proposal emission."""
        return {
            "reports_tracked": len(self._reports_per_host),
            "pre_proposal_size": len(self._pre_proposal),
            "proposal_size": len(self._proposal),
            "updates_in_progress": self._updates_in_progress,
        }

    def aggregate_for_proposal(self, msg: AlertMessage) -> List[Endpoint]:
        """Apply one alert (all its ring numbers); returns emitted proposal or []."""
        proposals: List[Endpoint] = []
        for ring_number in msg.ring_numbers:
            proposals.extend(
                self._aggregate(msg.edge_src, msg.edge_dst, msg.edge_status, ring_number)
            )
        return proposals

    def _aggregate(
        self, link_src: Endpoint, link_dst: Endpoint, status: EdgeStatus, ring_number: int
    ) -> List[Endpoint]:
        assert ring_number <= self.k
        if status == EdgeStatus.DOWN:
            self._seen_link_down_events = True

        reports_for_host = self._reports_per_host.setdefault(link_dst, {})
        if ring_number in reports_for_host:
            return []  # duplicate announcement for this (dst, ring)
        reports_for_host[ring_number] = link_src
        num_reports = len(reports_for_host)

        if num_reports == self.l:
            self._updates_in_progress += 1
            self._pre_proposal.add(link_dst)

        if num_reports == self.h:
            self._pre_proposal.discard(link_dst)
            self._proposal.add(link_dst)
            self._updates_in_progress -= 1
            if self._updates_in_progress == 0:
                self._proposal_count += 1
                ret = list(self._proposal)
                self._proposal.clear()
                if self._metrics is not None:
                    self._metrics.incr("cut.proposals_emitted")
                if self._tracer is not None:
                    self._tracer.event("cut_detected", size=len(ret))
                return ret
        return []

    def invalidate_failing_edges(self, view: "MembershipView") -> List[Endpoint]:
        """Implicit detection of edges between failing nodes
        (MultiNodeCutDetector.java:137-164)."""
        if not self._seen_link_down_events:
            return []
        proposals_to_return: List[Endpoint] = []
        for node_in_flux in list(self._pre_proposal):
            observers = (
                view.get_observers_of(node_in_flux)
                if view.is_host_present(node_in_flux)
                else view.get_expected_observers_of(node_in_flux)
            )
            for ring_number, observer in enumerate(observers):
                if observer in self._proposal or observer in self._pre_proposal:
                    status = (
                        EdgeStatus.DOWN
                        if view.is_host_present(node_in_flux)
                        else EdgeStatus.UP
                    )
                    proposals_to_return.extend(
                        self._aggregate(observer, node_in_flux, status, ring_number)
                    )
        return proposals_to_return

    def clear(self) -> None:
        """Reset after a view change (MultiNodeCutDetector.java:169-178)."""
        self._reports_per_host.clear()
        self._proposal.clear()
        self._updates_in_progress = 0
        self._proposal_count = 0
        self._pre_proposal.clear()
        self._seen_link_down_events = False
