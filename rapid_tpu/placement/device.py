"""Vectorized placement: the device-plane mirror of placement/engine.py.

Same arithmetic as the object model, expressed three ways over a ``[P, C]``
score matrix (P partitions x C candidate slots):

- ``_score_matrix`` / ``topr_full``: chunked numpy over uint32 lanes -- the
  host-side bulk path used for the one-time full build when placement is
  enabled on a Simulator (100k x 8k is a few tens of seconds of one-time
  work on a laptop core, amortized across the run).
- ``DevicePlacement.apply_view_change``: the incremental path driven from the
  sim plane's view changes. Removals only recompute the rows whose replica
  set intersects the removed slots; additions only merge the new columns into
  the stored top-R -- together exactly the minimal-motion set, so a churn
  step over 100k nodes touches thousandths of the matrix instead of all of
  it and stays well inside the bench wall-time budget.
- ``build_jit``: the whole map as ONE jitted dispatch, row-sharded over a
  device mesh with the same NamedSharding discipline as shard/engine.py
  (partitions are embarrassingly parallel, so the mesh splits the P axis).

Parity: assignments and the xxh64 map fingerprint are bit-identical with
engine.build_map for the same (view, weights, seed) whenever the active set
has at least R members -- pinned in tests/test_placement.py (including on an
8-device mesh) and in the golden vectors.

Ranking is by ``(score desc, slot index asc)``, encoded branch-free as a
uint64 composite ``(score << 32) | (0xFFFFFFFF - slot)`` so numpy
argpartition needs no tie-break pass; the jitted path gets the same order
from argmax's first-maximum rule.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from ..hashing import endpoint_hash_batch, xxh64_batch_auto
from .engine import GOLDEN64, MIX1, MIX2, PlacementConfig

_U32 = np.uint32
_U64 = np.uint64
_REV = _U64(0xFFFFFFFF)

__all__ = [
    "DevicePlacement",
    "DeviceDiff",
    "build_jit",
    "instance_keys32",
    "node_keys64",
    "partition_keys32",
    "topr_full",
]


def _fold32(h: np.ndarray) -> np.ndarray:
    """uint64[N] -> uint32[N]; mirrors engine.fold32."""
    return ((h ^ (h >> _U64(32))) & _REV).astype(_U32)


def partition_keys32(partitions: int, seed: int) -> np.ndarray:
    """engine.partition_key32 for all P at once: batched xxh64 over the
    8-LE-byte rows of the partition indices."""
    idx = np.arange(partitions, dtype=np.int64)
    data = (
        (idx[:, None] >> (8 * np.arange(8, dtype=np.int64))[None, :]) & 0xFF
    ).astype(np.uint8)
    lengths = np.full(partitions, 8, dtype=np.int64)
    return _fold32(xxh64_batch_auto(data, lengths, seed))


def node_keys64(
    hostnames: np.ndarray, host_lengths: np.ndarray, ports: np.ndarray, seed: int
) -> np.ndarray:
    """engine.node_key64 for all C slots at once; uint64[C]."""
    return endpoint_hash_batch(hostnames, host_lengths, ports, seed)


def instance_keys32(keys64: np.ndarray, max_weight: int) -> np.ndarray:
    """[V, C] uint32 virtual-instance keys; row v is every node's key
    advanced by v golden steps (engine.instance_key32)."""
    v = np.arange(max_weight, dtype=_U64)[:, None] * _U64(GOLDEN64)
    with np.errstate(over="ignore"):
        return _fold32(keys64[None, :].astype(_U64) + v)


def _mix32(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """engine.mix32 over uint32 lanes (broadcasting)."""
    with np.errstate(over="ignore"):
        h = (a ^ b) * _U32(MIX1)
        h = h ^ (h >> _U32(15))
        h = h * _U32(MIX2)
        h = h ^ (h >> _U32(13))
    return h


def _score_matrix(
    part32: np.ndarray, inst32: np.ndarray, weights: np.ndarray
) -> np.ndarray:
    """[B, M] uint32: each row-partition's score against each candidate
    column, max over that column's weight-many virtual instances. A node
    with weight >= 1 always applies instance 0, so the masked-to-zero
    unused instances can never win (scores are unsigned)."""
    acc = np.zeros((part32.shape[0], inst32.shape[1]), dtype=_U32)
    for v in range(inst32.shape[0]):
        s = _mix32(part32[:, None], inst32[v][None, :])
        live = weights > v
        if not live.all():
            s = np.where(live[None, :], s, _U32(0))
        np.maximum(acc, s, out=acc)
    return acc


def _composite(
    scores: np.ndarray, cols: np.ndarray, valid: np.ndarray
) -> np.ndarray:
    """(score << 32) | (0xFFFFFFFF - col) as uint64, 0 where invalid. Higher
    composite == better candidate; equal scores resolve to the lower slot,
    matching the engine's tie rule. Composite 0 is unreachable for any valid
    candidate (the index half is nonzero for col < 2**32 - 1)."""
    rev = _REV - cols.astype(_U64)
    if rev.ndim == 1:
        rev = rev[None, :]
    comp = (scores.astype(_U64) << _U64(32)) | rev
    return np.where(valid, comp, _U64(0))


def _select_topr(comp: np.ndarray, r: int) -> Tuple[np.ndarray, np.ndarray]:
    """Top-r composites per row, descending. Returns (assign [B,r] int32
    with -1 for empty slots, scores [B,r] uint32)."""
    n_rows, m = comp.shape
    k = min(r, m)
    if m > k:
        part = np.argpartition(comp, m - k, axis=1)[:, m - k:]
        vals = np.take_along_axis(comp, part, axis=1)
    else:
        vals = comp
    order = np.argsort(vals, axis=1)[:, ::-1]
    vals = np.take_along_axis(vals, order, axis=1)
    if k < r:
        vals = np.concatenate(
            [vals, np.zeros((n_rows, r - k), dtype=_U64)], axis=1
        )
    assign = (_REV - (vals & _REV)).astype(np.int64).astype(np.int32)
    assign = np.where(vals == _U64(0), np.int32(-1), assign)
    return assign, (vals >> _U64(32)).astype(_U32)


# rows-per-chunk sized so the [B, C] uint64 composite stays ~64 MB
_CHUNK_ELEMS = 8_000_000


def topr_full(
    part32: np.ndarray,
    inst32: np.ndarray,
    weights: np.ndarray,
    active: np.ndarray,
    replicas: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Full [P, R] build, chunked over partitions to bound peak memory."""
    n_parts = part32.shape[0]
    n_slots = inst32.shape[1]
    cols = np.arange(n_slots, dtype=np.int64)
    block = max(1, _CHUNK_ELEMS // max(n_slots, 1))
    assign = np.empty((n_parts, replicas), dtype=np.int32)
    scores = np.empty((n_parts, replicas), dtype=_U32)
    for start in range(0, n_parts, block):
        sub = part32[start : start + block]
        sc = _score_matrix(sub, inst32, weights)
        comp = _composite(sc, cols, active[None, :])
        assign[start : start + len(sub)], scores[start : start + len(sub)] = (
            _select_topr(comp, replicas)
        )
    return assign, scores


# jitted whole-map builders, keyed by the two Python values baked into the
# trace -- a fresh ``jax.jit`` per ``build_jit`` call would otherwise start
# an empty compile cache every call and re-trace the identical program
_BUILD_CACHE: dict = {}


def _builder(n_instances: int, replicas: int):
    """The jitted map builder for a (virtual-instance, replica) shape,
    compiled once per process and shared by every later ``build_jit``."""
    import jax.numpy as jnp

    from ..runtime.jitwatch import make_jit

    cached = _BUILD_CACHE.get((n_instances, replicas))
    if cached is not None:
        return cached

    def _build(p32, inst, w, act):
        acc = jnp.zeros((p32.shape[0], inst.shape[1]), dtype=jnp.uint32)
        for v in range(n_instances):
            h = (p32[:, None] ^ inst[v][None, :]) * jnp.uint32(MIX1)
            h = h ^ (h >> jnp.uint32(15))
            h = h * jnp.uint32(MIX2)
            h = h ^ (h >> jnp.uint32(13))
            h = jnp.where(w[None, :] > v, h, jnp.uint32(0))
            acc = jnp.maximum(acc, h)
        key = jnp.where(act[None, :], acc, jnp.uint32(0))
        col = jnp.arange(key.shape[1], dtype=jnp.int32)[None, :]
        picks, vals = [], []
        for _ in range(replicas):
            a = jnp.argmax(key, axis=1).astype(jnp.int32)
            v = jnp.max(key, axis=1)
            picks.append(jnp.where(v > 0, a, jnp.int32(-1)))
            vals.append(v)
            key = jnp.where(col == a[:, None], jnp.uint32(0), key)
        return jnp.stack(picks, axis=1), jnp.stack(vals, axis=1)

    jitted = _BUILD_CACHE[(n_instances, replicas)] = make_jit(  # devlint: jit-cached
        "placement.build_jit", _build
    )
    return jitted


def build_jit(
    part32: np.ndarray,
    inst32: np.ndarray,
    weights: np.ndarray,
    active: np.ndarray,
    replicas: int,
    mesh=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """The whole map as one jitted dispatch, optionally row-sharded.

    With a mesh, the P axis is split across devices exactly like the
    protocol state in shard/engine.py (NamedSharding over the mesh's axis
    names); every per-partition row is independent so no collectives are
    needed. P must divide by the device count. The theoretical parity gap
    vs the numpy path: an *active* candidate whose best score is exactly 0
    (p ~= 2**-32 per pair) is indistinguishable from a masked one here.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    _build = _builder(int(inst32.shape[0]), replicas)

    p32 = jnp.asarray(part32, dtype=jnp.uint32)
    inst = jnp.asarray(inst32, dtype=jnp.uint32)
    w = jnp.asarray(weights, dtype=jnp.int32)
    act = jnp.asarray(active, dtype=bool)
    if mesh is not None:
        rows = NamedSharding(mesh, P(mesh.axis_names))
        every = NamedSharding(mesh, P())
        p32 = jax.device_put(p32, rows)
        inst = jax.device_put(inst, every)
        w = jax.device_put(w, every)
        act = jax.device_put(act, every)
    assign, scores = _build(p32, inst, w, act)
    return np.asarray(assign, dtype=np.int32), np.asarray(scores, dtype=_U32)


@dataclass(frozen=True)
class DeviceDiff:
    """Array-plane PlacementDiff: moved partition indices and per-slot load
    delta, plus the old/new fingerprints for cross-plane agreement checks."""

    old_version: int
    new_version: int
    partitions_moved: np.ndarray  # int64[moved]
    load_delta: np.ndarray  # int64[C] (new slots held minus old, per slot)

    @property
    def moved(self) -> int:
        return int(self.partitions_moved.shape[0])


class DevicePlacement:
    """Stateful device-plane placement over a fixed slot universe.

    Construction fixes the candidate universe (every slot the simulator can
    ever host, alive or not) and precomputes all keys; ``build`` does the
    one-time full map for the starting active set; ``apply_view_change``
    tracks churn incrementally. Slot indices are the simulator's column
    indices, so candidate order -- and therefore tie-breaking -- is the
    same sorted-identity order on both planes when the caller's slots are
    sorted (VirtualCluster.synthesize and the parity tests sort)."""

    def __init__(
        self,
        config: PlacementConfig,
        hostnames: np.ndarray,
        host_lengths: np.ndarray,
        ports: np.ndarray,
        weights: Optional[np.ndarray] = None,
    ) -> None:
        self.config = config
        n_slots = int(ports.shape[0])
        self.replicas = min(config.replicas, n_slots)
        self.keys64 = node_keys64(hostnames, host_lengths, ports, config.seed)
        self.weights = (
            np.ones(n_slots, dtype=np.int32)
            if weights is None
            else weights.astype(np.int32)
        )
        self.inst32 = instance_keys32(self.keys64, int(self.weights.max()))
        self.part32 = partition_keys32(config.partitions, config.seed)
        self.active = np.zeros(n_slots, dtype=bool)
        self.assign: Optional[np.ndarray] = None  # [P, R] int32 slot ids
        self.scores: Optional[np.ndarray] = None  # [P, R] uint32
        self.version = 0

    # -- full build ------------------------------------------------------ #

    def build(self, active: np.ndarray) -> None:
        self.assign, self.scores = topr_full(
            self.part32, self.inst32, self.weights, active, self.replicas
        )
        self.active = active.copy()
        self.version = self._fingerprint()

    # -- incremental churn ---------------------------------------------- #

    def apply_view_change(self, new_active: np.ndarray) -> DeviceDiff:
        """Update the stored map for a new active set and return the diff.

        Rows are recomputed only when a removed slot sits in their replica
        set; added slots are merged against every surviving row's stored
        top-R. Both cases are exactly the rows rendezvous hashing says can
        change, so the moved set IS the minimal-motion set."""
        if self.assign is None:
            raise RuntimeError("build() must run before apply_view_change()")
        old_assign = self.assign
        removed = self.active & ~new_active
        added = new_active & ~self.active
        removed_slots = np.flatnonzero(removed)
        added_slots = np.flatnonzero(added)

        assign = old_assign.copy()
        scores = self.scores.copy()
        affected = (
            np.isin(old_assign, removed_slots).any(axis=1)
            if removed_slots.size
            else np.zeros(old_assign.shape[0], dtype=bool)
        )
        if affected.any():
            sub_assign, sub_scores = topr_full(
                self.part32[affected], self.inst32, self.weights,
                new_active, self.replicas,
            )
            assign[affected] = sub_assign
            scores[affected] = sub_scores
        if added_slots.size:
            untouched = ~affected
            sub_part = self.part32[untouched]
            new_sc = _score_matrix(
                sub_part, self.inst32[:, added_slots], self.weights[added_slots]
            )
            comp_new = _composite(new_sc, added_slots, True)
            comp_old = _composite(
                scores[untouched], assign[untouched], assign[untouched] >= 0
            )
            merged_a, merged_s = _select_topr(
                np.concatenate([comp_old, comp_new], axis=1), self.replicas
            )
            assign[untouched] = merged_a
            scores[untouched] = merged_s

        moved = np.flatnonzero((assign != old_assign).any(axis=1))
        old_counts = self._counts(old_assign)
        self.assign, self.scores = assign, scores
        self.active = new_active.copy()
        old_version = self.version
        self.version = self._fingerprint()
        return DeviceDiff(
            old_version=old_version,
            new_version=self.version,
            partitions_moved=moved,
            load_delta=self._counts(assign) - old_counts,
        )

    def apply_weight_change(self, new_weights: np.ndarray) -> DeviceDiff:
        """Re-derive the map after capacity weights change for existing
        members.

        Weights feed the per-node instance keys (one virtual instance per
        weight unit), so a weight change alters candidate scores globally --
        there is no removed/added slot set to scope an incremental update
        around. Deliberately a full rebuild over the current active set: the
        engine's ``update`` with changed weights does exactly the same full
        ``build_map``, and a cheaper path here would be a second scoring
        code path that could drift from it (the engine/device desync this
        method exists to prevent; parity pinned in tests)."""
        if self.assign is None:
            raise RuntimeError("build() must run before apply_weight_change()")
        new_weights = new_weights.astype(np.int32)
        if new_weights.shape != self.weights.shape:
            raise ValueError("weights must cover the full slot universe")
        old_assign = self.assign
        old_counts = self._counts(old_assign)
        old_version = self.version
        self.weights = new_weights
        self.inst32 = instance_keys32(self.keys64, int(new_weights.max()))
        self.assign, self.scores = topr_full(
            self.part32, self.inst32, self.weights, self.active, self.replicas
        )
        self.version = self._fingerprint()
        moved = np.flatnonzero((self.assign != old_assign).any(axis=1))
        return DeviceDiff(
            old_version=old_version,
            new_version=self.version,
            partitions_moved=moved,
            load_delta=self._counts(self.assign) - old_counts,
        )

    # -- introspection --------------------------------------------------- #

    def _counts(self, assign: np.ndarray) -> np.ndarray:
        flat = assign[assign >= 0]
        return np.bincount(flat, minlength=self.keys64.shape[0]).astype(np.int64)

    def counts(self) -> np.ndarray:
        if self.assign is None:
            return np.zeros(self.keys64.shape[0], dtype=np.int64)
        return self._counts(self.assign)

    def imbalance(self) -> float:
        """Same statistic as PlacementMap.imbalance over the active slots."""
        if self.assign is None or not self.active.any():
            return 0.0
        counts = self.counts()[self.active]
        weights = self.weights[self.active].astype(np.float64)
        total_slots = float(self.assign.size)
        fair = total_slots / float(weights.sum())
        if fair == 0.0:
            return 0.0
        return float((counts / weights).max() / fair)

    def _fingerprint(self) -> int:
        """engine._fingerprint mirror: xxh64 over the assigned node keys,
        8 LE bytes each, in partition-major order. Defined when every slot
        is filled (active count >= R), which the engine parity requires
        anyway."""
        keys = np.where(
            self.assign >= 0,
            self.keys64[np.clip(self.assign, 0, None)],
            _U64(0),
        )
        blob = keys.astype("<u8").reshape(1, -1).view(np.uint8)
        h = xxh64_batch_auto(
            blob, np.array([blob.shape[1]], dtype=np.int64), self.config.seed
        )
        u = int(h[0])
        return u - (1 << 64) if u >= (1 << 63) else u
