"""Placement plane: deterministic weighted shard maps over the membership.

``engine`` is the object model (pure-Python, imported by the protocol
plane); ``device`` is the vectorized/jitted mirror (imports numpy/jax --
pulled in lazily by the sim plane only, so protocol-side users of this
package stay light). See engine.py's module docstring for the scheme.
"""

from .engine import (
    DEFAULT_WEIGHT_KEY,
    MAX_WEIGHT,
    PlacementConfig,
    PlacementDiff,
    PlacementEngine,
    PlacementMap,
    PlacementSubscriber,
    build_map,
    diff_maps,
    rendezvous_route,
    weight_of,
    weight_seed,
)

__all__ = [
    "DEFAULT_WEIGHT_KEY",
    "MAX_WEIGHT",
    "PlacementConfig",
    "PlacementDiff",
    "PlacementEngine",
    "PlacementMap",
    "PlacementSubscriber",
    "build_map",
    "diff_maps",
    "rendezvous_route",
    "weight_of",
    "weight_seed",
]
