"""Deterministic weighted shard placement over the live membership.

The paper's closing evaluation (ATC'18 SS7, Fig. 13) routes application work
over the membership and rebalances a 10-node correlated failure in ONE view
change. This module makes that pattern a first-class subsystem: a map of P
partitions onto the view with R replicas each, computed as a *pure function*
of ``(configuration id, sorted view, per-node weights, seed)``. Because every
member runs the same function over the same strongly-consistent view, all
members derive bit-identical maps at every VIEW_CHANGE with zero extra
messages -- exactly the property strong membership buys ("Stable and
Consistent Membership at Scale with Rapid", PAPERS.md SS5).

Scheme: weighted rendezvous (highest-random-weight) hashing. Every node gets
``weight`` virtual instances; partition p scores instance v of node n by
mixing ``fold32(xxh64_long(p, seed))`` with
``fold32(endpoint_hash(n) + v*GOLDEN)``; a node's score is the max over its
instances and the replica set is the top-R nodes by ``(score desc, candidate
index asc)``. Virtual instances give *exactly* proportional expected shares;
rendezvous gives minimal motion -- a partition moves only when a node in its
top-R leaves (its other scores are untouched) or a new node out-scores its
current minimum.

The vectorized mirror of this exact arithmetic lives in
``placement/device.py`` (parity-pinned in tests/test_placement.py and the
golden vectors). Keep the two in lockstep.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from ..events import NodeStatusChange
from ..hashing import endpoint_hash, to_signed, xxh64, xxh64_long
from ..types import EdgeStatus, Endpoint

__all__ = [
    "DEFAULT_WEIGHT_KEY",
    "MAX_WEIGHT",
    "PlacementConfig",
    "PlacementDiff",
    "PlacementEngine",
    "PlacementMap",
    "PlacementSubscriber",
    "build_map",
    "diff_maps",
    "fold32",
    "instance_key32",
    "mix32",
    "node_key64",
    "partition_key32",
    "rendezvous_route",
    "weight_of",
    "weight_seed",
]

# Instance stride: 2**64 / phi, the additive constant that equidistributes
# virtual-instance keys; mix multipliers are the murmur3 fmix32 pair. All
# three are mirrored verbatim in placement/device.py.
GOLDEN64 = 0x9E3779B97F4A7C15
MIX1 = 0x85EBCA6B
MIX2 = 0xC2B2AE35
_MASK64 = (1 << 64) - 1
_MASK32 = (1 << 32) - 1

DEFAULT_WEIGHT_KEY = "capacity"
# Weights are virtual-instance counts; unbounded values would turn one bad
# metadata byte into an O(weight) score loop on every member.
MAX_WEIGHT = 64


def fold32(h: int) -> int:
    """uint64 -> uint32 by xor-folding the halves (keeps all input bits live)."""
    return (h ^ (h >> 32)) & _MASK32


def mix32(a: int, b: int) -> int:
    """The scored pair mix: murmur3-style avalanche of ``a ^ b`` (uint32)."""
    h = (a ^ b) & _MASK32
    h = (h * MIX1) & _MASK32
    h ^= h >> 15
    h = (h * MIX2) & _MASK32
    h ^= h >> 13
    return h


def partition_key32(partition: int, seed: int) -> int:
    """Partition key: xxh64 of the 8 LE bytes of the partition index."""
    return fold32(xxh64_long(partition, seed))


def node_key64(node: Endpoint, seed: int) -> int:
    """Node key: the same endpoint hash that orders the K rings."""
    return endpoint_hash(node.hostname, node.port, seed)


def instance_key32(key64: int, instance: int) -> int:
    """Virtual-instance key: node key advanced by ``instance`` golden steps."""
    return fold32((key64 + instance * GOLDEN64) & _MASK64)


def weight_of(metadata: Iterable[Tuple[str, bytes]],
              weight_key: str = DEFAULT_WEIGHT_KEY,
              default: int = 1) -> int:
    """Decode a node's placement weight from its metadata tags.

    The value is the ASCII integer under ``weight_key`` (shipped to joiners in
    JoinResponses via MetadataManager); absent or malformed values fall back
    to ``default`` so one corrupt tag cannot diverge maps across members that
    all see the same bytes."""
    for key, value in metadata:
        if key != weight_key:
            continue
        try:
            weight = int(value.decode("ascii").strip())
        except (UnicodeDecodeError, ValueError):
            return default
        return max(1, min(MAX_WEIGHT, weight))
    return default


@dataclass(frozen=True)
class PlacementConfig:
    """The deterministic inputs every member must agree on out-of-band
    (fixed at deploy time, like K/H/L)."""

    partitions: int = 256
    replicas: int = 3
    seed: int = 0
    weight_key: str = DEFAULT_WEIGHT_KEY
    default_weight: int = 1

    def __post_init__(self) -> None:
        if self.partitions <= 0:
            raise ValueError(f"partitions must be positive: {self.partitions}")
        if self.replicas <= 0:
            raise ValueError(f"replicas must be positive: {self.replicas}")


@dataclass(frozen=True)
class PlacementMap:
    """One configuration's full partition->replica-set assignment.

    ``version`` is an xxh64 fingerprint over the assigned node keys in
    partition order -- bit-identical across members and across the
    object/device planes, so statusz can detect placement disagreement the
    same way it detects configuration-id disagreement."""

    config: PlacementConfig
    configuration_id: int
    version: int
    members: Tuple[Endpoint, ...]
    assignments: Tuple[Tuple[Endpoint, ...], ...]
    weights: Tuple[int, ...] = ()

    def counts(self) -> Dict[Endpoint, int]:
        """Replica slots held per member (members holding zero included)."""
        out: Dict[Endpoint, int] = {node: 0 for node in self.members}
        for row in self.assignments:
            for node in row:
                out[node] += 1
        return out

    def owned(self, node: Endpoint) -> Tuple[int, ...]:
        """Partitions whose replica set contains ``node``."""
        return tuple(
            p for p, row in enumerate(self.assignments) if node in row
        )

    def imbalance(self) -> float:
        """max over members of (slots held / weight) divided by the
        weight-proportional fair share; 1.0 is perfectly balanced."""
        if not self.members:
            return 0.0
        weights = self.weights or tuple(1 for _ in self.members)
        total_slots = sum(len(row) for row in self.assignments)
        total_weight = sum(weights)
        if total_slots == 0 or total_weight == 0:
            return 0.0
        fair = total_slots / total_weight
        counts = self.counts()
        return max(
            counts[node] / weight / fair
            for node, weight in zip(self.members, weights)
        )


@dataclass(frozen=True)
class PlacementDiff:
    """What moved between two consecutive maps of the same geometry.

    ``handoffs`` pairs each moved partition's donors with its recipients
    positionally; a recipient with no departing donor (pure join growth)
    is paired with the partition's first surviving replica, which holds the
    data to stream from."""

    old_version: int
    new_version: int
    configuration_id: int
    partitions_moved: Tuple[int, ...]
    handoffs: Tuple[Tuple[int, Optional[Endpoint], Endpoint], ...]
    load_delta: Tuple[Tuple[Endpoint, int], ...]

    @property
    def moved(self) -> int:
        return len(self.partitions_moved)


def _score_node(part32: int, key64: int, weight: int) -> int:
    best = 0
    for v in range(weight):
        s = mix32(part32, instance_key32(key64, v))
        if s > best:
            best = s
    return best


def _fingerprint(assignments: Sequence[Sequence[Endpoint]],
                 keys: Mapping[Endpoint, int], seed: int) -> int:
    blob = b"".join(
        keys[node].to_bytes(8, "little")
        for row in assignments
        for node in row
    )
    return to_signed(xxh64(blob, seed))


def build_map(
    members: Iterable[Endpoint],
    weights: Mapping[Endpoint, int],
    config: PlacementConfig,
    configuration_id: int,
) -> PlacementMap:
    """The pure map function. Candidate order is the sorted view --
    (hostname, port) -- so every member iterates identically; ties in the
    32-bit scores (probability ~2**-32 per pair) resolve to the lower
    candidate index on both planes."""
    ordered = tuple(sorted(set(members)))
    member_weights = tuple(
        weights.get(node, config.default_weight) for node in ordered
    )
    keys = {node: node_key64(node, config.seed) for node in ordered}
    replicas = min(config.replicas, len(ordered))
    assignments: List[Tuple[Endpoint, ...]] = []
    for p in range(config.partitions):
        part32 = partition_key32(p, config.seed)
        # top-R by (score desc, index asc): sort on (score, -index) desc
        scored = sorted(
            ((_score_node(part32, keys[node], w), -i)
             for i, (node, w) in enumerate(zip(ordered, member_weights))),
            reverse=True,
        )
        assignments.append(
            tuple(ordered[-neg_i] for _, neg_i in scored[:replicas])
        )
    rows = tuple(assignments)
    return PlacementMap(
        config=config,
        configuration_id=configuration_id,
        version=_fingerprint(rows, keys, config.seed),
        members=ordered,
        assignments=rows,
        weights=member_weights,
    )


def diff_maps(old: PlacementMap, new: PlacementMap) -> PlacementDiff:
    """Rebalance plan between two maps of the same config."""
    if old.config != new.config:
        raise ValueError("cannot diff maps built from different configs")
    moved: List[int] = []
    handoffs: List[Tuple[int, Optional[Endpoint], Endpoint]] = []
    for p, (old_row, new_row) in enumerate(zip(old.assignments, new.assignments)):
        if old_row == new_row:
            continue
        moved.append(p)
        donors = [node for node in old_row if node not in new_row]
        recipients = [node for node in new_row if node not in old_row]
        survivors = [node for node in old_row if node in new_row]
        for i, recipient in enumerate(recipients):
            if i < len(donors):
                donor: Optional[Endpoint] = donors[i]
            elif survivors:
                donor = survivors[0]
            else:
                donor = None
            handoffs.append((p, donor, recipient))
    old_counts = old.counts()
    new_counts = new.counts()
    nodes = sorted(set(old_counts) | set(new_counts))
    load_delta = tuple(
        (node, new_counts.get(node, 0) - old_counts.get(node, 0))
        for node in nodes
        if new_counts.get(node, 0) != old_counts.get(node, 0)
    )
    return PlacementDiff(
        old_version=old.version,
        new_version=new.version,
        configuration_id=new.configuration_id,
        partitions_moved=tuple(moved),
        handoffs=tuple(handoffs),
        load_delta=load_delta,
    )


class PlacementEngine:
    """Stateful wrapper: rebuilds the map per configuration and diffs it
    against the previous one. Hosts no protocol state of its own -- feed it
    the view and it answers; two engines fed the same views are
    indistinguishable."""

    def __init__(self, config: PlacementConfig) -> None:
        self.config = config
        self.map: Optional[PlacementMap] = None  # guarded-by: protocol-executor
        self.last_diff: Optional[PlacementDiff] = None  # guarded-by: protocol-executor

    def update(
        self,
        configuration_id: int,
        members: Iterable[Endpoint],
        weights: Mapping[Endpoint, int],
    ) -> Tuple[PlacementMap, Optional[PlacementDiff]]:
        new_map = build_map(members, weights, self.config, configuration_id)
        diff = diff_maps(self.map, new_map) if self.map is not None else None
        self.map, self.last_diff = new_map, diff
        return new_map, diff


class PlacementSubscriber:
    """Drives a PlacementEngine purely from ClusterEvents.VIEW_CHANGE.

    The initial VIEW_CHANGE fired at service construction carries the full
    ring with metadata (MembershipService.java:162-165 parity), so the
    subscriber bootstraps its member/weight table from events alone --
    register it via ``ClusterBuilder.add_subscription`` or
    ``Cluster.register_subscription`` and it never touches the view."""

    def __init__(self, config: PlacementConfig) -> None:
        self._engine = PlacementEngine(config)
        self._weights: Dict[Endpoint, int] = {}
        self.view_changes = 0

    @property
    def config(self) -> PlacementConfig:
        return self._engine.config

    @property
    def map(self) -> Optional[PlacementMap]:
        return self._engine.map

    @property
    def last_diff(self) -> Optional[PlacementDiff]:
        return self._engine.last_diff

    def __call__(self, configuration_id: int,
                 changes: List[NodeStatusChange]) -> None:
        cfg = self._engine.config
        for change in changes:
            if change.status == EdgeStatus.UP:
                self._weights[change.endpoint] = weight_of(
                    change.metadata, cfg.weight_key, cfg.default_weight
                )
            else:
                self._weights.pop(change.endpoint, None)
        self.view_changes += 1
        self._engine.update(configuration_id, self._weights, self._weights)


# --------------------------------------------------------------------------
# Key-routing helpers (the examples/load_balancer.py rendezvous scheme)
# --------------------------------------------------------------------------

def weight_seed(backend: Endpoint) -> int:
    """Per-backend rendezvous seed: hash of the printable identity, masked
    positive so it is a valid xxh64 seed everywhere."""
    return xxh64(backend.hostname + b"#%d" % backend.port, 0) & 0x7FFFFFFF


def rendezvous_route(
    key: bytes,
    backends: Sequence[Endpoint],
    seeds: Mapping[Endpoint, int],
) -> Endpoint:
    """Classic per-key rendezvous over explicit backends: the backend whose
    seeded hash of the key is highest. ``seeds`` comes from weight_seed()."""
    if not backends:
        raise ValueError("no backends")
    return max(backends, key=lambda b: xxh64(key, seeds[b]))
