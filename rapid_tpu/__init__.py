"""rapid-tpu: a TPU-native framework with the capabilities of Rapid, the
scalable distributed membership service (USENIX ATC'18).

Two execution planes:
- the *protocol plane* (this package root): a full Rapid-equivalent membership
  stack -- Cluster API, membership service, K-ring views, cut detection, Fast
  Paxos -- running over pluggable messaging and failure-detector seams;
- the *simulation plane* (``rapid_tpu.sim`` / ``rapid_tpu.shard``): the same
  protocol vectorized as jitted JAX array programs, hosting up to 100k virtual
  nodes in TPU HBM and sharded over device meshes.
"""

from .cluster import Cluster, ClusterBuilder, JoinException, K, H, L
from .events import ClusterEvents, NodeStatusChange
from .membership import Configuration, MembershipView
from .cut_detector import MultiNodeCutDetector
from .handoff import (
    InMemoryPartitionStore,
    PartitionStore,
    TransferPlan,
    plan_transfers,
)
from .placement.engine import (
    PlacementConfig,
    PlacementDiff,
    PlacementMap,
    PlacementSubscriber,
)
from .settings import Settings
from .types import (
    EdgeStatus,
    Endpoint,
    JoinStatusCode,
    NodeId,
    NodeStatus,
)

__all__ = [
    "Cluster",
    "ClusterBuilder",
    "ClusterEvents",
    "Configuration",
    "EdgeStatus",
    "Endpoint",
    "InMemoryPartitionStore",
    "JoinException",
    "JoinStatusCode",
    "MembershipView",
    "MultiNodeCutDetector",
    "NodeId",
    "NodeStatus",
    "NodeStatusChange",
    "PartitionStore",
    "PlacementConfig",
    "PlacementDiff",
    "PlacementMap",
    "PlacementSubscriber",
    "Settings",
    "TransferPlan",
    "plan_transfers",
    "K",
    "H",
    "L",
]

__version__ = "0.1.0"
