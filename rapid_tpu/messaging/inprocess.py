"""In-process transport: full protocol, zero sockets.

Reference trick: the gRPC in-process channel keyed by endpoint string
(GrpcClient.java:165-171, GrpcServer.java:133-138) lets 50-100 node clusters
run the complete protocol in one JVM. Here an InProcessNetwork is the registry;
delivery hops through the scheduler (so messages are asynchronous and ordered
by virtual/real time), and per-link fault hooks (drop/delay/partition) are
first-class -- they subsume the reference's test interceptors
(ServerDropInterceptors/ClientInterceptors, MessageDropInterceptor.java).
"""

from __future__ import annotations

import logging
import random
import zlib
from typing import Callable, Dict, List, Optional

from ..runtime.futures import Promise
from ..runtime.scheduler import Scheduler
from ..settings import Settings
from ..types import Endpoint, NodeStatus, ProbeMessage, ProbeResponse, RapidMessage
from .base import IMessagingClient, IMessagingServer
from .retries import call_with_retries

LOG = logging.getLogger(__name__)

# (src, dst, msg) -> keep delivering? Returning False drops the message.
LinkFilter = Callable[[Endpoint, Endpoint, RapidMessage], bool]
# (src, dst, msg) -> extra one-way delay in ms (0 = none)
LinkDelay = Callable[[Endpoint, Endpoint, RapidMessage], int]


class InProcessNetwork:
    """Shared registry + fault-injection plane for one in-process cluster."""

    def __init__(self, scheduler: Scheduler) -> None:
        self.scheduler = scheduler
        self._servers: Dict[Endpoint, "InProcessServer"] = {}
        self._filters: List[LinkFilter] = []
        self._delays: List[LinkDelay] = []
        # fallback handlers for endpoints not backed by a per-node server --
        # e.g. a TpuSimMessaging swarm hosting thousands of virtual nodes
        # behind one handler (owns(ep) -> bool, handle(dst, msg) -> Promise)
        self._handlers: List[object] = []

    # -- fault injection -----------------------------------------------------

    def add_filter(self, f: LinkFilter) -> Callable[[], None]:
        self._filters.append(f)
        return lambda: self._filters.remove(f)

    def add_delay(self, d: LinkDelay) -> Callable[[], None]:
        self._delays.append(d)
        return lambda: self._delays.remove(d)

    def partition_one_way(self, src: Endpoint, dst: Endpoint) -> Callable[[], None]:
        """Drop all src->dst traffic (models iptables INPUT one-way loss)."""
        return self.add_filter(lambda s, d, m: not (s == src and d == dst))

    # -- registry ------------------------------------------------------------

    def register(self, server: "InProcessServer") -> None:
        self._servers[server.address] = server

    def unregister(self, server: "InProcessServer") -> None:
        if self._servers.get(server.address) is server:
            del self._servers[server.address]

    def attach_handler(self, handler) -> None:
        """Attach a multi-endpoint fallback handler (e.g. a simulation swarm)."""
        self._handlers.append(handler)

    def is_listening(self, address: Endpoint) -> bool:
        """Is a per-node server currently registered at this address?"""
        return address in self._servers

    # -- delivery ------------------------------------------------------------

    def deliver(self, src: Endpoint, dst: Endpoint, msg: RapidMessage,
                timeout_ms: int) -> Promise:
        """One attempt: apply fault plane, hop through the scheduler, dispatch
        at the destination server, enforce the deadline."""
        out: Promise = Promise()
        for f in self._filters:
            if not f(src, dst, msg):
                # dropped on the wire: the sender just sees its deadline expire
                self.scheduler.schedule(timeout_ms, lambda: _timeout(out, dst, msg))
                return out
        delay = sum(d(src, dst, msg) for d in self._delays)

        def attempt() -> None:
            server = self._servers.get(dst)
            if server is None:
                for handler in self._handlers:
                    if handler.owns(dst):
                        server = handler
                        break
            if server is None:
                _fail(out, ConnectionError(f"no server listening at {dst}"))
                return
            try:
                if server in self._handlers:
                    promise = server.handle(dst, msg)
                else:
                    promise = server.handle(msg)
                promise.add_callback(lambda p: _copy(p, out))
            except Exception as e:  # noqa: BLE001
                _fail(out, e)

        self.scheduler.schedule(delay, attempt)
        self.scheduler.schedule(timeout_ms + delay, lambda: _timeout(out, dst, msg))
        return out


def _copy(src: Promise, dst: Promise) -> None:
    if dst.done():
        return
    exc = src.exception()
    if exc is not None:
        _fail(dst, exc)
    else:
        dst.try_set_result(src._result)  # noqa: SLF001 -- promise-internal copy


def _fail(p: Promise, exc: BaseException) -> None:
    if not p.done():
        try:
            p.set_exception(exc)
        except Exception:  # noqa: BLE001 -- lost race with completion
            pass


def _timeout(p: Promise, dst: Endpoint, msg: RapidMessage) -> None:
    _fail(p, TimeoutError(f"no response from {dst} for {type(msg).__name__}"))


class InProcessServer(IMessagingServer):
    """Dispatches incoming messages to the node's MembershipService.

    Until set_membership_service is called, probes are answered BOOTSTRAPPING
    and everything else is silently dropped (GrpcServer.java:77-96) -- the
    joining node's server is started before the join completes.
    """

    def __init__(self, address: Endpoint, network: InProcessNetwork) -> None:
        self.address = address
        self._network = network
        self._service = None
        self._started = False
        # test seam: functions (msg) -> bool; False drops the message at the
        # server (ServerDropInterceptors.FirstN, MessageDropInterceptor.java)
        self.interceptors: List[Callable[[RapidMessage], bool]] = []

    def start(self) -> None:
        self._network.register(self)
        self._started = True

    def shutdown(self) -> None:
        self._network.unregister(self)
        self._started = False

    def set_membership_service(self, service) -> None:
        self._service = service

    def handle(self, msg: RapidMessage) -> Promise:
        for interceptor in self.interceptors:
            if not interceptor(msg):
                return Promise()  # never completes -> sender times out
        if self._service is None:
            if isinstance(msg, ProbeMessage):
                return Promise.completed(ProbeResponse(NodeStatus.BOOTSTRAPPING))
            return Promise()  # dropped (GrpcServer.java:77-82)
        return self._service.handle_message(msg)


class InProcessClient(IMessagingClient):
    """Client side: per-message-type deadlines + async retries
    (GrpcClient.java:102-131)."""

    def __init__(self, address: Endpoint, network: InProcessNetwork,
                 settings: Optional[Settings] = None,
                 rng: Optional[random.Random] = None) -> None:
        self.address = address
        self._network = network
        self._settings = settings if settings is not None else Settings()
        # jitter draws; content-seeded (not id/hash-salted) so virtual-time
        # runs replay bit-identically across processes
        self._rng = rng if rng is not None else random.Random(
            zlib.crc32(address.hostname) ^ address.port
        )
        self._shutdown = False

    def send_message(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        timeout = self._settings.timeout_for(msg)
        return call_with_retries(
            lambda: self._network.deliver(self.address, remote, msg, timeout),
            self._settings.message_retries,
            scheduler=self._network.scheduler,
            policy=self._settings.retry_policy(),
            deadline_ms=self._settings.deadline_for(msg),
            rng=self._rng,
        )

    def send_message_best_effort(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        timeout = self._settings.timeout_for(msg)
        return self._network.deliver(self.address, remote, msg, timeout)

    def shutdown(self) -> None:
        self._shutdown = True
