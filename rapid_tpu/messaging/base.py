"""The messaging plugin seam.

Reference: messaging/IMessagingClient.java:25-48, IMessagingServer.java:24-41,
IBroadcaster.java:24-29. This is one of the two seams Rapid exposes for
swapping transports (the other is the edge failure detector); the TPU
simulation backend implements exactly these interfaces, as do the in-process
and TCP transports.
"""

from __future__ import annotations

from typing import List

from ..runtime.futures import Promise
from ..types import Endpoint, RapidMessage


class IMessagingClient:
    """Sends messages to remote nodes."""

    def send_message(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        """Send with per-message-type timeouts and retries
        (IMessagingClient.java:25-37)."""
        raise NotImplementedError

    def send_message_best_effort(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        """Single attempt, no retries (IMessagingClient.java:39-45)."""
        raise NotImplementedError

    def shutdown(self) -> None:
        raise NotImplementedError


class IMessagingServer:
    """Receives messages and hands them to a MembershipService."""

    def start(self) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        raise NotImplementedError

    def set_membership_service(self, service) -> None:
        """Until this is called the server must not dispatch protocol messages
        (probes get a BOOTSTRAPPING answer instead, GrpcServer.java:77-96)."""
        raise NotImplementedError


class IBroadcaster:
    """Disseminates a message to all cluster members (IBroadcaster.java:24-29).

    Broadcast is deliberately not a transport primitive: the default
    implementation is best-effort unicast-to-all, but gossip/flooding
    alternatives can be plugged in.
    """

    def broadcast(self, msg: RapidMessage) -> List[Promise]:
        raise NotImplementedError

    def set_membership(self, recipients: List[Endpoint]) -> None:
        raise NotImplementedError
