"""Socket gateway: the TPU-hosted swarm reachable by external OS processes.

The reference's seam design means *any* transport can host a membership
service (IMessagingServer.java:24-41, GrpcServer.java:133-148). This module
hosts ``TpuSimMessaging`` -- N virtual nodes whose protocol state lives as
device arrays in the TPU simulator -- behind a real TCP socket, so a real
agent process (the shape of the reference's standalone agent,
StandaloneAgent.java:94-116) joins, probes, broadcasts, votes, observes cuts,
and leaves against TPU-hosted peers over the wire.

Routing: one gateway socket fronts *thousands* of virtual endpoints, so the
wire frame must carry the destination (a plain rapid frame does not -- the
reference's server knows who it is by which socket it binds). The routed
frame prepends the destination endpoint to the standard codec envelope;
responses travel back correlated by request number exactly as in the plain
transport (NettyClientServer.java:267-277's pattern). Agent-side, a
``GatewayRoutedClient`` wraps the agent's normal transport: destinations
whose hostname is locally routable go direct (agent <-> agent traffic),
everything else -- the synthetic 10.x.y.z virtual addresses -- rides the
gateway connection. This is a transport-plugin concern, exactly what the
IMessagingClient seam exists for (IMessagingClient.java:25-48).

Threading model mirrors the reference: ALL swarm-side protocol logic
(bridge.handle + pump) is serialized on one protocol thread
(SharedResources.java:53's single protocolExecutor). The bridge's
clock-advance during the pre-decision vote exchange (pump phase B) is mapped
onto that thread's own task queue: ``run_for`` drains inbound requests for
the wait window, so real members' votes are tallied *during* the pause
rather than queuing behind it.
"""

from __future__ import annotations

import itertools
import logging
import queue
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..runtime.lockdep import make_lock
from ..runtime.futures import Promise
from ..runtime.scheduler import RealScheduler
from ..settings import Settings
from ..types import (
    Endpoint,
    JoinMessage,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    RapidMessage,
)
from .base import IBroadcaster, IMessagingClient
from .codec import ENVELOPE, decode, encode
from .retries import call_with_retries
from .tcp import (
    FramedTcpServer,
    TcpClientServer,
    _Connection,
    _write_frame,
    send_framed,
)

LOG = logging.getLogger(__name__)

# routed envelope: request number, destination host length (host bytes and a
# u32 port follow), then the standard (tag, payload) body
ROUTED_HEADER = struct.Struct("!QH")
_PORT = struct.Struct("!I")


def encode_routed(request_no: int, dst: Endpoint, msg: RapidMessage) -> bytes:
    body = encode(request_no, msg)[ENVELOPE.size - 1 :]  # (tag, payload)
    return (
        ROUTED_HEADER.pack(request_no, len(dst.hostname))
        + dst.hostname
        + _PORT.pack(dst.port)
        + body
    )


def decode_routed(frame: bytes) -> Tuple[int, Endpoint, RapidMessage]:
    request_no, host_len = ROUTED_HEADER.unpack_from(frame)
    offset = ROUTED_HEADER.size
    host = frame[offset : offset + host_len]
    offset += host_len
    (port,) = _PORT.unpack_from(frame, offset)
    offset += _PORT.size
    # reconstitute a standard envelope for the shared decoder
    _, msg = decode(ENVELOPE.pack(request_no, frame[offset]) + frame[offset + 1 :])
    return request_no, Endpoint(host, port), msg


DEFAULT_DIRECT_HOSTS = (b"127.0.0.1", b"localhost")


class GatewayRoutedClient(IMessagingClient):
    """Agent-side client: direct transport for routable peers, the gateway
    connection for everything else (the swarm's virtual endpoints)."""

    def __init__(
        self,
        address: Endpoint,
        gateway: Endpoint,
        direct: IMessagingClient,
        settings: Optional[Settings] = None,
        direct_hosts: Optional[Set[bytes]] = None,
    ) -> None:
        self.address = address
        self.gateway = gateway
        self._direct = direct
        self._settings = settings if settings is not None else Settings()
        self._direct_hosts = (
            set(direct_hosts)
            if direct_hosts is not None
            else set(DEFAULT_DIRECT_HOSTS)
        )
        self._direct_hosts.add(address.hostname)
        self._request_no = itertools.count(1)
        self._conn: Optional[_Connection] = None
        self._conn_lock = make_lock("GatewayRoutedClient._conn_lock")

    def _is_direct(self, remote: Endpoint) -> bool:
        return remote.hostname in self._direct_hosts

    def _connection(self) -> _Connection:
        with self._conn_lock:
            if self._conn is None or self._conn.closed:
                # deliberately dialing under the lock: there is exactly ONE
                # upstream (the gateway), so no unrelated sender is stalled,
                # and serializing the dial prevents a thundering herd of
                # duplicate gateway connections after a drop
                self._conn = _Connection(  # noqa: blocking-under-lock
                    self.gateway, self._settings.message_timeout_ms / 1000.0
                )
            return self._conn

    def _send_routed_once(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        try:
            conn = self._connection()
        except OSError as e:
            return Promise.failed(e)
        request_no = next(self._request_no)
        return send_framed(
            conn, request_no, encode_routed(request_no, remote, msg),
            self._settings.timeout_for(msg) / 1000.0, remote,
        )

    def send_message(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        if self._is_direct(remote):
            return self._direct.send_message(remote, msg)
        return call_with_retries(
            lambda: self._send_routed_once(remote, msg),
            self._settings.message_retries,
        )

    def send_message_best_effort(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        if self._is_direct(remote):
            return self._direct.send_message_best_effort(remote, msg)
        return self._send_routed_once(remote, msg)

    def shutdown(self) -> None:
        self._direct.shutdown()
        with self._conn_lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None


# wildcard destination: one routed frame that the gateway ingests once on
# behalf of every virtual member (see GatewaySwarmBroadcaster)
SWARM_BROADCAST = Endpoint(b"*", 0)


class GatewaySwarmBroadcaster(IBroadcaster):
    """Broadcaster for members running behind a gateway.

    Unicast-to-all through a gateway is pathological at swarm scale: a
    broadcast to N members becomes N identical frames ground through ONE
    socket (at 10k virtual nodes a single vote broadcast takes tens of
    seconds and floods the gateway's protocol queue). But every
    swarm-bound copy is redundant -- the bridge ingests alert batches and
    votes once per sender and the device delivers them to every virtual
    member as array work -- so this broadcaster collapses them into ONE
    wildcard frame (``SWARM_BROADCAST``; TpuSimMessaging.handle_broadcast),
    while direct (real-member) recipients keep the reference's
    per-recipient best-effort unicast."""

    def __init__(self, routed: "GatewayRoutedClient") -> None:
        self._routed = routed
        self._direct_recipients: List[Endpoint] = []
        self._any_swarm = False

    def set_membership(self, recipients: List[Endpoint]) -> None:
        self._direct_recipients = [
            r for r in recipients if self._routed._is_direct(r)  # noqa: SLF001
        ]
        self._any_swarm = len(self._direct_recipients) < len(recipients)

    def broadcast(self, msg: RapidMessage) -> List[Promise]:
        promises = [
            self._routed.send_message_best_effort(r, msg)
            for r in self._direct_recipients
        ]
        if self._any_swarm:
            promises.append(
                self._routed._send_routed_once(SWARM_BROADCAST, msg)  # noqa: SLF001
            )
        return promises


class GatewayGossipBroadcaster(IBroadcaster):
    """Epidemic dissemination among the real members behind a gateway.

    Composition of the two broadcast optimizations: swarm-bound copies
    collapse into ONE wildcard frame exactly like GatewaySwarmBroadcaster
    (the device delivers them to every virtual member as array work), while
    the direct (real-member) plane uses GossipBroadcaster's relay instead of
    unicast-to-all -- at M real members that turns each broadcast's direct
    leg from M-1 sends into ~fanout, the dissemination alternative the
    reference names but never ships (IBroadcaster.java:24-26). The swarm is
    one "super-node" from the epidemic's viewpoint: it hears every broadcast
    exactly once and never relays."""

    def __init__(self, routed: "GatewayRoutedClient", gossip) -> None:
        self._routed = routed
        self._gossip = gossip
        self._any_swarm = False

    def set_membership(self, recipients: List[Endpoint]) -> None:
        direct = [
            r for r in recipients if self._routed._is_direct(r)  # noqa: SLF001
        ]
        self._any_swarm = len(direct) < len(recipients)
        self._gossip.set_membership(direct)

    def broadcast(self, msg: RapidMessage) -> List[Promise]:
        promises = self._gossip.broadcast(msg)
        if self._any_swarm:
            promises.append(
                self._routed._send_routed_once(SWARM_BROADCAST, msg)  # noqa: SLF001
            )
        return promises

    def receive(self, env) -> Optional[RapidMessage]:
        """Relay-plane entry (the membership service forwards inbound
        GossipEnvelopes here, like for a plain GossipBroadcaster)."""
        return self._gossip.receive(env)


class _GatewayScheduler(RealScheduler):
    """RealScheduler plus ``run_for``: the bridge's clock advance drains the
    gateway's protocol queue for the window, so inbound votes are processed
    *during* the pre-decision pause (TpuSimMessaging._advance_clock)."""

    def __init__(self, drain: Callable[[float], None]) -> None:
        super().__init__()
        self._drain = drain

    def run_for(self, ms: int) -> None:
        self._drain(ms / 1000.0)


class _LivenessState:
    __slots__ = ("alive", "misses", "last_query")

    def __init__(self, alive: bool, now: float) -> None:
        self.alive = alive
        self.misses = 0
        self.last_query = now


class _GatewayNetwork:
    """The bridge-facing network adapter: liveness by dialing, delivery over
    the gateway's outbound client (InProcessNetwork's contract, on sockets).

    Liveness dials run on a background monitor, NOT the protocol thread: the
    bridge senses every real member each pump, and a loaded box misses dials
    (0.25 s timeout each) -- 50 members' worth of synchronous dials blocked
    the protocol thread for seconds per pump, starving joiners' phase-1
    requests past their retry budget (the 50-joiner starvation, VERDICT r4
    weak #1). ``is_listening`` now answers from the monitor's cache in O(1);
    only the FIRST query for an unknown endpoint dials synchronously (the
    join-admission path, where the agent was just talking to us)."""

    PROBE_TIMEOUT_S = 0.25
    # background refresh cadence; death detection latency is one period plus
    # the timeout-tolerance window below
    REFRESH_S = 0.5
    # watched endpoints not asked about for this long are dropped (removed
    # members stop being queried by the bridge, so the watch set self-cleans)
    WATCH_TTL_S = 30.0
    # parallel dial lanes for the refresher (dials are I/O-bound waits)
    DIAL_WORKERS = 8

    # ambiguous dial failures (timeouts under load) tolerated before a
    # member is reported gone; a refused connection is definitive death
    DIAL_TIMEOUTS_TO_FAIL = 3

    def __init__(self, out_client: TcpClientServer, scheduler: RealScheduler) -> None:
        self.scheduler = scheduler
        self._out = out_client
        self._handlers: List[object] = []
        self._watch: Dict[Endpoint, _LivenessState] = {}
        self._watch_lock = make_lock("_GatewayNetwork._watch_lock")
        self._stop = threading.Event()
        self._monitor = threading.Thread(  # noqa: messaging-thread
            target=self._monitor_loop, name="gateway-liveness", daemon=True
        )
        self._dialers = ThreadPoolExecutor(
            max_workers=self.DIAL_WORKERS, thread_name_prefix="gateway-dial"
        )
        # delivery workers: sends (whose connect can block for the full
        # message timeout on an unreachable member) run OFF the protocol
        # thread, so probes/joins from healthy agents are never queued behind
        # a dead member's dials. Per-destination frame order is preserved by
        # hashing the destination to a fixed single-thread lane; multiple
        # lanes keep one slow member from backing up deliveries to the rest
        self._delivery = [
            ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"gateway-delivery-{i}"
            )
            for i in range(4)
        ]
        # started last: the monitor loop dereferences _dialers (and a first
        # refresh can race construction), so every executor must be assigned
        # before the thread runs
        self._monitor.start()

    def attach_handler(self, handler) -> None:
        self._handlers.append(handler)

    def _dial(self, address: Endpoint) -> Optional[bool]:
        """One dial: True = listening, False = definitively gone (refused),
        None = ambiguous (timeout/transient on a loaded host)."""
        try:
            probe = socket.create_connection(
                (address.hostname.decode(), address.port),
                timeout=self.PROBE_TIMEOUT_S,
            )
            probe.close()
            return True
        except ConnectionRefusedError:
            return False
        except OSError:
            return None

    def _refresh_one(self, address: Endpoint, state: _LivenessState) -> None:
        outcome = self._dial(address)
        if outcome is True:
            state.alive = True
            state.misses = 0
        elif outcome is False:
            # the port actively refused: the process is gone -- definitive
            state.alive = False
            state.misses = 0
        else:
            # timeout or transient error: a loaded host can miss a dial
            # without being dead, and declaring a live member gone starts a
            # cut/rejoin cascade -- tolerate consecutive ambiguous misses
            state.misses += 1
            if state.misses >= self.DIAL_TIMEOUTS_TO_FAIL:
                # declared gone; reset the budget so a rejoin at this
                # address gets the full tolerance again
                state.alive = False
                state.misses = 0

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.REFRESH_S):
            now = time.monotonic()
            with self._watch_lock:
                expired = [
                    ep
                    for ep, st in self._watch.items()
                    if now - st.last_query > self.WATCH_TTL_S
                ]
                for ep in expired:
                    del self._watch[ep]
                snapshot = list(self._watch.items())
            if not snapshot:
                continue
            try:
                list(
                    self._dialers.map(
                        lambda item: self._refresh_one(*item), snapshot
                    )
                )
            except RuntimeError:  # pool shut down mid-refresh
                return

    def is_listening(self, address: Endpoint) -> bool:
        now = time.monotonic()
        conn = self._out._connections.get(address)  # noqa: SLF001
        if conn is not None and not conn.closed:
            # keep (or seed) the watch entry while the live connection
            # answers for us: when the member later dies and the cached
            # connection drops, the monitor must already be watching, or
            # the next pump pays a synchronous dial per dead member
            with self._watch_lock:
                state = self._watch.get(address)
                if state is None:
                    self._watch[address] = _LivenessState(True, now)
                else:
                    state.alive = True
                    state.misses = 0
                    state.last_query = now
            return True
        with self._watch_lock:
            state = self._watch.get(address)
            if state is not None:
                state.last_query = now
                return state.alive
        # first contact (join admission, or a rejoin after the watch entry
        # expired): one synchronous dial seeds the watch entry. An ambiguous
        # first dial counts as alive -- the monitor's tolerance window takes
        # over from here
        outcome = self._dial(address)
        alive = outcome is not False
        with self._watch_lock:
            self._watch.setdefault(address, _LivenessState(alive, now))
        return alive

    def deliver(
        self, src: Endpoint, dst: Endpoint, msg: RapidMessage, timeout_ms: int
    ) -> Promise:
        # src rides inside the message payload, as on every rapid transport.
        # Retried (send_message, not best-effort): decision packets are the
        # member's only way to learn a view change, and a transient socket
        # failure must not strand it on the old configuration
        out: Promise = Promise()

        def send() -> None:
            try:
                self._out.send_message_with_timeout(
                    dst, msg, timeout_ms
                ).add_callback(
                    lambda p: out.done()
                    or (
                        out.set_exception(p.exception())
                        if p.exception() is not None
                        else out.try_set_result(p._result)  # noqa: SLF001
                    )
                )
            except Exception as e:  # noqa: BLE001
                if not out.done():
                    out.set_exception(e)

        lane = hash(dst) % len(self._delivery)
        try:
            self._delivery[lane].submit(send)
        except RuntimeError as e:  # pool shut down: gateway teardown race
            out.set_exception(e)
        return out

    def shutdown(self) -> None:
        self._stop.set()
        self._dialers.shutdown(wait=False)
        for pool in self._delivery:
            pool.shutdown(wait=False)


class SwarmGateway:
    """Hosts a TpuSimMessaging swarm behind one real TCP socket.

    start() binds the socket and the pump loop; external processes join the
    swarm through ``seed_endpoint()`` using a GatewayRoutedClient. All bridge
    access is serialized on the protocol thread; responses complete
    asynchronously when the simulated view change commits (parked joins),
    mirroring MembershipService.java:229-286 over a real wire.
    """

    def __init__(
        self,
        listen_address: Endpoint,
        n_virtual: int = 0,
        capacity: Optional[int] = None,
        config=None,
        seed: int = 0,
        settings: Optional[Settings] = None,
        pump_interval_ms: int = 100,
        pump_max_rounds: int = 32,
        restore_from: Optional[str] = None,
        restore_config_overrides: Optional[dict] = None,
        mesh=None,
        native_server: bool = False,
    ) -> None:
        """``native_server``: accept/read routed frames on the C++ epoll
        reactor (native/rapid_io.cpp) instead of the thread-per-connection
        Python server; the wire format and everything above it (routing,
        parking, the pump) is identical."""
        from ..sim.bridge import TpuSimMessaging

        self.address = listen_address
        self._settings = settings if settings is not None else Settings()
        self._out = TcpClientServer(listen_address, self._settings)
        # join-class prioritization (the reference gives joins a 5x RPC
        # deadline for the same reason, GrpcClient.java:55-59): a joiner's
        # phase-1 request is answered ahead of queued broadcast traffic and
        # ahead of a pending pump (whose device dispatches are the longest
        # tasks on this thread), so a join wave cannot starve later joiners
        # past their retry budget. Within a class, FIFO via the sequence
        self._tasks: "queue.PriorityQueue[Tuple[int, int, Optional[Callable[[], None]]]]" = (
            queue.PriorityQueue()
        )
        self._task_seq = itertools.count()
        self._scheduler = _GatewayScheduler(self._drain_for)
        self.network = _GatewayNetwork(self._out, self._scheduler)
        if restore_from is not None:
            if n_virtual or capacity is not None or config is not None or seed:
                raise ValueError(
                    "restore_from takes identity/config from the snapshot; "
                    "re-apply non-persisted SimConfig fields via "
                    "restore_config_overrides, not n_virtual/capacity/"
                    "config/seed"
                )
            self.bridge = TpuSimMessaging.restore(
                self.network, restore_from,
                config_overrides=restore_config_overrides,
                mesh=mesh,
            )
        else:
            if n_virtual <= 0:
                raise ValueError("pass n_virtual > 0, or restore_from a snapshot")
            self.bridge = TpuSimMessaging(
                self.network,
                n_virtual=n_virtual,
                capacity=capacity,
                config=config,
                seed=seed,
                mesh=mesh,
            )
        self._pump_interval_s = pump_interval_ms / 1000.0
        self._pump_max_rounds = pump_max_rounds
        self._native_server = native_server
        self._reactor = None
        self._framed = (
            None
            if native_server
            else FramedTcpServer(listen_address, self._on_frame, "gateway")
        )
        self._threads: List[threading.Thread] = []
        self._task_stats: Dict[str, list] = {}
        # reply-writer lanes: see _on_frame (keyed by connection so one
        # agent's backpressure cannot block replies to the rest)
        self._writers = [
            ThreadPoolExecutor(max_workers=1,
                               thread_name_prefix=f"gateway-writer-{i}")
            for i in range(2)
        ]
        self._running = False
        self._decisions: List[object] = []
        self._decision_lock = make_lock("SwarmGateway._decision_lock")
        self._warned_lock = make_lock("SwarmGateway._warned_lock")
        self._warned_unowned: set = set()  # guarded-by: _warned_lock

    # task classes for the protocol thread's priority queue. The pump
    # shares the frame class on purpose: at a strictly lower priority a
    # sustained stream of broadcast frames could starve it forever, and the
    # pump is the only producer of decisions, parked-join completion, and
    # liveness sensing -- FIFO within the class bounds its wait by the
    # backlog present when it was enqueued. Join-class frames still jump
    # the whole queue (the reference's 5x join deadline rationale).
    PRIO_JOIN = 0   # PreJoin / Join: small, latency-sensitive
    PRIO_FRAME = 1  # other inbound frames, save/warm, the pump
    PRIO_PUMP = 1
    _PRIO_SENTINEL = 3

    def _put_task(self, fn: Optional[Callable[[], None]], prio: int,
                  label: str = "task") -> None:
        item = None if fn is None else (fn, label)
        self._tasks.put((prio, next(self._task_seq), item))

    def _run_task(self, fn: Callable[[], None], label: str) -> None:
        """Execute one protocol task with per-class wall-time accounting.
        The gateway's single protocol thread is its scarcest resource
        (SharedResources.java:53's model); when something starves, the
        stats say WHICH task class ate the thread instead of leaving it to
        archaeology."""
        start = time.monotonic()
        try:
            fn()
        except Exception:  # noqa: BLE001 -- the loop must survive
            LOG.exception("gateway protocol task failed (%s)", label)
        finally:
            elapsed = time.monotonic() - start
            stats = self._task_stats.setdefault(label, [0, 0.0, 0.0])
            stats[0] += 1
            stats[1] += elapsed
            stats[2] = max(stats[2], elapsed)
            if elapsed > 1.0:
                LOG.warning(
                    "slow protocol task %s: %.2fs (joiners' phase-1 "
                    "deadline is %dms)", label, elapsed,
                    self._settings.join_message_timeout_ms,
                )

    def task_stats(self) -> Dict[str, Tuple[int, float, float]]:
        """{label: (count, total_s, max_s)} for the protocol thread."""
        return {k: tuple(v) for k, v in self._task_stats.items()}

    # ------------------------------------------------------------------ #
    # public surface
    # ------------------------------------------------------------------ #

    def seed_endpoint(self, slot: int = 0) -> Endpoint:
        return self.bridge.endpoint(slot)

    def decisions(self) -> List[object]:
        with self._decision_lock:
            return list(self._decisions)

    def configuration_id(self) -> int:
        return self.bridge.sim.configuration_id()

    def membership_size(self) -> int:
        return self.bridge.sim.membership_size

    def save(self, path: str, timeout: float = 30.0) -> None:
        """Checkpoint the swarm (configuration + real-member plane) from the
        protocol thread, so the snapshot is consistent with in-flight
        handling. A new gateway started with ``restore_from=path`` resumes
        the same configuration id; live agents reconnect transparently."""
        done = threading.Event()
        error: list = []

        def task() -> None:
            try:
                self.bridge.save(path)
            except Exception as e:  # noqa: BLE001
                error.append(e)
            finally:
                done.set()

        self._put_task(task, self.PRIO_FRAME, "save")
        if not done.wait(timeout):
            raise TimeoutError("gateway snapshot did not complete")
        if error:
            raise error[0]

    def warm(self, timeout: float = 600.0) -> None:
        """Compile-warm the swarm engine (one no-fault decision probe on the
        protocol thread). Call between start() and advertising the seed:
        at large capacities the first jit compile can exceed a joining
        agent's retry budget, so agents should find a warmed swarm."""
        done = threading.Event()
        error: list = []

        def task() -> None:
            try:
                # probe variants, the decision path, and the classic
                # fallback -- everything the pump can hit once agents exist
                # (a cold 10k-capacity compile mid-join-wave starves every
                # joiner past its phase-1 retry budget)
                self.bridge.warm_compile()
            except Exception as e:  # noqa: BLE001
                error.append(e)
            finally:
                done.set()

        self._put_task(task, self.PRIO_FRAME, "warm")
        if not done.wait(timeout):
            raise TimeoutError("gateway warm-up did not complete")
        if error:
            raise error[0]

    def start(self) -> None:
        self._running = True
        threads = [
            (self._protocol_loop, "gateway-protocol"),
            (self._pump_loop, "gateway-pump"),
        ]
        if self._native_server:
            from ..runtime.native_io import NativeReactor

            self._reactor = NativeReactor(
                self.address.hostname.decode(), self.address.port
            )
            threads.append((self._native_dispatch_loop, "gateway-reactor"))
        else:
            self._framed.start()
        for target, name in threads:
            t = threading.Thread(target=target, name=name, daemon=True)  # noqa: messaging-thread
            t.start()
            self._threads.append(t)

    def _native_dispatch_loop(self) -> None:
        from ..runtime.native_io import EV_FRAME, EV_SHUTDOWN

        reactor = self._reactor
        while self._running:
            ev, conn_id, payload = reactor.poll(timeout_ms=500)
            if ev == EV_SHUTDOWN:
                return
            if ev == EV_FRAME:
                self._on_native_frame(conn_id, payload)  # decode guarded inside

    def shutdown(self) -> None:
        self._running = False
        if self._reactor is not None:
            self._reactor.shutdown()
        if self._framed is not None:
            self._framed.shutdown()
        self._put_task(None, self._PRIO_SENTINEL)
        self.network.shutdown()
        for pool in self._writers:
            pool.shutdown(wait=False)
        self._out.shutdown()
        self._scheduler.shutdown()

    # ------------------------------------------------------------------ #
    # protocol serialization
    # ------------------------------------------------------------------ #

    def _protocol_loop(self) -> None:
        while self._running:
            _, _, item = self._tasks.get()
            if item is None:
                return
            self._run_task(*item)

    def _drain_for(self, seconds: float) -> None:
        """Process queued tasks for a wall-clock window (bridge clock advance;
        runs ON the protocol thread, so serialization is preserved)."""
        deadline = time.monotonic() + seconds
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            try:
                _, _, item = self._tasks.get(timeout=remaining)
            except queue.Empty:
                return
            if item is None:
                # re-post the shutdown sentinel
                self._put_task(None, self._PRIO_SENTINEL)
                return
            self._run_task(*item)

    def _pump_loop(self) -> None:
        pending = threading.Event()

        def pump() -> None:
            try:
                rec = self.bridge.pump(max_rounds=self._pump_max_rounds)
                if rec is not None:
                    with self._decision_lock:
                        self._decisions.append(rec)
            finally:
                pending.clear()

        while self._running:
            time.sleep(self._pump_interval_s)
            if not self._running:
                return
            if not pending.is_set():
                pending.set()
                self._put_task(pump, self.PRIO_PUMP, "pump")

    # ------------------------------------------------------------------ #
    # inbound routed connections
    # ------------------------------------------------------------------ #

    def _on_frame(self, sock: socket.socket, write_lock: threading.Lock,
                  frame: bytes) -> None:
        # reply writes are offloaded to writer lanes keyed by connection: a
        # slow-reading agent fills its socket buffer, and a synchronous
        # write would block whichever thread replies (the protocol thread,
        # for parked join responses) on that one agent's backpressure
        def reply_send(data: bytes) -> None:
            def write() -> None:
                try:
                    with write_lock:
                        _write_frame(sock, data)
                except OSError:
                    pass

            fd = sock.fileno()
            if fd < 0:
                return  # socket already closed; nothing to reply to
            self._writers[fd % len(self._writers)].submit(write)

        self._enqueue_routed(reply_send, frame)

    def _on_native_frame(self, conn_id: int, frame: bytes) -> None:
        reactor = self._reactor

        def reply_send(data: bytes) -> None:
            if reactor is not None:
                reactor.send(conn_id, data)

        self._enqueue_routed(reply_send, frame)

    def _enqueue_routed(self, reply_send, frame: bytes) -> None:
        try:
            request_no, dst, msg = decode_routed(frame)
        except Exception:  # noqa: BLE001 -- a bad frame must not kill either
            LOG.warning("undecodable routed frame dropped")  # front door
            return
        if isinstance(msg, ProbeMessage) and dst != SWARM_BROADCAST:
            # Probe fast path ON THE READER THREAD, never the protocol
            # queue: at swarm scale the FD probe volume is the dominant
            # frame class (every real member probes K virtual subjects per
            # FD interval), and grinding it through the protocol thread
            # starves joins behind it. The reference answers probes outside
            # the protocol path too (GrpcServer.java:83-96 replies before
            # the service is even wired). The racy reads (slot map, sim
            # liveness arrays) are safe: CPython dict/numpy-scalar reads
            # are atomic, and a probe seeing a one-pump-stale liveness bit
            # is indistinguishable from probe-in-flight timing.
            self._answer_probe(reply_send, request_no, dst)
            return
        prio = (
            self.PRIO_JOIN
            if isinstance(msg, (PreJoinMessage, JoinMessage))
            else self.PRIO_FRAME
        )
        self._put_task(
            lambda rs=reply_send, rn=request_no, d=dst, m=msg: self._handle_one(
                rs, rn, d, m
            ),
            prio,
            f"frame:{type(msg).__name__}",
        )

    def _warn_unowned_once(self, dst: Endpoint) -> bool:
        """True exactly once per unowned endpoint. The probe fast path warns
        from the reader thread while routed frames warn from the protocol
        thread, so the warn-once set needs its own guard."""
        with self._warned_lock:
            if dst in self._warned_unowned:
                return False
            self._warned_unowned.add(dst)
            return True

    def _answer_probe(self, reply_send, request_no: int, dst: Endpoint) -> None:
        slot = self.bridge._slot_of.get(dst)  # noqa: SLF001
        if slot is None or dst in self.bridge._real:  # noqa: SLF001
            # not a virtual endpoint; the sender's deadline handles it --
            # but keep the warn-once misroute diagnostic (probes are the
            # dominant peer traffic; silently eating them would turn a
            # missing --direct-host into an undiagnosed cut cascade)
            if self._warn_unowned_once(dst):
                LOG.warning(
                    "routed probe for non-virtual endpoint %s dropped; if "
                    "this is a real agent's address, its peers need it in "
                    "their direct-host set",
                    dst,
                )
            return
        sim = self.bridge.sim
        if bool(sim.active[slot]) and bool(sim.alive[slot]):
            reply_send(encode(request_no, ProbeResponse()))
        # a dead virtual node sends no response, like a dead process

    def _handle_one(
        self,
        reply_send,  # Callable[[bytes], None]: framed write to the requester
        request_no: int,
        dst: Endpoint,
        msg: RapidMessage,
    ) -> None:
        if dst == SWARM_BROADCAST:
            # one frame standing for a broadcast to every virtual member
            # (GatewaySwarmBroadcaster); ingested exactly once
            try:
                promise = self.bridge.handle_broadcast(msg)
            except Exception:  # noqa: BLE001
                LOG.exception("handle_broadcast failed")
                return
            self._attach_reply(reply_send, request_no, promise)
            return
        if not self.bridge.owns(dst):
            # a real member's address, or an unknown endpoint: there is no
            # virtual node here; the sender's deadline handles it. Warn once
            # per endpoint -- a steady stream of these means an agent is
            # misrouting peer traffic here (missing --direct-host)
            if self._warn_unowned_once(dst):
                LOG.warning(
                    "routed frame for non-virtual endpoint %s dropped; if this "
                    "is a real agent's address, its peers need it in their "
                    "direct-host set",
                    dst,
                )
            return
        try:
            promise = self.bridge.handle(dst, msg)
        except Exception:  # noqa: BLE001
            LOG.exception("bridge.handle failed for %s", dst)
            return
        self._attach_reply(reply_send, request_no, promise)

    @staticmethod
    def _attach_reply(reply_send, request_no: int, promise: Promise) -> None:
        def reply(p: Promise) -> None:
            if p.exception() is not None:
                return  # no response; the sender's deadline expires
            response = p._result  # noqa: SLF001
            if response is None:
                return
            reply_send(encode(request_no, response))

        promise.add_callback(reply)
