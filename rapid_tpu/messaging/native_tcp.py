"""TCP transport with a native epoll server half.

Same seam, same wire format, same client half as ``TcpClientServer`` --
only the server's socket mechanics move to native code: the C++ reactor
(native/rapid_io.cpp via runtime.native_io) multiplexes all accepted
connections on one epoll thread, where the Python server spends a blocking
reader thread per connection. This mirrors how the reference stacks its
transport on a shared native-adjacent event loop (Netty's NIO group,
SharedResources.java:63-67) rather than on JDK blocking sockets.

Interoperability is total: the frame format is codec's u32-length prefix,
so ``NativeTcpClientServer`` servers talk to ``TcpClientServer`` clients
and vice versa; the two are drop-in replacements for each other anywhere
an ``IMessagingServer`` is expected (Cluster, the standalone agent, the
multi-process harness).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..runtime.futures import Promise
from ..runtime.native_io import EV_CLOSED, EV_FRAME, EV_SHUTDOWN, NativeReactor
from ..runtime.native_io import available as native_io_available
from ..settings import Settings
from ..types import Endpoint
from .codec import decode, encode
from .tcp import TcpClientServer

LOG = logging.getLogger(__name__)

__all__ = ["NativeTcpClientServer", "native_io_available"]


class NativeTcpClientServer(TcpClientServer):
    """``TcpClientServer`` with the server half on the native reactor.

    The client half (connection cache, request correlation, retries) is
    inherited unchanged; ``start``/``shutdown`` swap the accept/read
    machinery for the epoll loop, and replies address connections by the
    reactor's ``conn_id`` instead of a socket object.
    """

    def __init__(
        self, listen_address: Endpoint, settings: Optional[Settings] = None
    ) -> None:
        super().__init__(listen_address, settings)
        # the parent's FramedTcpServer stays constructed-but-never-started
        # (no socket until start()); its shutdown() is a safe no-op, so the
        # inherited lifecycle keeps working on this subclass
        self._reactor: Optional[NativeReactor] = None
        self._dispatcher: Optional[threading.Thread] = None
        self._running = False

    # -- server side ---------------------------------------------------------

    def start(self) -> None:
        self._reactor = NativeReactor(
            self.address.hostname.decode(), self.address.port
        )
        if self.address.port == 0:  # ephemeral bind: adopt the real port
            self.address = Endpoint(self.address.hostname, self._reactor.port)
        self._running = True
        self._dispatcher = threading.Thread(  # noqa: messaging-thread
            target=self._dispatch_loop,
            name=f"native-tcp-{self.address}",
            daemon=True,
        )
        self._dispatcher.start()

    def _dispatch_loop(self) -> None:
        reactor = self._reactor
        assert reactor is not None
        while self._running:
            ev, conn_id, payload = reactor.poll(timeout_ms=500)
            if ev == EV_SHUTDOWN:
                return
            if ev == EV_FRAME:
                try:
                    request_no, msg = decode(payload)
                except Exception:  # noqa: BLE001 -- malformed frame: drop it
                    LOG.warning("undecodable frame from conn %d", conn_id)
                    continue
                self._dispatch(msg).add_callback(
                    lambda p, c=conn_id, rn=request_no: self._native_reply(
                        c, rn, p
                    )
                )
            elif ev == EV_CLOSED:
                pass  # request/response transport: no per-conn state to drop

    def _native_reply(self, conn_id: int, request_no: int,
                      promise: Promise) -> None:
        if promise.exception() is not None:
            return  # no response; the caller's deadline handles it
        response = promise._result  # noqa: SLF001
        if response is None:
            return
        reactor = self._reactor
        if reactor is not None:
            reactor.send(conn_id, encode(request_no, response))

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self) -> None:
        self._running = False
        if self._reactor is not None:
            self._reactor.shutdown()
        if self._dispatcher is not None:
            self._dispatcher.join(timeout=2.0)
        self._shutdown_client_half()
