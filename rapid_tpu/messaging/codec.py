"""Binary wire codec for the framed TCP transport.

The reference's alternative transport serializes a WrappedRapidRequest
{long reqNo, RapidRequest} with Java object streams over length-prefixed TCP
frames (NettyClientServer.java:283-303). Here the envelope is
``(request_no: u64, type_tag: u8, msgpack payload)`` inside a u32
length-prefixed frame -- compact, language-neutral, and with explicit type
tags playing the role of the reference's protobuf ``oneof`` envelope
(rapid.proto:21-45).
"""

from __future__ import annotations

import struct
from collections import OrderedDict
from typing import Any, Dict, Tuple, Type

import msgpack

from ..runtime.lockdep import make_lock
from .. import types as T
from ..forensics.hlc import HlcStamp, hlc_of, stamp_hlc
from ..observability import TraceContext, stamp_trace_context, trace_context_of

# Encode memo for LARGE tuples (a 100k-member JoinResponse's endpoint and
# identifier streams): the gateway sends the same configuration content to
# every one of a joiner's K observers and to every joiner of a configuration
# (the bridge reuses the same tuple objects, rapid_tpu/sim/bridge.py
# _full_config_response), so the Python-level _enc walk -- ~1M dict builds
# per copy at 100k -- runs once per content instead of once per send. Keyed
# by object identity with the tuple held strongly, so a hit is always the
# same (immutable) object; bounded FIFO. Bytes on the wire are unchanged.
_ENC_MEMO_MIN = 4096
_ENC_MEMO_CAP = 8
_enc_memo: "OrderedDict[int, Tuple[tuple, list]]" = OrderedDict()
# concurrent encodes are real (gateway delivery lanes + protocol thread,
# exactly the >=4096-element JoinResponse case the memo targets): guard the
# OrderedDict mutations, or one thread's eviction races another's
# move_to_end into a KeyError and corrupts the dict's internal list
_enc_memo_lock = make_lock("codec._enc_memo_lock")

# Inner-message encode memo (the "__msg" field-value form): bounded LRU of
# whole-message _enc results, hit once per peer after the first walk when a
# broadcaster fans one frozen message object out through batch envelopes or
# gossip relays. Identity-checked like the tuple memo; the strong reference
# in each entry keeps the id() stable for the entry's lifetime.
_MSG_MEMO_CAP = 512
_msg_memo: "OrderedDict[int, Tuple[Any, dict]]" = OrderedDict()
_msg_memo_lock = make_lock("codec._msg_memo_lock")

# Decoded-Endpoint intern table: a cluster talks about the same few hundred
# addresses over and over (every alert, vote, and membership row names
# them), so decoding builds each address once and reuses the frozen
# instance. Plain dict on purpose: reads and writes are GIL-atomic, a lost
# race merely constructs a duplicate, and at the cap the table is cleared
# wholesale -- correctness never depends on a hit.
_EP_INTERN_CAP = 4096
_ep_intern: Dict[Tuple[bytes, int], "T.Endpoint"] = {}

# stable wire tags per message type (appending only; never renumber)
_TYPES: Tuple[Type, ...] = (
    T.PreJoinMessage,  # 0
    T.JoinMessage,  # 1
    T.JoinResponse,  # 2
    T.BatchedAlertMessage,  # 3
    T.AlertMessage,  # 4
    T.ProbeMessage,  # 5
    T.ProbeResponse,  # 6
    T.FastRoundPhase2bMessage,  # 7
    T.Phase1aMessage,  # 8
    T.Phase1bMessage,  # 9
    T.Phase2aMessage,  # 10
    T.Phase2bMessage,  # 11
    T.LeaveMessage,  # 12
    T.Response,  # 13
    T.ConsensusResponse,  # 14
    T.GossipEnvelope,  # 15
    T.FastRoundVoteBatch,  # 16
    T.ClusterStatusRequest,  # 17
    T.ClusterStatusResponse,  # 18
    T.HandoffRequest,  # 19
    T.HandoffChunk,  # 20
    T.HandoffAck,  # 21
    T.Get,  # 22
    T.Put,  # 23
    T.PutAck,  # 24
    T.MessageBatch,  # 25
    T.CellDigestMessage,  # 26
    T.GlobalViewMessage,  # 27
)
_TAG_OF = {cls: tag for tag, cls in enumerate(_TYPES)}

HEADER = struct.Struct("!I")  # frame length
ENVELOPE = struct.Struct("!QB")  # request number, type tag


def _enc(obj: Any) -> Any:
    if isinstance(obj, T.Endpoint):
        return {"__ep": [obj.hostname, obj.port]}
    if isinstance(obj, T.NodeId):
        return {"__id": [obj.high, obj.low]}
    if isinstance(obj, T.Rank):
        return {"__rk": [obj.round, obj.node_index]}
    if isinstance(obj, (T.EdgeStatus, T.JoinStatusCode, T.NodeStatus)):
        return {"__en": [type(obj).__name__, int(obj)]}
    if isinstance(obj, tuple):
        if len(obj) < _ENC_MEMO_MIN:
            return [_enc(x) for x in obj]
        with _enc_memo_lock:
            hit = _enc_memo.get(id(obj))
            if hit is not None and hit[0] is obj:
                _enc_memo.move_to_end(id(obj))
                return hit[1]
        enc = [_enc(x) for x in obj]
        with _enc_memo_lock:
            _enc_memo[id(obj)] = (obj, enc)
            while len(_enc_memo) > _ENC_MEMO_CAP:
                _enc_memo.popitem(last=False)
        return enc
    if isinstance(obj, T.AlertMessage):
        # predates the generic "__msg" form; kept for wire stability of
        # BatchedAlertMessage frames across versions
        return {"__al": {k: _enc(v) for k, v in _fields_of(obj).items()}}
    if type(obj) in _TAG_OF:
        # a message carried as a field value (e.g. a GossipEnvelope payload
        # or a MessageBatch inner). A broadcaster fans ONE message object to
        # every peer, and each peer's envelope re-walks it -- with identical
        # output every time, because messages are frozen dataclasses and the
        # inner form never carries trace context. Memoize per object, same
        # identity-checked shape as the tuple memo above.
        with _msg_memo_lock:
            hit = _msg_memo.get(id(obj))
            if hit is not None and hit[0] is obj:
                _msg_memo.move_to_end(id(obj))
                return hit[1]
        enc = {
            "__msg": [
                _TAG_OF[type(obj)],
                {k: _enc(v) for k, v in _fields_of(obj).items()},
            ]
        }
        with _msg_memo_lock:
            _msg_memo[id(obj)] = (obj, enc)
            while len(_msg_memo) > _MSG_MEMO_CAP:
                _msg_memo.popitem(last=False)
        return enc
    if isinstance(obj, dict):
        return {k: _enc(v) for k, v in obj.items()}
    return obj


_ENUMS = {"EdgeStatus": T.EdgeStatus, "JoinStatusCode": T.JoinStatusCode,
          "NodeStatus": T.NodeStatus}


def _dec(obj: Any) -> Any:
    if isinstance(obj, dict):
        if "__ep" in obj:
            host, port = obj["__ep"]
            key = (bytes(host), int(port))
            ep = _ep_intern.get(key)
            if ep is None:
                if len(_ep_intern) >= _EP_INTERN_CAP:
                    _ep_intern.clear()
                ep = T.Endpoint(*key)
                _ep_intern[key] = ep
            return ep
        if "__id" in obj:
            return T.NodeId(*obj["__id"])
        if "__rk" in obj:
            return T.Rank(*obj["__rk"])
        if "__en" in obj:
            name, value = obj["__en"]
            return _ENUMS[name](value)
        if "__al" in obj:
            return T.AlertMessage(**{k: _tupled(_dec(v)) for k, v in obj["__al"].items()})
        if "__msg" in obj:
            tag, fields = obj["__msg"]
            return _TYPES[tag](
                **{k: _tupled(_dec(v)) for k, v in fields.items()}
            )
        return {k: _dec(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_dec(x) for x in obj]
    return obj


def _fields_of(msg: Any) -> Dict[str, Any]:
    return {name: getattr(msg, name) for name in msg.__dataclass_fields__}


def _tupled(value: Any) -> Any:
    """dataclass fields that are tuples on the way in come back as lists."""
    if isinstance(value, list):
        return tuple(_tupled(v) for v in value)
    return value


# Packed-body memo for large messages (the >=64 KB full-configuration
# JoinResponses a swarm bridge streams to every joiner): the body depends
# only on the message object, not the request number, and the bridge reuses
# one response object per (configuration, sender) -- so msgpack runs once
# per object instead of once per send. Same identity-keyed, lock-guarded
# shape as the _enc memo above.
_BODY_MEMO_MIN = 65536
_BODY_MEMO_CAP = 32
_BODY_MEMO_BYTES = 64 * 1024 * 1024  # pinned bodies are MBs at 100k scale
_body_memo: "OrderedDict[int, Tuple[Any, bytes]]" = OrderedDict()
_body_memo_bytes = 0
_body_memo_lock = make_lock("codec._body_memo_lock")


def encode(request_no: int, msg: Any) -> bytes:
    tag = _TAG_OF[type(msg)]
    with _body_memo_lock:
        hit = _body_memo.get(id(msg))
        if hit is not None and hit[0] is msg:
            # a memo hit reuses the body packed at first encode, including
            # whatever trace context was stamped then -- restamping a
            # memoized >=64 KB message later does not change wire bytes
            # (acceptable: only huge JoinResponses reach this path, and
            # their trace context is set before the first send)
            _body_memo.move_to_end(id(msg))
            return ENVELOPE.pack(request_no, tag) + hit[1]
    payload = {k: _enc(v) for k, v in _fields_of(msg).items()}
    ctx = trace_context_of(msg)
    if ctx is not None:
        # reserved key, never a dataclass field name; decoders strip every
        # "__"-prefixed top-level key, so peers that don't know this one
        # (or future reserved keys) parse the frame unchanged
        payload["__tc"] = ctx.to_wire()
    hlc = hlc_of(msg)
    if hlc is not None:
        # same reserved-key discipline as "__tc": absent unless the
        # forensics plane stamped the message, so with the kill switch off
        # the frame is byte-identical to the pre-forensics encoding
        payload["__hlc"] = hlc.to_wire()
    body = msgpack.packb(payload, use_bin_type=True)
    if len(body) >= _BODY_MEMO_MIN:
        global _body_memo_bytes
        with _body_memo_lock:
            # two threads can race to pack the same message: the insert
            # replaces the loser's entry, so its bytes must come off the
            # budget inside the same critical section or the accounting
            # drifts up and evicts live entries early
            prior = _body_memo.get(id(msg))
            if prior is not None:
                _body_memo_bytes -= len(prior[1])
            _body_memo[id(msg)] = (msg, body)
            _body_memo_bytes += len(body)
            # count AND bytes caps: the memo strongly pins message objects
            # and their packed bodies, and at 100k capacity each is several
            # MB -- without a bytes budget, stale configurations' responses
            # would stay resident for the life of the process
            while len(_body_memo) > _BODY_MEMO_CAP or (
                _body_memo_bytes > _BODY_MEMO_BYTES and len(_body_memo) > 1
            ):
                _, (_, old) = _body_memo.popitem(last=False)
                _body_memo_bytes -= len(old)
    return ENVELOPE.pack(request_no, tag) + body


# The wire dialect this codec natively speaks. Rolling upgrades are modeled
# relative to it (faults.py WireVersionRule): a NEWER dialect adds reserved
# "__"-prefixed envelope keys (which every decoder since PR 3 strips) and
# thins optional fields whose value equals the dataclass default (which
# every decoder reconstructs via cls(**kwargs) defaulting); an OLDER dialect
# (< 1) predates the "__tc" trace-context extension and omits it.
WIRE_VERSION = 1


def encode_versioned(request_no: int, msg: Any, version: int) -> bytes:
    """Encode ``msg`` as a peer speaking wire dialect ``version`` would.

    ``version == WIRE_VERSION`` matches :func:`encode` byte-for-byte (minus
    the large-body memo). The bytes differ across versions; the decoded
    message must not -- that invariant is what rolling-upgrade replay pins.
    """
    import dataclasses as _dc

    tag = _TAG_OF[type(msg)]
    fields = _fields_of(msg)
    if version > WIRE_VERSION:
        payload = {}
        defaults = {
            f.name: f.default for f in _dc.fields(msg)
            if f.default is not _dc.MISSING
        }
        for name, value in fields.items():
            # stripped optional tags: a newer encoder omits what the decoder
            # reconstructs (dataclass defaults), shrinking its frames
            if name in defaults and value == defaults[name]:
                continue
            payload[name] = _enc(value)
        # extra reserved fields a current decoder has never seen; the
        # "__"-stripping rule must make them invisible
        payload[f"__v{version}"] = version
        payload[f"__v{version}_ext"] = {"reserved": [version, "future"]}
    else:
        payload = {k: _enc(v) for k, v in fields.items()}
    ctx = trace_context_of(msg)
    if ctx is not None and version >= 1:
        payload["__tc"] = ctx.to_wire()
    hlc = hlc_of(msg)
    if hlc is not None and version >= 1:
        payload["__hlc"] = hlc.to_wire()
    body = msgpack.packb(payload, use_bin_type=True)
    return ENVELOPE.pack(request_no, tag) + body


def wire_roundtrip(msg: Any, version: int) -> Any:
    """``msg`` as a ``version``-speaking peer would put it on the wire and a
    current peer would read it back. Equality with the original (modulo a
    dropped trace context below version 1) is the forward/backward-compat
    contract the rolling-upgrade nemesis replays on live traffic."""
    _, out = decode(encode_versioned(0, msg, version))
    return out


def decode(frame: bytes) -> Tuple[int, Any]:
    request_no, tag = ENVELOPE.unpack_from(frame)
    cls = _TYPES[tag]
    raw = msgpack.unpackb(frame[ENVELOPE.size :], raw=False)
    # "__"-prefixed top-level keys are envelope extensions (today: "__tc"
    # trace context and "__hlc" hybrid-logical-clock stamps), not dataclass
    # fields -- strip them all so frames from newer peers always construct
    # cleanly
    tc = raw.pop("__tc", None)
    hlc = raw.pop("__hlc", None)
    kwargs = {
        name: _tupled(_dec(value))
        for name, value in raw.items()
        if not name.startswith("__")
    }
    msg = cls(**kwargs)
    if tc is not None:
        stamp_trace_context(msg, TraceContext.from_wire(tc))
    if hlc is not None:
        stamp = HlcStamp.from_wire(hlc)
        if stamp is not None:
            stamp_hlc(msg, stamp)
    return request_no, msg
