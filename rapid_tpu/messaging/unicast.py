"""Default broadcaster: best-effort unicast to every member.

Reference: UnicastToAllBroadcaster.java:46-63. Recipients are shuffled once per
configuration so the send order differs across nodes and spreads load.
"""

from __future__ import annotations

import random
from typing import List, Optional

from ..runtime.futures import Promise
from ..types import Endpoint, RapidMessage
from .base import IBroadcaster, IMessagingClient


class UnicastToAllBroadcaster(IBroadcaster):
    def __init__(self, client: IMessagingClient, rng: Optional[random.Random] = None) -> None:
        self._client = client
        self._recipients: List[Endpoint] = []
        self._rng = rng if rng is not None else random.Random()

    def broadcast(self, msg: RapidMessage) -> List[Promise]:
        return [
            self._client.send_message_best_effort(recipient, msg)
            for recipient in self._recipients
        ]

    def set_membership(self, recipients: List[Endpoint]) -> None:
        shuffled = list(recipients)
        self._rng.shuffle(shuffled)
        self._recipients = shuffled
