"""Default broadcaster: best-effort unicast to every member.

Reference: UnicastToAllBroadcaster.java:46-63. Recipients are shuffled once per
configuration so the send order differs across nodes and spreads load.
"""

from __future__ import annotations

import random
from typing import List, Optional

from ..observability import (
    current_trace_context,
    stamp_trace_context,
    trace_context_of,
)
from ..runtime.futures import Promise
from ..types import Endpoint, RapidMessage
from .base import IBroadcaster, IMessagingClient


class UnicastToAllBroadcaster(IBroadcaster):
    def __init__(self, client: IMessagingClient, rng: Optional[random.Random] = None) -> None:
        self._client = client
        self._recipients: List[Endpoint] = []  # guarded-by: protocol-executor
        self._rng = rng if rng is not None else random.Random()

    def broadcast(self, msg: RapidMessage) -> List[Promise]:
        # trace injection at the send seam: keep an explicit stamp (the
        # service's churn context), else inherit the ambient span (e.g. a
        # consensus vote broadcast from inside an alert_batch span). One
        # stamp serves every recipient -- the same object fans out.
        if trace_context_of(msg) is None:
            stamp_trace_context(msg, current_trace_context())
        return [
            self._client.send_message_best_effort(recipient, msg)
            for recipient in self._recipients
        ]

    def set_membership(self, recipients: List[Endpoint]) -> None:
        shuffled = list(recipients)
        self._rng.shuffle(shuffled)
        self._recipients = shuffled
