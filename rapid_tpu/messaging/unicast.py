"""Default broadcaster: best-effort unicast to every member.

Reference: UnicastToAllBroadcaster.java:46-63. Recipients are shuffled once per
configuration so the send order differs across nodes and spreads load.

With ``Settings.broadcast_flush_window_ms > 0`` the broadcaster coalesces:
per-recipient sends accumulate in a ``BatchingSink`` for one flush window and
leave as a single ``MessageBatch`` envelope per peer -- a churn wave's alerts
and votes ride one frame per peer instead of one each. The default window of
0 preserves the legacy send-per-message path (and exact virtual-time timing).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from ..observability import (
    current_trace_context,
    stamp_trace_context,
    trace_context_of,
)
from ..runtime.futures import Promise
from ..runtime.lockdep import make_lock
from ..settings import Settings
from ..types import Endpoint, MessageBatch, RapidMessage
from .base import IBroadcaster, IMessagingClient


class BatchingSink:
    """Per-peer flush-window coalescer shared by the broadcasters: ``offer``
    queues one message for one recipient; the first offer of a quiet window
    schedules a flush ``window_ms`` later on the caller's scheduler (virtual
    or wall clock), and the flush sends each peer's accumulated messages as
    one ``MessageBatch`` envelope (or the bare message when only one
    accumulated -- an unbatched peer sees no format change on light
    traffic). Batched sends are fire-and-forget: the transport promises are
    dropped, exactly like the legacy best-effort broadcast promises."""

    def __init__(
        self,
        client: IMessagingClient,
        my_addr: Endpoint,
        scheduler,
        window_ms: int,
    ) -> None:
        self._client = client
        self._my_addr = my_addr
        self._scheduler = scheduler
        self._window_ms = window_ms
        self._lock = make_lock("BatchingSink._lock")
        self._pending: Dict[Endpoint, List[RapidMessage]] = {}  # guarded-by: _lock
        self._flush_scheduled = False  # guarded-by: _lock

    def offer(self, recipient: Endpoint, msg: RapidMessage) -> None:
        with self._lock:
            self._pending.setdefault(recipient, []).append(msg)
            schedule = not self._flush_scheduled
            if schedule:
                self._flush_scheduled = True
        if schedule:
            self._scheduler.schedule(self._window_ms, self.flush)

    def flush(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, {}
            self._flush_scheduled = False
        metrics = getattr(self._client, "metrics", None)
        for recipient, msgs in pending.items():
            if len(msgs) == 1:
                self._client.send_message_best_effort(recipient, msgs[0])
                continue
            batch = MessageBatch(sender=self._my_addr, messages=tuple(msgs))
            # the native codec carries only the TOP-LEVEL trace context, so
            # the envelope wears the first inner stamp; the receiver
            # re-stamps any inner that lost its own (service.py)
            ctx = next(
                (c for c in map(trace_context_of, msgs) if c is not None),
                None,
            )
            if ctx is not None:
                stamp_trace_context(batch, ctx)
            if metrics is not None:
                metrics.incr("msg.batches_sent")
                metrics.incr("msg.batched_messages", len(msgs))
            self._client.send_message_best_effort(recipient, batch)


def make_batching_sink(
    client: IMessagingClient,
    my_addr: Optional[Endpoint],
    scheduler,
    settings: Optional[Settings],
) -> Optional[BatchingSink]:
    """A sink iff batching is configured AND the caller supplied the pieces
    it needs (address for the envelope sender, scheduler for the window)."""
    if (
        settings is None
        or settings.broadcast_flush_window_ms <= 0
        or scheduler is None
        or my_addr is None
    ):
        return None
    return BatchingSink(
        client, my_addr, scheduler, settings.broadcast_flush_window_ms
    )


class UnicastToAllBroadcaster(IBroadcaster):
    def __init__(
        self,
        client: IMessagingClient,
        rng: Optional[random.Random] = None,
        settings: Optional[Settings] = None,
        scheduler=None,
        my_addr: Optional[Endpoint] = None,
    ) -> None:
        self._client = client
        self._recipients: List[Endpoint] = []  # guarded-by: protocol-executor
        self._rng = rng if rng is not None else random.Random()
        self._sink = make_batching_sink(client, my_addr, scheduler, settings)

    def broadcast(self, msg: RapidMessage) -> List[Promise]:
        # trace injection at the send seam: keep an explicit stamp (the
        # service's churn context), else inherit the ambient span (e.g. a
        # consensus vote broadcast from inside an alert_batch span). One
        # stamp serves every recipient -- the same object fans out.
        if trace_context_of(msg) is None:
            stamp_trace_context(msg, current_trace_context())
        if self._sink is not None:
            for recipient in self._recipients:
                self._sink.offer(recipient, msg)
            return []  # fire-and-forget; flushed after the window
        return [
            self._client.send_message_best_effort(recipient, msg)
            for recipient in self._recipients
        ]

    def set_membership(self, recipients: List[Endpoint]) -> None:
        shuffled = list(recipients)
        self._rng.shuffle(shuffled)
        self._recipients = shuffled
