"""Gossip (epidemic) broadcaster: the IBroadcaster alternative the
reference anticipates but never ships.

``IBroadcaster.java:24-26`` names "gossip-based dissemination" as the
intended alternative to unicast-to-all; this is that implementation for the
native-codec transports. ``broadcast`` wraps the message in a
``GossipEnvelope`` (fresh 128-bit id, TTL ~ log2(N) + margin) and sends it
to the origin itself plus ``fanout`` random members; receivers relay with
TTL-1 and deliver the payload locally exactly once, deduping by envelope
id. Relaying uses blind-counter rumor mongering: a node relays an envelope
on each of its first ``relay_budget`` sightings (not only the first), which
lifts per-node delivery probability from ~1-e^-fanout to
~1-e^-(fanout*relay_budget) for a few extra relays. Per-broadcast cost at
the origin drops from O(N) sends to O(fanout), traded for
O(N*fanout*relay_budget) total relay traffic spread across the membership
-- the standard epidemic trade. The reference's own evaluation keeps
unicast-to-all, so parity defaults stay unchanged; this is opt-in via
``ClusterBuilder.set_broadcaster_factory``.

Delivery is probabilistic-complete, and the membership protocol tolerates
residual loss by design (the cut detector aggregates K independent
observers; consensus needs 3/4, not all, votes); the convergence tests
drive full cut/join cycles over this broadcaster to pin that end-to-end.
"""

from __future__ import annotations

import math
import random
from collections import OrderedDict
from typing import List, Optional, Tuple

from ..runtime.futures import Promise
from ..types import Endpoint, GossipEnvelope, NodeId, RapidMessage
from .base import IBroadcaster, IMessagingClient

_SEEN_CAP = 8192  # bounded dedup memory; ids are per-broadcast random


class GossipBroadcaster(IBroadcaster):
    def __init__(
        self,
        client: IMessagingClient,
        my_addr: Endpoint,
        fanout: int = 4,
        relay_budget: int = 2,
        ttl: Optional[int] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        self._client = client
        self._my_addr = my_addr
        self._fanout = fanout
        self._relay_budget = relay_budget
        self._ttl_override = ttl
        self._rng = rng if rng is not None else random.Random()
        self._members: List[Endpoint] = []
        self._others: List[Endpoint] = []  # cached non-self peer pool
        # envelope id -> sightings so far (blind-counter rumor mongering)
        self._seen: "OrderedDict[Tuple[int, int], int]" = OrderedDict()

    # -- IBroadcaster --------------------------------------------------------

    def set_membership(self, recipients: List[Endpoint]) -> None:
        self._members = list(recipients)
        # membership changes only at view changes; relays are per-message --
        # cache the non-self peer pool so each send is O(fanout), not O(N)
        self._others = [m for m in self._members if m != self._my_addr]

    def broadcast(self, msg: RapidMessage) -> List[Promise]:
        """Send to self + ``fanout`` random members; relays do the rest. The
        origin's own copy arrives through the transport like everyone
        else's (UnicastToAllBroadcaster's self-delivery semantics)."""
        env = GossipEnvelope(
            sender=self._my_addr,
            gossip_id=NodeId(
                self._rng.getrandbits(64) - (1 << 63),
                self._rng.getrandbits(64) - (1 << 63),
            ),
            ttl=self._ttl(),
            payload=msg,
        )
        return self._send(env, include_self=True)

    # -- relay plane ---------------------------------------------------------

    def receive(self, env: GossipEnvelope) -> Optional[RapidMessage]:
        """Called by the membership service for every inbound envelope.
        Relays on each of the first ``relay_budget`` sightings (TTL-1 to
        ``fanout`` random members); returns the payload for local delivery
        on the FIRST sighting only, None afterwards."""
        key = (env.gossip_id.high, env.gossip_id.low)
        sightings = self._seen.get(key, 0)
        self._seen[key] = sightings + 1
        while len(self._seen) > _SEEN_CAP:
            self._seen.popitem(last=False)
        if sightings < self._relay_budget and env.ttl > 0:
            relay = GossipEnvelope(
                sender=self._my_addr,
                gossip_id=env.gossip_id,
                ttl=env.ttl - 1,
                payload=env.payload,
            )
            self._send(relay, include_self=False)
        return env.payload if sightings == 0 else None

    # -- internals -----------------------------------------------------------

    def _ttl(self) -> int:
        if self._ttl_override is not None:
            return self._ttl_override
        n = max(len(self._members), 2)
        return int(math.ceil(math.log2(n))) + 2

    def _peers(self) -> List[Endpoint]:
        if len(self._others) <= self._fanout:
            return self._others
        return self._rng.sample(self._others, self._fanout)

    def _send(self, env: GossipEnvelope, include_self: bool) -> List[Promise]:
        targets = self._peers()
        if include_self:
            targets = [self._my_addr] + targets
        return [
            self._client.send_message_best_effort(t, env) for t in targets
        ]
