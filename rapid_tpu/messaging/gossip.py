"""Gossip (epidemic) broadcaster: the IBroadcaster alternative the
reference anticipates but never ships.

``IBroadcaster.java:24-26`` names "gossip-based dissemination" as the
intended alternative to unicast-to-all; this is that implementation for the
native-codec transports. ``broadcast`` wraps the message in a
``GossipEnvelope`` (fresh 128-bit id, TTL ~ log2(N) + margin) and sends it
to the origin itself plus ``fanout`` random members; receivers relay with
TTL-1 and deliver the payload locally exactly once, deduping by envelope
id. Two relay disciplines:

- ``mode="eager"`` (default): blind-counter rumor mongering -- a node
  relays the full envelope on each of its first ``relay_budget`` sightings
  (not only the first), which lifts per-node delivery probability from
  ~1-e^-fanout to ~1-e^-(fanout*relay_budget) for a few extra relays, at
  ~fanout*relay_budget duplicate payload receptions per node.
- ``mode="pushpull"`` (anti-entropy): the full payload is relayed eagerly
  only on the FIRST sighting; later sightings (up to ``relay_budget``) send
  a tiny IHAVE advertisement instead. A node that sees an IHAVE for an id
  it has not received PULLs the payload from the advertiser, which answers
  from its recent-envelope store. Payload redundancy drops toward ~fanout
  receptions per node while the IHAVE/PULL legs recover the reliability the
  withheld duplicates provided -- the classic push-pull epidemic repair
  (the lazy-push/graft shape of Plumtree). Measured by
  experiments/message_load.py (table in BASELINE.md).

Per-broadcast cost at the origin drops from O(N) sends to O(fanout), traded
for relay traffic spread across the membership -- the standard epidemic
trade. The reference's own evaluation keeps unicast-to-all, so parity
defaults stay unchanged; this is opt-in via
``ClusterBuilder.set_broadcaster_factory``.

Delivery is probabilistic-complete, and the membership protocol tolerates
residual loss by design (the cut detector aggregates K independent
observers; consensus needs 3/4, not all, votes); the convergence tests
drive full cut/join cycles over both modes to pin that end-to-end.
"""

from __future__ import annotations

import math
import random
import time
from collections import OrderedDict, deque
from typing import List, Optional, Tuple

from ..observability import (
    current_trace_context,
    stamp_trace_context,
    trace_context_of,
)
from ..runtime.futures import Promise
from ..settings import Settings
from ..types import Endpoint, GossipEnvelope, NodeId, RapidMessage
from .base import IBroadcaster, IMessagingClient
from .unicast import make_batching_sink

# Dedup memory is bounded by BOTH a size floor and an age floor: an entry is
# only evicted once the table exceeds the cap AND the entry is older than
# _SEEN_MIN_AGE_S (a generous bound on how long an envelope can still be
# circulating: TTL relay hops at network latency). Evicting a still-live
# envelope would make it look first-seen again -- duplicate local delivery
# plus a fresh relay budget (traffic amplification). Under sustained load the
# table therefore grows to (broadcast rate x age window), the correct bound,
# instead of silently re-admitting live envelopes. The cap also scales with
# membership so big clusters (more concurrent broadcasts) get more room.
_SEEN_CAP = 8192
_SEEN_MIN_AGE_S = 30.0
_PULL_RETRY_S = 1.0  # re-pull an unanswered id on a fresh IHAVE after this


class GossipBroadcaster(IBroadcaster):
    def __init__(
        self,
        client: IMessagingClient,
        my_addr: Endpoint,
        fanout: int = 4,
        relay_budget: int = 2,
        ttl: Optional[int] = None,
        rng: Optional[random.Random] = None,
        mode: str = "eager",
        settings: Optional[Settings] = None,
        scheduler=None,
    ) -> None:
        assert mode in ("eager", "pushpull"), mode
        self._client = client
        self._my_addr = my_addr
        # flush-window coalescing of outbound envelopes (one MessageBatch
        # per peer per window) when Settings.broadcast_flush_window_ms > 0;
        # None keeps the legacy send-per-envelope path
        self._sink = make_batching_sink(client, my_addr, scheduler, settings)
        self._fanout = fanout
        self._relay_budget = relay_budget
        self._ttl_override = ttl
        self._rng = rng if rng is not None else random.Random()
        self._mode = mode
        self._members: List[Endpoint] = []
        self._others: List[Endpoint] = []  # cached non-self peer pool
        # envelope id -> (sightings so far, first-seen monotonic time,
        # stored relay envelope for answering pulls -- pushpull mode only);
        # insertion order == age order, so eviction pops from the front
        self._seen: "OrderedDict[Tuple[int, int], Tuple[int, float, Optional[GossipEnvelope]]]" = (
            OrderedDict()
        )
        # ids pulled but not yet received (id -> request monotonic time);
        # bounds repeat pulls while an answer is in flight
        self._pending_pulls: dict = {}
        # pushpull payload store keys, oldest first: the age-guarded _seen
        # eviction lets the TABLE grow under sustained load, but full
        # payloads must not grow with it (rate x 30 s of envelopes is a
        # large amplification over the int-per-id table). The hard payload
        # ceiling drops stored envelopes oldest-first (entry payload ->
        # None) while KEEPING the dedup key, so dedup safety is unaffected
        # and pulls for dropped payloads stay best-effort (unanswered, the
        # puller retries against a fresher advertiser).
        # (key, store generation) in store order. A key may appear more than
        # once (stored, nulled, re-stored): the generation stamps which
        # store a deque slot refers to, so only the LIVE generation's slot
        # can evict a payload -- re-seen ids evict oldest-first instead of
        # a stale slot nulling the fresh payload.
        self._payload_keys: "deque[Tuple[Tuple[int, int], int]]" = deque()
        self._payload_gen: dict = {}  # key -> generation of its live payload
        self._gen = 0
        self._stored_payloads = 0  # LIVE stored envelopes

    # -- IBroadcaster --------------------------------------------------------

    def set_membership(self, recipients: List[Endpoint]) -> None:
        self._members = list(recipients)
        # membership changes only at view changes; relays are per-message --
        # cache the non-self peer pool so each send is O(fanout), not O(N)
        self._others = [m for m in self._members if m != self._my_addr]

    def broadcast(self, msg: RapidMessage) -> List[Promise]:
        """Send to self + ``fanout`` random members; relays do the rest. The
        origin's own copy arrives through the transport like everyone
        else's (UnicastToAllBroadcaster's self-delivery semantics)."""
        # trace injection mirrors the unicast broadcaster, but the codec only
        # carries the TOP-LEVEL message's context -- so the wrapping envelope
        # (not just the payload) must wear the stamp to survive serialization
        if trace_context_of(msg) is None:
            stamp_trace_context(msg, current_trace_context())
        env = GossipEnvelope(
            sender=self._my_addr,
            gossip_id=NodeId(
                self._rng.getrandbits(64) - (1 << 63),
                self._rng.getrandbits(64) - (1 << 63),
            ),
            ttl=self._ttl(),
            payload=msg,
        )
        stamp_trace_context(env, trace_context_of(msg))
        return self._send(env, include_self=True)

    # -- relay plane ---------------------------------------------------------

    def receive(self, env: GossipEnvelope) -> Optional[RapidMessage]:
        """Called by the membership service for every inbound envelope.

        PAYLOAD frames: relays on each of the first ``relay_budget``
        sightings (TTL-1 to ``fanout`` random members) -- the full envelope
        every time in eager mode, the full envelope on the first sighting
        and tiny IHAVE advertisements afterwards in pushpull mode; returns
        the payload for local delivery on the FIRST sighting only, None
        afterwards. IHAVE/PULL frames run the anti-entropy repair and never
        deliver locally."""
        if env.kind == GossipEnvelope.KIND_IHAVE:
            self._on_ihave(env)
            return None
        if env.kind == GossipEnvelope.KIND_PULL:
            self._on_pull(env)
            return None
        key = (env.gossip_id.high, env.gossip_id.low)
        now = time.monotonic()
        self._pending_pulls.pop(key, None)
        prior = self._seen.get(key)
        sightings, first_seen = (prior[0], prior[1]) if prior else (0, now)
        # the inbound envelope carried the trace over the wire; put it back on
        # the payload so local delivery sees it, and keep it on every derived
        # envelope (relay, stored pull-answer) so downstream hops inherit it
        ctx = trace_context_of(env)
        if ctx is not None and trace_context_of(env.payload) is None:
            stamp_trace_context(env.payload, ctx)
        relay: Optional[GossipEnvelope] = None
        if sightings < self._relay_budget and env.ttl > 0:
            relay = GossipEnvelope(
                sender=self._my_addr,
                gossip_id=env.gossip_id,
                ttl=env.ttl - 1,
                payload=env.payload,
            )
            stamp_trace_context(relay, ctx)
        # pushpull answers later pulls from this store; eager never pulls
        stored = None
        if self._mode == "pushpull":
            stored = prior[2] if prior else None
            if stored is None:
                if relay is not None:
                    stored = relay
                else:
                    stored = GossipEnvelope(
                        sender=self._my_addr, gossip_id=env.gossip_id, ttl=0,
                        payload=env.payload,
                    )
                    stamp_trace_context(stored, ctx)
        if key in self._seen:  # preserve age order: do not move to the end
            self._seen[key] = (sightings + 1, first_seen, stored)
        else:
            self._seen[key] = (1, first_seen, stored)
        if stored is not None and (prior is None or prior[2] is None):
            self._gen += 1
            self._payload_gen[key] = self._gen
            self._payload_keys.append((key, self._gen))
            self._stored_payloads += 1
        cap = max(_SEEN_CAP, 4 * len(self._members))
        while len(self._seen) > cap:
            _, entry = next(iter(self._seen.items()))
            if now - entry[1] < _SEEN_MIN_AGE_S:
                break  # everything old enough is gone; let the table grow
            evicted_key, evicted = self._seen.popitem(last=False)
            if evicted[2] is not None:
                self._stored_payloads -= 1
                self._payload_gen.pop(evicted_key, None)
        # compact the deque head: slots whose generation is no longer live
        # (entry left _seen via age eviction, or was re-stored under a newer
        # generation) are dead weight -- without this the deque grows without
        # bound under sustained age-based turnover
        while self._payload_keys and (
            self._payload_gen.get(self._payload_keys[0][0])
            != self._payload_keys[0][1]
        ):
            self._payload_keys.popleft()
        # hard payload ceiling, counted over LIVE stored envelopes: only the
        # slot carrying a key's live generation may null its payload, so a
        # re-stored id keeps its fresh payload until its own turn comes up
        # oldest-first
        while self._stored_payloads > cap and self._payload_keys:
            stale_key, gen = self._payload_keys.popleft()
            if self._payload_gen.get(stale_key) != gen:
                continue  # superseded or already evicted
            entry = self._seen.get(stale_key)
            del self._payload_gen[stale_key]
            if entry is not None and entry[2] is not None:
                self._seen[stale_key] = (entry[0], entry[1], None)
                self._stored_payloads -= 1
        if relay is not None:
            if self._mode == "pushpull" and sightings > 0:
                # anti-entropy: advertise instead of re-pushing the payload
                ihave = GossipEnvelope(
                    sender=self._my_addr,
                    gossip_id=env.gossip_id,
                    ttl=env.ttl - 1,
                    kind=GossipEnvelope.KIND_IHAVE,
                )
                self._send(ihave, include_self=False)
            else:
                self._send(relay, include_self=False)
        return env.payload if sightings == 0 else None

    def _on_ihave(self, env: GossipEnvelope) -> None:
        """An advertisement: pull the payload from the advertiser iff the id
        is unseen and no pull is already in flight (re-pull after a timeout,
        so a lost answer is repaired by the next advertisement)."""
        key = (env.gossip_id.high, env.gossip_id.low)
        if key in self._seen:
            return
        now = time.monotonic()
        asked = self._pending_pulls.get(key)
        if asked is not None and now - asked < _PULL_RETRY_S:
            return
        if len(self._pending_pulls) > _SEEN_CAP:
            self._pending_pulls.clear()  # stale flood; repairs re-request
        self._pending_pulls[key] = now
        pull = GossipEnvelope(
            sender=self._my_addr,
            gossip_id=env.gossip_id,
            ttl=0,
            kind=GossipEnvelope.KIND_PULL,
        )
        self._client.send_message_best_effort(env.sender, pull)

    def _on_pull(self, env: GossipEnvelope) -> None:
        """Answer a pull from the recent-envelope store (best effort: an
        evicted or never-stored id is simply not answered; the puller
        retries on the next advertisement)."""
        key = (env.gossip_id.high, env.gossip_id.low)
        entry = self._seen.get(key)
        if entry is None or entry[2] is None:
            return
        self._client.send_message_best_effort(env.sender, entry[2])

    # -- internals -----------------------------------------------------------

    def _ttl(self) -> int:
        if self._ttl_override is not None:
            return self._ttl_override
        n = max(len(self._members), 2)
        return int(math.ceil(math.log2(n))) + 2

    def _peers(self) -> List[Endpoint]:
        if len(self._others) <= self._fanout:
            return self._others
        return self._rng.sample(self._others, self._fanout)

    def _send(self, env: GossipEnvelope, include_self: bool) -> List[Promise]:
        targets = self._peers()
        if include_self:
            targets = [self._my_addr] + targets
        if self._sink is not None:
            for t in targets:
                self._sink.offer(t, env)
            return []  # fire-and-forget; flushed after the window
        return [
            self._client.send_message_best_effort(t, env) for t in targets
        ]
