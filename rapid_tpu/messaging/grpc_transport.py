"""gRPC transport speaking the reference's exact wire format.

The analogue of the default GrpcClient/GrpcServer pair (GrpcClient.java,
GrpcServer.java): one unary RPC ``remoting.MembershipService/sendRequest``
carrying the RapidRequest/RapidResponse ``oneof`` envelopes, so a rapid-tpu
node is byte-compatible on the wire with JVM Rapid peers. Client side keeps a
channel cache with per-message-type deadlines and async retries
(GrpcClient.java:87-131,194-203); server side answers probes BOOTSTRAPPING
until the membership service is wired (GrpcServer.java:77-96).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Dict, Optional

import grpc
import grpc.aio

from ..runtime.lockdep import make_lock
from .. import types as T
from ..forensics.hlc import HlcStamp, hlc_of, stamp_hlc
from ..observability import TraceContext, stamp_trace_context, trace_context_of
from ..runtime.futures import Promise
from ..settings import Settings
from .base import IMessagingClient, IMessagingServer
from .retries import call_with_retries, wall_scheduler
from .wire_schema import GRPC_METHOD_PATH, MSG

LOG = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# dataclass <-> proto conversion
# ---------------------------------------------------------------------------


def _ep(endpoint: T.Endpoint):
    out = MSG["Endpoint"]()
    out.hostname = endpoint.hostname
    out.port = endpoint.port
    return out


def _ep_back(msg) -> T.Endpoint:
    return T.Endpoint(bytes(msg.hostname), int(msg.port))


def _nid(node_id: T.NodeId):
    out = MSG["NodeId"]()
    out.high = node_id.high
    out.low = node_id.low
    return out


def _nid_back(msg) -> T.NodeId:
    return T.NodeId(int(msg.high), int(msg.low))


def _meta(metadata) :
    out = MSG["Metadata"]()
    for key, value in metadata:
        out.metadata[key] = value
    return out


def _meta_back(msg):
    return tuple(sorted((k, bytes(v)) for k, v in msg.metadata.items()))


def _alert(alert: T.AlertMessage):
    out = MSG["AlertMessage"]()
    out.edgeSrc.CopyFrom(_ep(alert.edge_src))
    out.edgeDst.CopyFrom(_ep(alert.edge_dst))
    out.edgeStatus = int(alert.edge_status)
    out.configurationId = alert.configuration_id
    out.ringNumber.extend(alert.ring_numbers)
    if alert.node_id is not None:
        out.nodeId.CopyFrom(_nid(alert.node_id))
    out.metadata.CopyFrom(_meta(alert.metadata))
    return out


def _alert_back(msg) -> T.AlertMessage:
    return T.AlertMessage(
        edge_src=_ep_back(msg.edgeSrc),
        edge_dst=_ep_back(msg.edgeDst),
        edge_status=T.EdgeStatus(msg.edgeStatus),
        configuration_id=int(msg.configurationId),
        ring_numbers=tuple(msg.ringNumber),
        node_id=_nid_back(msg.nodeId) if msg.HasField("nodeId") else None,
        metadata=_meta_back(msg.metadata),
    )


def to_wire_request(msg: T.RapidMessage):
    """Wrap a protocol dataclass into the RapidRequest oneof envelope."""
    req = MSG["RapidRequest"]()
    if isinstance(msg, T.PreJoinMessage):
        req.preJoinMessage.sender.CopyFrom(_ep(msg.sender))
        req.preJoinMessage.nodeId.CopyFrom(_nid(msg.node_id))
    elif isinstance(msg, T.JoinMessage):
        j = req.joinMessage
        j.sender.CopyFrom(_ep(msg.sender))
        j.nodeId.CopyFrom(_nid(msg.node_id))
        j.ringNumber.extend(msg.ring_numbers)
        j.configurationId = msg.configuration_id
        j.metadata.CopyFrom(_meta(msg.metadata))
    elif isinstance(msg, T.BatchedAlertMessage):
        b = req.batchedAlertMessage
        b.sender.CopyFrom(_ep(msg.sender))
        for alert in msg.messages:
            b.messages.append(_alert(alert))
    elif isinstance(msg, T.ProbeMessage):
        req.probeMessage.sender.CopyFrom(_ep(msg.sender))
    elif isinstance(msg, T.FastRoundPhase2bMessage):
        f = req.fastRoundPhase2bMessage
        f.sender.CopyFrom(_ep(msg.sender))
        f.configurationId = msg.configuration_id
        f.endpoints.extend(_ep(e) for e in msg.endpoints)
    elif isinstance(msg, T.Phase1aMessage):
        p = req.phase1aMessage
        p.sender.CopyFrom(_ep(msg.sender))
        p.configurationId = msg.configuration_id
        p.rank.round = msg.rank.round
        p.rank.nodeIndex = msg.rank.node_index
    elif isinstance(msg, T.Phase1bMessage):
        p = req.phase1bMessage
        p.sender.CopyFrom(_ep(msg.sender))
        p.configurationId = msg.configuration_id
        p.rnd.round, p.rnd.nodeIndex = msg.rnd.round, msg.rnd.node_index
        p.vrnd.round, p.vrnd.nodeIndex = msg.vrnd.round, msg.vrnd.node_index
        p.vval.extend(_ep(e) for e in msg.vval)
    elif isinstance(msg, T.Phase2aMessage):
        p = req.phase2aMessage
        p.sender.CopyFrom(_ep(msg.sender))
        p.configurationId = msg.configuration_id
        p.rnd.round, p.rnd.nodeIndex = msg.rnd.round, msg.rnd.node_index
        p.vval.extend(_ep(e) for e in msg.vval)
    elif isinstance(msg, T.Phase2bMessage):
        p = req.phase2bMessage
        p.sender.CopyFrom(_ep(msg.sender))
        p.configurationId = msg.configuration_id
        p.rnd.round, p.rnd.nodeIndex = msg.rnd.round, msg.rnd.node_index
        p.endpoints.extend(_ep(e) for e in msg.endpoints)
    elif isinstance(msg, T.LeaveMessage):
        req.leaveMessage.sender.CopyFrom(_ep(msg.sender))
    elif isinstance(msg, T.ClusterStatusRequest):
        req.clusterStatusRequest.sender.CopyFrom(_ep(msg.sender))
        req.clusterStatusRequest.includeHistory = msg.include_history
    elif isinstance(msg, T.HandoffRequest):
        h = req.handoffRequest
        h.sender.CopyFrom(_ep(msg.sender))
        h.sessionId = msg.session_id
        h.partition = msg.partition
        h.offset = msg.offset
        h.length = msg.length
        h.mapVersion = msg.map_version
    elif isinstance(msg, T.HandoffAck):
        h = req.handoffAck
        h.sender.CopyFrom(_ep(msg.sender))
        h.sessionId = msg.session_id
        h.partition = msg.partition
        h.fingerprint = msg.fingerprint
        h.mapVersion = msg.map_version
    elif isinstance(msg, T.Get):
        g = req.get
        g.sender.CopyFrom(_ep(msg.sender))
        g.key = msg.key
        g.quorum = msg.quorum
        g.mapVersion = msg.map_version
    elif isinstance(msg, T.Put):
        p = req.put
        p.sender.CopyFrom(_ep(msg.sender))
        p.key = msg.key
        p.value = msg.value
        p.requestId = msg.request_id
        p.replicate = msg.replicate
        p.version = msg.version
        p.mapVersion = msg.map_version
    elif isinstance(msg, T.MessageBatch):
        b = req.messageBatch
        b.sender.CopyFrom(_ep(msg.sender))
        # whole envelopes nested: recursion carries each inner request's own
        # oneof discriminator and trace context unchanged
        for inner in msg.messages:
            b.requests.append(to_wire_request(inner))
    elif isinstance(msg, T.CellDigestMessage):
        c = req.cellDigestMessage
        c.sender.CopyFrom(_ep(msg.sender))
        c.cell = msg.cell
        c.configurationId = msg.configuration_id
        c.membershipSize = msg.membership_size
        c.leader = msg.leader
        c.fingerprint = msg.fingerprint
        c.parentRound = msg.parent_round
    elif isinstance(msg, T.GlobalViewMessage):
        g = req.globalViewMessage
        g.sender.CopyFrom(_ep(msg.sender))
        g.parentConfigurationId = msg.parent_configuration_id
        g.globalFingerprint = msg.global_fingerprint
        g.cells.extend(msg.cells)
        g.epochs.extend(msg.epochs)
        g.sizes.extend(msg.sizes)
        g.leaders.extend(msg.leaders)
        g.fingerprints.extend(msg.fingerprints)
        g.parentRound = msg.parent_round
    else:
        raise TypeError(f"not a request type: {type(msg).__name__}")
    ctx = trace_context_of(msg)
    if ctx is not None:
        tc = req.traceCtx
        tc.traceId = ctx.trace_id
        tc.parentSpanId = ctx.parent_span_id
        tc.origin = ctx.origin
        tc.flags = ctx.flags
    stamp = hlc_of(msg)
    if stamp is not None:
        h = req.hlc
        h.physicalMs = stamp.physical_ms
        h.logical = stamp.logical
        h.incarnation = stamp.incarnation
    return req


def from_wire_request(req) -> T.RapidMessage:
    msg = _from_wire_request_content(req)
    if req.HasField("traceCtx"):
        tc = req.traceCtx
        stamp_trace_context(msg, TraceContext(
            trace_id=int(tc.traceId),
            parent_span_id=int(tc.parentSpanId),
            origin=str(tc.origin),
            flags=int(tc.flags),
        ))
    if req.HasField("hlc"):
        h = req.hlc
        stamp_hlc(msg, HlcStamp(
            physical_ms=int(h.physicalMs),
            logical=int(h.logical),
            incarnation=max(1, int(h.incarnation)),
        ))
    return msg


def _from_wire_request_content(req) -> T.RapidMessage:
    which = req.WhichOneof("content")
    if which == "preJoinMessage":
        m = req.preJoinMessage
        return T.PreJoinMessage(sender=_ep_back(m.sender), node_id=_nid_back(m.nodeId))
    if which == "joinMessage":
        m = req.joinMessage
        return T.JoinMessage(
            sender=_ep_back(m.sender),
            node_id=_nid_back(m.nodeId),
            ring_numbers=tuple(m.ringNumber),
            configuration_id=int(m.configurationId),
            metadata=_meta_back(m.metadata),
        )
    if which == "batchedAlertMessage":
        m = req.batchedAlertMessage
        return T.BatchedAlertMessage(
            sender=_ep_back(m.sender),
            messages=tuple(_alert_back(a) for a in m.messages),
        )
    if which == "probeMessage":
        return T.ProbeMessage(sender=_ep_back(req.probeMessage.sender))
    if which == "fastRoundPhase2bMessage":
        m = req.fastRoundPhase2bMessage
        return T.FastRoundPhase2bMessage(
            sender=_ep_back(m.sender),
            configuration_id=int(m.configurationId),
            endpoints=tuple(_ep_back(e) for e in m.endpoints),
        )
    if which == "phase1aMessage":
        m = req.phase1aMessage
        return T.Phase1aMessage(
            sender=_ep_back(m.sender),
            configuration_id=int(m.configurationId),
            rank=T.Rank(int(m.rank.round), int(m.rank.nodeIndex)),
        )
    if which == "phase1bMessage":
        m = req.phase1bMessage
        return T.Phase1bMessage(
            sender=_ep_back(m.sender),
            configuration_id=int(m.configurationId),
            rnd=T.Rank(int(m.rnd.round), int(m.rnd.nodeIndex)),
            vrnd=T.Rank(int(m.vrnd.round), int(m.vrnd.nodeIndex)),
            vval=tuple(_ep_back(e) for e in m.vval),
        )
    if which == "phase2aMessage":
        m = req.phase2aMessage
        return T.Phase2aMessage(
            sender=_ep_back(m.sender),
            configuration_id=int(m.configurationId),
            rnd=T.Rank(int(m.rnd.round), int(m.rnd.nodeIndex)),
            vval=tuple(_ep_back(e) for e in m.vval),
        )
    if which == "phase2bMessage":
        m = req.phase2bMessage
        return T.Phase2bMessage(
            sender=_ep_back(m.sender),
            configuration_id=int(m.configurationId),
            rnd=T.Rank(int(m.rnd.round), int(m.rnd.nodeIndex)),
            endpoints=tuple(_ep_back(e) for e in m.endpoints),
        )
    if which == "leaveMessage":
        return T.LeaveMessage(sender=_ep_back(req.leaveMessage.sender))
    if which == "clusterStatusRequest":
        return T.ClusterStatusRequest(
            sender=_ep_back(req.clusterStatusRequest.sender),
            include_history=int(req.clusterStatusRequest.includeHistory),
        )
    if which == "handoffRequest":
        m = req.handoffRequest
        return T.HandoffRequest(
            sender=_ep_back(m.sender),
            session_id=int(m.sessionId),
            partition=int(m.partition),
            offset=int(m.offset),
            length=int(m.length),
            map_version=int(m.mapVersion),
        )
    if which == "handoffAck":
        m = req.handoffAck
        return T.HandoffAck(
            sender=_ep_back(m.sender),
            session_id=int(m.sessionId),
            partition=int(m.partition),
            fingerprint=int(m.fingerprint),
            map_version=int(m.mapVersion),
        )
    if which == "get":
        m = req.get
        return T.Get(
            sender=_ep_back(m.sender),
            key=bytes(m.key),
            quorum=int(m.quorum),
            map_version=int(m.mapVersion),
        )
    if which == "put":
        m = req.put
        return T.Put(
            sender=_ep_back(m.sender),
            key=bytes(m.key),
            value=bytes(m.value),
            request_id=int(m.requestId),
            replicate=int(m.replicate),
            version=int(m.version),
            map_version=int(m.mapVersion),
        )
    if which == "messageBatch":
        m = req.messageBatch
        return T.MessageBatch(
            sender=_ep_back(m.sender),
            messages=tuple(from_wire_request(r) for r in m.requests),
        )
    if which == "cellDigestMessage":
        m = req.cellDigestMessage
        return T.CellDigestMessage(
            sender=_ep_back(m.sender),
            cell=int(m.cell),
            configuration_id=int(m.configurationId),
            membership_size=int(m.membershipSize),
            leader=str(m.leader),
            fingerprint=int(m.fingerprint),
            parent_round=int(m.parentRound),
        )
    if which == "globalViewMessage":
        m = req.globalViewMessage
        return T.GlobalViewMessage(
            sender=_ep_back(m.sender),
            parent_configuration_id=int(m.parentConfigurationId),
            global_fingerprint=int(m.globalFingerprint),
            cells=tuple(int(c) for c in m.cells),
            epochs=tuple(int(e) for e in m.epochs),
            sizes=tuple(int(s) for s in m.sizes),
            leaders=tuple(str(l) for l in m.leaders),
            fingerprints=tuple(int(f) for f in m.fingerprints),
            parent_round=int(m.parentRound),
        )
    raise ValueError(f"empty RapidRequest envelope: {which}")


def to_wire_response(msg) :
    resp = MSG["RapidResponse"]()
    if isinstance(msg, T.JoinResponse):
        j = resp.joinResponse
        j.sender.CopyFrom(_ep(msg.sender))
        j.statusCode = int(msg.status_code)
        j.configurationId = msg.configuration_id
        j.endpoints.extend(_ep(e) for e in msg.endpoints)
        j.identifiers.extend(_nid(i) for i in msg.identifiers)
        for endpoint, metadata in msg.metadata:
            j.metadataKeys.append(_ep(endpoint))
            j.metadataValues.append(_meta(metadata))
    elif isinstance(msg, T.ProbeResponse):
        resp.probeResponse.status = int(msg.status)
    elif isinstance(msg, T.ConsensusResponse):
        resp.consensusResponse.SetInParent()
    elif isinstance(msg, T.ClusterStatusResponse):
        s = resp.clusterStatusResponse
        s.sender.CopyFrom(_ep(msg.sender))
        s.configurationId = msg.configuration_id
        s.membershipSize = msg.membership_size
        s.reportsTracked = msg.reports_tracked
        s.preProposalSize = msg.pre_proposal_size
        s.proposalSize = msg.proposal_size
        s.updatesInProgress = msg.updates_in_progress
        s.consensusDecided = int(msg.consensus_decided)
        s.consensusVotes = msg.consensus_votes
        s.metricNames.extend(msg.metric_names)
        s.metricValues.extend(msg.metric_values)
        s.journal.extend(msg.journal)
        s.placementVersion = msg.placement_version
        s.placementPartitions = msg.placement_partitions
        s.placementOwned = msg.placement_owned
        s.handoffInFlight = msg.handoff_in_flight
        s.handoffCompleted = msg.handoff_completed
        s.handoffFailed = msg.handoff_failed
        s.handoffPartitions.extend(msg.handoff_partitions)
        s.handoffFingerprints.extend(msg.handoff_fingerprints)
        s.servingGets = msg.serving_gets
        s.servingPuts = msg.serving_puts
        s.servingPutAcks = msg.serving_put_acks
        s.servingPartitions.extend(msg.serving_partitions)
        s.servingLeaders.extend(msg.serving_leaders)
        s.fdSubjects.extend(msg.fd_subjects)
        s.fdRttMicros.extend(msg.fd_rtt_micros)
        s.fdSuspicionMilli.extend(msg.fd_suspicion_milli)
        s.fdTiers.extend(msg.fd_tiers)
        s.fdTierIntervalMs.extend(msg.fd_tier_interval_ms)
        s.fdTierThreshold.extend(msg.fd_tier_threshold)
        s.fdTierFlushMs.extend(msg.fd_tier_flush_ms)
        s.history.extend(msg.history)
        s.durabilitySegments = msg.durability_segments
        s.durabilitySnapshotVersion = msg.durability_snapshot_version
        s.durabilityReplayed = msg.durability_replayed
        s.sloNames.extend(msg.slo_names)
        s.sloBurnMilli.extend(msg.slo_burn_milli)
        s.sloFiring.extend(msg.slo_firing)
        s.sloAttributedTrace.extend(msg.slo_attributed_trace)
        s.journalDropped = msg.journal_dropped
        s.journalCapacity = msg.journal_capacity
        s.hlcPhysicalMs = msg.hlc_physical_ms
        s.hlcLogical = msg.hlc_logical
        s.hlcIncarnation = msg.hlc_incarnation
        s.cellId = msg.cell_id
        s.cellSize = msg.cell_size
        s.parentConfigurationId = msg.parent_configuration_id
        s.globalFingerprint = msg.global_fingerprint
        s.globalCells.extend(msg.global_cells)
        s.globalEpochs.extend(msg.global_epochs)
        s.globalSizes.extend(msg.global_sizes)
        s.globalLeaders.extend(msg.global_leaders)
    elif isinstance(msg, T.PutAck):
        a = resp.putAck
        a.sender.CopyFrom(_ep(msg.sender))
        a.status = msg.status
        a.key = msg.key
        a.value = msg.value
        a.version = msg.version
        a.requestId = msg.request_id
        if msg.leader is not None:
            a.leader.CopyFrom(_ep(msg.leader))
        a.mapVersion = msg.map_version
    elif isinstance(msg, T.HandoffChunk):
        h = resp.handoffChunk
        h.sender.CopyFrom(_ep(msg.sender))
        h.sessionId = msg.session_id
        h.partition = msg.partition
        h.offset = msg.offset
        h.data = msg.data
        h.totalSize = msg.total_size
        h.fingerprint = msg.fingerprint
        h.status = msg.status
    else:  # Response / None -> empty ack
        resp.response.SetInParent()
    return resp


def from_wire_response(resp):
    which = resp.WhichOneof("content")
    if which == "joinResponse":
        m = resp.joinResponse
        return T.JoinResponse(
            sender=_ep_back(m.sender),
            status_code=T.JoinStatusCode(m.statusCode),
            configuration_id=int(m.configurationId),
            endpoints=tuple(_ep_back(e) for e in m.endpoints),
            identifiers=tuple(_nid_back(i) for i in m.identifiers),
            metadata=tuple(
                (_ep_back(k), _meta_back(v))
                for k, v in zip(m.metadataKeys, m.metadataValues)
            ),
        )
    if which == "probeResponse":
        return T.ProbeResponse(T.NodeStatus(resp.probeResponse.status))
    if which == "consensusResponse":
        return T.ConsensusResponse()
    if which == "clusterStatusResponse":
        m = resp.clusterStatusResponse
        return T.ClusterStatusResponse(
            sender=_ep_back(m.sender),
            configuration_id=int(m.configurationId),
            membership_size=int(m.membershipSize),
            reports_tracked=int(m.reportsTracked),
            pre_proposal_size=int(m.preProposalSize),
            proposal_size=int(m.proposalSize),
            updates_in_progress=int(m.updatesInProgress),
            consensus_decided=bool(m.consensusDecided),
            consensus_votes=int(m.consensusVotes),
            metric_names=tuple(m.metricNames),
            metric_values=tuple(int(v) for v in m.metricValues),
            journal=tuple(m.journal),
            placement_version=int(m.placementVersion),
            placement_partitions=int(m.placementPartitions),
            placement_owned=int(m.placementOwned),
            handoff_in_flight=int(m.handoffInFlight),
            handoff_completed=int(m.handoffCompleted),
            handoff_failed=int(m.handoffFailed),
            handoff_partitions=tuple(int(p) for p in m.handoffPartitions),
            handoff_fingerprints=tuple(int(f) for f in m.handoffFingerprints),
            serving_gets=int(m.servingGets),
            serving_puts=int(m.servingPuts),
            serving_put_acks=int(m.servingPutAcks),
            serving_partitions=tuple(int(p) for p in m.servingPartitions),
            serving_leaders=tuple(str(s) for s in m.servingLeaders),
            fd_subjects=tuple(str(s) for s in m.fdSubjects),
            fd_rtt_micros=tuple(int(v) for v in m.fdRttMicros),
            fd_suspicion_milli=tuple(int(v) for v in m.fdSuspicionMilli),
            fd_tiers=tuple(str(t) for t in m.fdTiers),
            fd_tier_interval_ms=tuple(int(v) for v in m.fdTierIntervalMs),
            fd_tier_threshold=tuple(int(v) for v in m.fdTierThreshold),
            fd_tier_flush_ms=tuple(int(v) for v in m.fdTierFlushMs),
            history=tuple(str(line) for line in m.history),
            durability_segments=int(m.durabilitySegments),
            durability_snapshot_version=int(m.durabilitySnapshotVersion),
            durability_replayed=int(m.durabilityReplayed),
            slo_names=tuple(str(s) for s in m.sloNames),
            slo_burn_milli=tuple(int(v) for v in m.sloBurnMilli),
            slo_firing=tuple(int(v) for v in m.sloFiring),
            slo_attributed_trace=tuple(int(v) for v in m.sloAttributedTrace),
            journal_dropped=int(m.journalDropped),
            journal_capacity=int(m.journalCapacity),
            hlc_physical_ms=int(m.hlcPhysicalMs),
            hlc_logical=int(m.hlcLogical),
            hlc_incarnation=int(m.hlcIncarnation),
            cell_id=int(m.cellId),
            cell_size=int(m.cellSize),
            parent_configuration_id=int(m.parentConfigurationId),
            global_fingerprint=int(m.globalFingerprint),
            global_cells=tuple(int(c) for c in m.globalCells),
            global_epochs=tuple(int(e) for e in m.globalEpochs),
            global_sizes=tuple(int(s) for s in m.globalSizes),
            global_leaders=tuple(str(l) for l in m.globalLeaders),
        )
    if which == "putAck":
        m = resp.putAck
        return T.PutAck(
            sender=_ep_back(m.sender),
            status=int(m.status),
            key=bytes(m.key),
            value=bytes(m.value),
            version=int(m.version),
            request_id=int(m.requestId),
            leader=_ep_back(m.leader) if m.HasField("leader") else None,
            map_version=int(m.mapVersion),
        )
    if which == "handoffChunk":
        m = resp.handoffChunk
        return T.HandoffChunk(
            sender=_ep_back(m.sender),
            session_id=int(m.sessionId),
            partition=int(m.partition),
            offset=int(m.offset),
            data=bytes(m.data),
            total_size=int(m.totalSize),
            fingerprint=int(m.fingerprint),
            status=int(m.status),
        )
    return T.Response()


# ---------------------------------------------------------------------------
# transport
# ---------------------------------------------------------------------------


class _SharedAioLoop:
    """One process-wide event loop thread hosting every grpc.aio server.

    grpc.aio's completion-queue poller is process-global, so multiple event
    loops in one process trip over each other (EAGAIN storms on shutdown).
    One shared loop is also the faithful analogue of the reference's lazy
    shared Netty event-loop group (SharedResources.java:48-67): many servers,
    one reactor. The daemon thread starts on first use and lives for the
    process -- individual servers start/stop on it without tearing it down.
    """

    _lock = make_lock("_SharedAioLoop._lock")
    _loop: Optional[asyncio.AbstractEventLoop] = None

    @classmethod
    def get(cls) -> asyncio.AbstractEventLoop:
        with cls._lock:
            if cls._loop is None or cls._loop.is_closed():
                loop = asyncio.new_event_loop()

                def run() -> None:
                    asyncio.set_event_loop(loop)
                    loop.run_forever()

                thread = threading.Thread(  # noqa: messaging-thread
                    target=run, name="grpc-aio-shared-loop", daemon=True
                )
                thread.start()
                cls._loop = loop
            return cls._loop

    @classmethod
    def call(cls, coro, timeout: float = 10.0):
        """Run a coroutine on the shared loop and wait for its result."""
        return asyncio.run_coroutine_threadsafe(coro, cls.get()).result(timeout)


class GrpcServer(IMessagingServer):
    """Async-completion server: no thread is ever parked on a pending response.

    The reference's server is futures end-to-end -- the RPC completes whenever
    the service's ListenableFuture does, without holding a worker thread
    (GrpcServer.java:77-96). Join phase-2 responses are parked until the view
    change commits (MembershipService.java:229-286), so a thread-per-response
    server deadlocks at >= pool-size concurrent joiners; here the grpc.aio
    event loop awaits each Promise, so thousands of parked joins cost nothing
    but memory.
    """

    def __init__(self, listen_address: T.Endpoint, max_workers: int = 8) -> None:
        self.address = listen_address
        self._service = None
        self._server: Optional[grpc.aio.Server] = None
        # retained for API compatibility; the aio server has no worker pool
        self._max_workers = max_workers

    async def _handle(self, request, context):
        service = self._service
        if service is None:
            msg = from_wire_request(request)
            if isinstance(msg, T.ProbeMessage):
                return to_wire_response(T.ProbeResponse(T.NodeStatus.BOOTSTRAPPING))
            await context.abort(
                grpc.StatusCode.UNAVAILABLE, "membership service not ready"
            )
        promise = service.handle_message(from_wire_request(request))
        loop = asyncio.get_running_loop()
        done: asyncio.Future = loop.create_future()

        def on_complete(p: Promise) -> None:
            def settle() -> None:
                if done.cancelled():
                    return
                exc = p.exception()
                if exc is not None:
                    done.set_exception(exc)
                else:
                    done.set_result(p._result)  # noqa: SLF001

            loop.call_soon_threadsafe(settle)

        promise.add_callback(on_complete)
        try:
            result = await asyncio.wait_for(done, timeout=30)
        except Exception as e:  # noqa: BLE001
            await context.abort(grpc.StatusCode.INTERNAL, str(e))
        return to_wire_response(result)

    def start(self) -> None:
        async def boot() -> grpc.aio.Server:
            handler = grpc.unary_unary_rpc_method_handler(
                self._handle,
                request_deserializer=MSG["RapidRequest"].FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )
            service = grpc.method_handlers_generic_handler(
                "remoting.MembershipService", {"sendRequest": handler}
            )
            server = grpc.aio.server()
            server.add_generic_rpc_handlers((service,))
            server.add_insecure_port(
                f"{self.address.hostname.decode()}:{self.address.port}"
            )
            await server.start()
            return server

        self._server = _SharedAioLoop.call(boot())

    def shutdown(self) -> None:
        server = self._server
        if server is None:
            return
        self._server = None
        try:
            _SharedAioLoop.call(server.stop(grace=0.5))
        except Exception:  # noqa: BLE001 -- loop already gone at interpreter exit
            pass

    def set_membership_service(self, service) -> None:
        self._service = service


class GrpcClient(IMessagingClient):
    """Channel-caching client with the reference's lifecycle rules: the cached
    channel is invalidated on call failure (Retries.java:63-66 ->
    GrpcClient.java:113,131) and evicted after 30s idle (GrpcClient.java:87-95),
    so a peer that restarts on the same address is reached over a fresh
    connection within the retry budget instead of starving behind a dead one.
    """

    IDLE_EVICT_S = 30.0
    # grpc-python's Channel.close() hard-cancels in-flight RPCs (there is no
    # graceful shutdown() like the Java ManagedChannel), so invalidated and
    # idle-evicted channels are *retired* -- dropped from the cache so new
    # sends dial fresh -- and only closed once their in-flight calls (parked
    # joins run the longest, <= the server's 30s ceiling) have drained.
    RETIRE_CLOSE_S = 60.0

    def __init__(self, address: T.Endpoint, settings: Optional[Settings] = None) -> None:
        self.address = address
        self._settings = settings if settings is not None else Settings()
        self._channels: Dict[T.Endpoint, grpc.Channel] = {}
        self._stubs: Dict[T.Endpoint, object] = {}
        self._last_used: Dict[T.Endpoint, float] = {}
        self._retired: list = []  # [(retired_at, channel)]
        self._lock = make_lock("GrpcClient._lock")

    def _stub(self, remote: T.Endpoint):
        now = time.monotonic()
        with self._lock:
            self._evict_idle_locked(now)
            stub = self._stubs.get(remote)
            if stub is None:
                # a local subchannel pool makes "new channel" mean "new
                # connection": with the default process-global pool, a channel
                # dialed right after an invalidation would reuse the broken
                # subchannel still sitting in connect-backoff
                channel = grpc.insecure_channel(
                    f"{remote.hostname.decode()}:{remote.port}",
                    options=[("grpc.use_local_subchannel_pool", 1)],
                )
                stub = channel.unary_unary(
                    GRPC_METHOD_PATH,
                    request_serializer=lambda m: m.SerializeToString(),
                    response_deserializer=MSG["RapidResponse"].FromString,
                )
                self._channels[remote] = channel
                self._stubs[remote] = stub
            self._last_used[remote] = now
            return stub

    def _sweep_retired_locked(self, now: float) -> None:
        while self._retired and now - self._retired[0][0] > self.RETIRE_CLOSE_S:
            _, channel = self._retired.pop(0)
            channel.close()

    def _evict_idle_locked(self, now: float) -> None:
        for ep in [
            ep
            for ep, used in self._last_used.items()
            if now - used > self.IDLE_EVICT_S
        ]:
            channel = self._channels.pop(ep, None)
            self._stubs.pop(ep, None)
            self._last_used.pop(ep, None)
            if channel is not None:
                self._retired.append((now, channel))
        self._sweep_retired_locked(now)

    def invalidate(self, remote: T.Endpoint) -> None:
        """Drop the cached channel so the next attempt dials fresh
        (GrpcClient.java:113,131 via Retries.onCallFailure). The channel is
        retired, not closed: closing would cancel unrelated in-flight RPCs
        sharing it (e.g. a parked join, while a probe's failure triggered the
        invalidation)."""
        now = time.monotonic()
        with self._lock:
            channel = self._channels.pop(remote, None)
            self._stubs.pop(remote, None)
            self._last_used.pop(remote, None)
            if channel is not None:
                self._retired.append((now, channel))
            # sweep here too: a client that stops dialing new stubs must not
            # hold retired channels' sockets past the drain window
            self._sweep_retired_locked(now)

    def _send_once(self, remote: T.Endpoint, msg: T.RapidMessage) -> Promise:
        out: Promise = Promise()
        try:
            stub = self._stub(remote)
            timeout_s = self._settings.timeout_for(msg) / 1000.0
            future = stub.future(to_wire_request(msg), timeout=timeout_s)
        except Exception as e:  # noqa: BLE001
            self.invalidate(remote)
            out.set_exception(e)
            return out

        def on_done(f):
            try:
                out.try_set_result(from_wire_response(f.result()))
            except Exception as e:  # noqa: BLE001
                self.invalidate(remote)
                if not out.done():
                    out.set_exception(e)

        future.add_done_callback(on_done)
        return out

    def send_message(self, remote: T.Endpoint, msg: T.RapidMessage) -> Promise:
        if self._settings.retry_base_delay_ms > 0:
            return call_with_retries(
                lambda: self._send_once(remote, msg),
                self._settings.message_retries,
                scheduler=wall_scheduler(),
                policy=self._settings.retry_policy(),
                deadline_ms=self._settings.deadline_for(msg),
            )
        return call_with_retries(
            lambda: self._send_once(remote, msg), self._settings.message_retries
        )

    def send_message_best_effort(self, remote: T.Endpoint, msg: T.RapidMessage) -> Promise:
        return self._send_once(remote, msg)

    def shutdown(self) -> None:
        with self._lock:
            for channel in self._channels.values():
                channel.close()
            for _, channel in self._retired:
                channel.close()
            self._channels.clear()
            self._stubs.clear()
            self._last_used.clear()
            self._retired.clear()
