"""Async retry combinator (Retries.callWithRetries, Retries.java:44-91),
hardened with exponential backoff, decorrelated jitter and an overall
deadline.

The reference resubscribes immediately on failure -- under a lossy link that
is a retry storm ("The Performance of Paxos in the Cloud", PAPERS.md, shows
this class of tail behavior dominating consensus latency). The hardened form
spaces attempts by a :class:`RetryPolicy` and bounds the whole exchange by a
deadline, both driven through the :class:`~..runtime.scheduler.Scheduler`
seam so virtual-time tests pin the exact schedule deterministically.

Defaults are bit-compatible with the legacy combinator: no policy and no
deadline means immediate resubscription, and none of the existing call sites
change behavior until Settings opts them in.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Callable, Optional

from ..runtime.lockdep import make_lock
from ..runtime.futures import Promise
from ..runtime.scheduler import Scheduler


class RetryDeadlineExceeded(TimeoutError):
    """The overall retry deadline elapsed before an attempt succeeded."""


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule between attempts.

    ``base_delay_ms == 0`` reproduces the legacy immediate-resubscribe
    behavior exactly. With ``jitter="decorrelated"`` the delay follows the
    AWS decorrelated-jitter recurrence ``sleep = min(cap, uniform(base,
    prev * 3))``; ``jitter="none"`` is plain capped exponential doubling.
    """

    base_delay_ms: int = 0
    max_delay_ms: int = 30_000
    jitter: str = "decorrelated"  # "decorrelated" | "none"

    def __post_init__(self) -> None:
        assert self.jitter in ("decorrelated", "none"), self.jitter
        assert 0 <= self.base_delay_ms <= self.max_delay_ms

    def next_delay_ms(self, prev_delay_ms: int, rng: random.Random) -> int:
        if self.base_delay_ms == 0:
            return 0
        if self.jitter == "none":
            grown = prev_delay_ms * 2 if prev_delay_ms > 0 else self.base_delay_ms
            return min(self.max_delay_ms, grown)
        lo = self.base_delay_ms
        hi = max(lo, prev_delay_ms * 3)
        return min(self.max_delay_ms, int(rng.uniform(lo, hi)))


# Wall-clock scheduler shared by socket transports that have no scheduler of
# their own (TCP/gRPC clients): one timer thread lazily created on the first
# backoff/deadline actually requested, never for the 0-delay default path.
_wall_lock = make_lock("retries._wall_lock")
_wall_scheduler: Optional[Scheduler] = None


def wall_scheduler() -> Scheduler:
    from ..runtime.scheduler import RealScheduler

    global _wall_scheduler
    with _wall_lock:
        if _wall_scheduler is None:
            _wall_scheduler = RealScheduler(name="rapid-retry-backoff")
        return _wall_scheduler


def call_with_retries(
    attempt: Callable[[], Promise],
    retries: int,
    *,
    scheduler: Optional[Scheduler] = None,
    policy: Optional[RetryPolicy] = None,
    deadline_ms: Optional[int] = None,
    rng: Optional[random.Random] = None,
    metrics=None,
) -> Promise:
    """Run ``attempt`` up to ``retries + 1`` times, resubscribing on failure.

    - ``policy``: backoff between attempts; delays hop through ``scheduler``
      (required when the policy's base delay is nonzero).
    - ``deadline_ms``: overall budget across every attempt, measured on
      ``scheduler.now_ms()``. A retry that cannot start before the deadline
      fails the promise with :class:`RetryDeadlineExceeded` chaining the last
      attempt's error. Requires ``scheduler``.
    - ``metrics``: optional :class:`~..observability.Metrics`; counts
      ``retry_attempts`` / ``retry_exhausted`` / ``retry_deadline_exceeded``
      and observes each realized backoff into ``retry_backoff_ms``.
    """
    out: Promise = Promise()
    policy = policy if policy is not None else RetryPolicy()
    needs_clock = deadline_ms is not None or policy.base_delay_ms > 0
    assert scheduler is not None or not needs_clock, (
        "backoff/deadline retries need a scheduler for time"
    )
    rng = rng if rng is not None else random.Random()
    start_ms = scheduler.now_ms() if scheduler is not None else 0
    state = {"prev_delay": 0}

    def run(remaining: int) -> None:
        if metrics is not None:
            metrics.incr("retry_attempts")
        try:
            p = attempt()
        except Exception as e:  # noqa: BLE001 -- synchronous failure counts too
            _on_fail(e, remaining)
            return
        p.add_callback(lambda done: _on_done(done, remaining))

    def _on_done(done: Promise, remaining: int) -> None:
        exc = done.exception()
        if exc is None:
            if not out.done():
                out.try_set_result(done._result)  # noqa: SLF001
        else:
            _on_fail(exc, remaining)

    def _on_fail(exc: BaseException, remaining: int) -> None:
        if remaining <= 0:
            if metrics is not None:
                metrics.incr("retry_exhausted")
            if not out.done():
                out.try_set_exception(exc)
            return
        delay = policy.next_delay_ms(state["prev_delay"], rng)
        state["prev_delay"] = delay
        if deadline_ms is not None and (
            scheduler.now_ms() + delay >= start_ms + deadline_ms
        ):
            if metrics is not None:
                metrics.incr("retry_deadline_exceeded")
            if not out.done():
                dead = RetryDeadlineExceeded(
                    f"retry deadline of {deadline_ms} ms exhausted"
                )
                dead.__cause__ = exc
                out.try_set_exception(dead)
            return
        if delay > 0:
            if metrics is not None:
                # the realized jitter schedule, observable next to fd.rtt_ms:
                # under a DelayRule'd or slow link the histogram shows how
                # backoff and the per-message deadline split the budget
                metrics.observe("retry_backoff_ms", delay)
            scheduler.schedule(delay, lambda: run(remaining - 1))
        else:
            run(remaining - 1)

    run(retries)
    return out
