"""Async retry combinator (Retries.callWithRetries, Retries.java:44-91)."""

from __future__ import annotations

from typing import Callable

from ..runtime.futures import Promise


def call_with_retries(attempt: Callable[[], Promise], retries: int) -> Promise:
    """Run ``attempt`` up to ``retries + 1`` times, resubscribing on failure."""
    out: Promise = Promise()

    def run(remaining: int) -> None:
        try:
            p = attempt()
        except Exception as e:  # noqa: BLE001 -- synchronous failure counts too
            _on_fail(e, remaining)
            return
        p.add_callback(lambda done: _on_done(done, remaining))

    def _on_done(done: Promise, remaining: int) -> None:
        exc = done.exception()
        if exc is None:
            if not out.done():
                out.try_set_result(done._result)  # noqa: SLF001
        else:
            _on_fail(exc, remaining)

    def _on_fail(exc: BaseException, remaining: int) -> None:
        if remaining > 0:
            run(remaining - 1)
        elif not out.done():
            out.set_exception(exc)

    run(retries)
    return out
