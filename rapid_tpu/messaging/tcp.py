"""Real-socket TCP transport: length-prefixed frames, request/response
correlation, connection reuse.

The analogue of the reference's raw-Netty alternative transport
(NettyClientServer.java): one class implements both IMessagingClient and
IMessagingServer (:65); responses are matched to requests via a per-connection
request number (:267-277); outbound channels are cached per remote. Framing
and payload encoding live in rapid_tpu.messaging.codec.

Built on threads + blocking sockets (one reader thread per connection): the
protocol's fan-out is K-bounded per node, so a node talks to tens of peers,
not thousands. Used by the standalone agent and the multi-process
integration tests (tier 3 of the test strategy, SURVEY.md §4.3).
"""

from __future__ import annotations

import heapq
import itertools
import logging
import socket
import threading
import time
from typing import Callable, Dict, Optional

from ..runtime.lockdep import make_condition, make_lock
from ..runtime.futures import Promise
from ..settings import Settings
from ..types import Endpoint, NodeStatus, ProbeMessage, ProbeResponse, RapidMessage
from .base import IMessagingClient, IMessagingServer
from .codec import HEADER, decode, encode
from .retries import call_with_retries, wall_scheduler

LOG = logging.getLogger(__name__)


def _read_exactly(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _read_frame(sock: socket.socket) -> Optional[bytes]:
    header = _read_exactly(sock, HEADER.size)
    if header is None:
        return None
    (length,) = HEADER.unpack(header)
    if length > 64 * 1024 * 1024:
        raise ValueError(f"oversized frame: {length}")
    return _read_exactly(sock, length)


def _write_frame(sock: socket.socket, frame: bytes) -> None:
    sock.sendall(HEADER.pack(len(frame)) + frame)


class _Connection:
    """One outbound connection: writer + response-correlating reader."""

    def __init__(self, remote: Endpoint, timeout_s: float) -> None:
        self.sock = socket.create_connection(
            (remote.hostname.decode(), remote.port), timeout=timeout_s
        )
        self.sock.settimeout(None)
        self.lock = make_lock("_Connection.lock")
        self.outstanding: Dict[int, Promise] = {}
        self.closed = False
        self.reader = threading.Thread(
            target=self._read_loop, name=f"tcp-client-{remote}", daemon=True
        )
        self.reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                frame = _read_frame(self.sock)
                if frame is None:
                    break
                request_no, response = decode(frame)
                with self.lock:
                    promise = self.outstanding.pop(request_no, None)
                if promise is not None:
                    promise.try_set_result(response)
        except (OSError, ValueError):
            pass
        finally:
            self.close()

    def forget(self, request_no: int) -> None:
        """Drop a correlation entry whose promise completed without a response
        frame (timeout/drop) -- otherwise entries accumulate for the life of
        the connection."""
        with self.lock:
            self.outstanding.pop(request_no, None)

    def close(self) -> None:
        with self.lock:
            if self.closed:
                return
            self.closed = True
            pending = list(self.outstanding.values())
            self.outstanding.clear()
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        for promise in pending:
            if not promise.done():
                try:
                    promise.set_exception(ConnectionError("connection closed"))
                except Exception:  # noqa: BLE001 -- lost race with completion
                    pass


class FramedTcpServer:
    """Accept loop + connection lifecycle for length-prefixed framed servers.

    Owns the subtle socket mechanics shared by every framed server (the node
    transport and the swarm gateway): accepted-socket tracking, the
    shutdown()-before-close() dance -- a thread blocked in accept()/recv()
    holds the fd, so close() alone neither wakes it nor sends the FIN peers
    rely on to sense liveness -- and the accept-vs-shutdown race. Inbound
    frames are handed to ``on_frame(sock, write_lock, frame)``.
    """

    def __init__(
        self,
        listen_address: Endpoint,
        on_frame: Callable[[socket.socket, threading.Lock, bytes], None],
        name: str = "tcp-server",
    ) -> None:
        self.address = listen_address
        self._on_frame = on_frame
        self._name = name
        self._server_sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._accepted: set = set()
        self._accepted_lock = make_lock("FramedTcpServer._accepted_lock")
        self._running = False

    def start(self) -> None:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self.address.hostname.decode(), self.address.port))
        sock.listen(128)
        self._server_sock = sock
        self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{self._name}-{self.address}", daemon=True
        )
        self._accept_thread.start()

    def shutdown(self) -> None:
        self._running = False
        if self._server_sock is not None:
            for op in (lambda s: s.shutdown(socket.SHUT_RDWR), lambda s: s.close()):
                try:
                    op(self._server_sock)
                except OSError:
                    pass
        with self._accepted_lock:
            accepted = list(self._accepted)
            self._accepted.clear()
        for sock in accepted:
            for op in (lambda s: s.shutdown(socket.SHUT_RDWR), lambda s: s.close()):
                try:
                    op(sock)
                except OSError:
                    pass

    def _accept_loop(self) -> None:
        assert self._server_sock is not None
        while self._running:
            try:
                conn, _ = self._server_sock.accept()
            except OSError:
                return
            with self._accepted_lock:
                if not self._running:
                    # lost the race with shutdown(): its sweep already ran
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                self._accepted.add(conn)
            threading.Thread(
                target=self._serve_connection, args=(conn,), daemon=True
            ).start()

    def _serve_connection(self, sock: socket.socket) -> None:
        write_lock = make_lock("FramedTcpServer.write_lock")
        try:
            while True:
                frame = _read_frame(sock)
                if frame is None:
                    return
                self._on_frame(sock, write_lock, frame)
        except (OSError, ValueError):
            pass
        finally:
            with self._accepted_lock:
                self._accepted.discard(sock)
            try:
                sock.close()
            except OSError:
                pass


class _TimeoutWheel:
    """One shared deadline thread for every in-flight framed request.

    The obvious per-request ``threading.Timer`` is an OS thread per send; at
    swarm scale (50 agents x K probe subjects per FD interval in one test
    process) that is ~1000 thread creations per second and ~1000 live timer
    threads -- a GIL convoy that starves every protocol stack on the box
    (observed as load averages in the hundreds and multi-minute protocol
    stalls). One heap + one thread arms every deadline; completed promises
    simply expire off the heap (``try_set_exception`` on a completed promise
    is a no-op), so no cancellation bookkeeping is needed."""

    def __init__(self) -> None:
        self._heap: list = []
        self._seq = itertools.count()
        self._cond = make_condition("_TimeoutWheel._cond")
        self._thread: Optional[threading.Thread] = None

    def arm(self, timeout_s: float, promise: Promise, remote: Endpoint) -> None:
        deadline = time.monotonic() + timeout_s
        with self._cond:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="rapid-timeouts", daemon=True
                )
                self._thread.start()
            heapq.heappush(self._heap, (deadline, next(self._seq), promise, remote))
            self._cond.notify()

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._heap:
                    self._cond.wait()
                delay = self._heap[0][0] - time.monotonic()
                if delay > 0:
                    self._cond.wait(delay)
                    continue
                _, _, promise, remote = heapq.heappop(self._heap)
            if not promise.done():
                promise.try_set_exception(
                    TimeoutError(f"no response from {remote}")
                )


_timeouts = _TimeoutWheel()


def send_framed(conn: _Connection, request_no: int, frame: bytes,
                timeout_s: float, remote: Endpoint) -> Promise:
    """One framed request over a correlated connection: register the entry,
    write the frame (under the connection lock -- concurrent senders must not
    interleave partial frames), arm the deadline, and reap the correlation
    entry on completion. Shared by the node transport and the gateway-routed
    client so the scaffolding cannot drift between them."""
    out: Promise = Promise()
    try:
        with conn.lock:
            conn.outstanding[request_no] = out
            # sendall under the connection lock is the point: concurrent
            # senders must not interleave partial frames on one socket
            _write_frame(conn.sock, frame)  # noqa: blocking-under-lock
    except OSError as e:
        if not out.done():
            out.set_exception(e)
        return out
    # non-strict: a response arriving at exactly the deadline must win the
    # race, not crash the deadline thread
    _timeouts.arm(timeout_s, out, remote)

    def on_complete(_p: Promise, c=conn, rn=request_no) -> None:
        c.forget(rn)

    out.add_callback(on_complete)
    return out


class TcpClientServer(IMessagingClient, IMessagingServer):
    """Both halves of the transport in one object, like the reference's
    NettyClientServer."""

    def __init__(self, listen_address: Endpoint, settings: Optional[Settings] = None) -> None:
        self.address = listen_address
        self._settings = settings if settings is not None else Settings()
        self._service = None
        self._request_no = itertools.count()
        self._connections: Dict[Endpoint, _Connection] = {}
        self._conn_lock = make_lock("TcpClientServer._conn_lock")
        self._framed = FramedTcpServer(listen_address, self._on_frame, "tcp-server")

    # -- server side ---------------------------------------------------------

    def start(self) -> None:
        self._framed.start()

    def _on_frame(self, sock: socket.socket, write_lock: threading.Lock,
                  frame: bytes) -> None:
        request_no, msg = decode(frame)
        self._dispatch(msg).add_callback(
            lambda p, rn=request_no: self._reply(sock, write_lock, rn, p)
        )

    def _reply(self, sock: socket.socket, write_lock: threading.Lock,
               request_no: int, promise: Promise) -> None:
        if promise.exception() is not None:
            return  # no response; the caller's deadline handles it
        response = promise._result  # noqa: SLF001
        if response is None:
            return
        try:
            with write_lock:
                # replies from concurrent protocol tasks share one socket;
                # the per-connection write lock keeps frames whole
                _write_frame(sock, encode(request_no, response))  # noqa: blocking-under-lock
        except OSError:
            pass

    def _dispatch(self, msg: RapidMessage) -> Promise:
        service = self._service
        if service is None:
            if isinstance(msg, ProbeMessage):
                return Promise.completed(ProbeResponse(NodeStatus.BOOTSTRAPPING))
            return Promise()  # dropped until the service is wired
        try:
            return service.handle_message(msg)
        except Exception as e:  # noqa: BLE001
            return Promise.failed(e)

    def set_membership_service(self, service) -> None:
        self._service = service

    # -- client side ---------------------------------------------------------

    def _connection(self, remote: Endpoint) -> _Connection:
        with self._conn_lock:
            conn = self._connections.get(remote)
            if conn is not None and not conn.closed:
                return conn
        # dial OUTSIDE the lock: connect() can block for seconds on an
        # unreachable peer, and the cache lock is shared across all remotes
        # -- one dead peer must not stall every sender on the node
        fresh = _Connection(remote, self._settings.message_timeout_ms / 1000.0)
        with self._conn_lock:
            conn = self._connections.get(remote)
            if conn is not None and not conn.closed:
                winner = conn  # lost a dial race; keep the established one
            else:
                winner = self._connections[remote] = fresh
        if winner is not fresh:
            fresh.close()
        return winner

    def _send_once(self, remote: Endpoint, msg: RapidMessage,
                   timeout_ms: Optional[int] = None) -> Promise:
        try:
            conn = self._connection(remote)
        except OSError as e:
            return Promise.failed(e)
        request_no = next(self._request_no)
        timeout = (
            timeout_ms if timeout_ms is not None
            else self._settings.timeout_for(msg)
        )
        return send_framed(
            conn, request_no, encode(request_no, msg), timeout / 1000.0,
            remote,
        )

    def _retry_kwargs(self, deadline_ms: int) -> dict:
        """Backoff/deadline wiring for the hardened retry combinator: only a
        nonzero settings backoff pays for the shared wall-clock scheduler."""
        if self._settings.retry_base_delay_ms <= 0:
            return {}
        return {
            "scheduler": wall_scheduler(),
            "policy": self._settings.retry_policy(),
            "deadline_ms": deadline_ms,
        }

    def send_message(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        return call_with_retries(
            lambda: self._send_once(remote, msg),
            self._settings.message_retries,
            **self._retry_kwargs(self._settings.deadline_for(msg)),
        )

    def send_message_with_timeout(
        self, remote: Endpoint, msg: RapidMessage, timeout_ms: int
    ) -> Promise:
        """send_message with an explicit per-attempt deadline, for callers
        whose message class deserves a different budget than the settings
        table (the gateway's decision-packet deliveries use the join-class
        deadline: the receiving member may be mid-bootstrap of a new view,
        busy rather than dead)."""
        return call_with_retries(
            lambda: self._send_once(remote, msg, timeout_ms),
            self._settings.message_retries,
            **self._retry_kwargs(
                timeout_ms * (self._settings.message_retries + 1)
            ),
        )

    def send_message_best_effort(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        return self._send_once(remote, msg)

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self) -> None:
        self._framed.shutdown()
        self._shutdown_client_half()

    def _shutdown_client_half(self) -> None:
        """Close every cached outbound connection (shared with subclasses
        that replace the server half, e.g. the native-reactor transport)."""
        with self._conn_lock:
            connections = list(self._connections.values())
            self._connections.clear()
        for conn in connections:
            conn.close()
