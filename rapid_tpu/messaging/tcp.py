"""Real-socket TCP transport: length-prefixed frames, request/response
correlation, connection reuse.

The analogue of the reference's raw-Netty alternative transport
(NettyClientServer.java): one class implements both IMessagingClient and
IMessagingServer (:65); responses are matched to requests via a per-connection
request number (:267-277); outbound channels are cached per remote. Framing
and payload encoding live in rapid_tpu.messaging.codec.

Built on the event-loop core in ``messaging/reactor.py``: one I/O thread per
``TcpClientServer`` multiplexes every inbound and outbound socket through a
``selectors`` loop, replacing the old thread-per-connection design (a reader
thread per ``_Connection``, a thread per accepted socket, and the shared
``_TimeoutWheel`` deadline thread). Request deadlines are reactor timers;
outbound frames coalesce in per-peer channel queues and flush with one
scatter-gather syscall per tick per peer; dials are nonblocking ``connect``s
observed by the reactor, gated per peer by a decorrelated-jitter backoff so
a crashed peer costs one pending dial, not a connect storm. Used by the
standalone agent and the multi-process integration tests (tier 3 of the
test strategy, SURVEY.md §4.3).
"""

from __future__ import annotations

import itertools
import logging
import random
import socket
import threading
import time
from typing import Callable, Dict, Optional

from ..observability import Metrics, global_metrics
from ..runtime.lockdep import make_lock
from ..runtime.futures import Promise
from ..settings import Settings
from ..types import Endpoint, NodeStatus, ProbeMessage, ProbeResponse, RapidMessage
from .base import IMessagingClient, IMessagingServer
from .codec import HEADER, decode, encode
from .reactor import Acceptor, Channel, Reactor, shared_reactor
from .retries import RetryPolicy, call_with_retries, wall_scheduler

LOG = logging.getLogger(__name__)


def _read_exactly(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _read_frame(sock: socket.socket) -> Optional[bytes]:
    """Blocking framed read for plain sockets (raw test clients and the
    simulator's out-of-band helpers; transport reads go through the
    reactor's zero-copy parser)."""
    header = _read_exactly(sock, HEADER.size)
    if header is None:
        return None
    (length,) = HEADER.unpack(header)
    if length > 64 * 1024 * 1024:
        raise ValueError(f"oversized frame: {length}")
    return _read_exactly(sock, length)


def _write_frame(sock, frame: bytes) -> None:
    """Write one length-prefixed frame. Channel-backed writers (everything
    the reactor accepted) expose ``send_frame`` and take the zero-copy
    queued path; plain sockets fall back to a blocking ``sendall``."""
    send_frame = getattr(sock, "send_frame", None)
    if send_frame is not None:
        send_frame(frame)
    else:
        sock.sendall(HEADER.pack(len(frame)) + frame)


class _Connection:
    """One outbound connection: a reactor channel plus the response
    correlation map. The dial is nonblocking -- frames queue in the channel
    until the connect completes, and a failed or timed-out dial fails every
    pending promise via the channel's close callback."""

    def __init__(
        self,
        remote: Endpoint,
        timeout_s: float,
        reactor: Optional[Reactor] = None,
        metrics: Optional[Metrics] = None,
        on_dial_outcome: Optional[Callable[[Endpoint, bool], None]] = None,
        on_closed: Optional[Callable[["_Connection"], None]] = None,
    ) -> None:
        self.remote = remote
        self.reactor = reactor if reactor is not None else shared_reactor()
        self.lock = make_lock("_Connection.lock")
        self.outstanding: Dict[int, Promise] = {}  # guarded-by: lock
        self.closed = False  # guarded-by: lock
        self._on_dial_outcome = on_dial_outcome
        self._on_closed = on_closed
        self.channel = Channel.connect(
            self.reactor,
            (remote.hostname.decode(), remote.port),
            timeout_s,
            self._chan_frame,
            on_close=self._chan_closed,
            on_connect=self._chan_connected,
            metrics=metrics,
        )

    def _chan_frame(self, channel: Channel, frame: memoryview) -> None:
        request_no, response = decode(frame)
        with self.lock:
            promise = self.outstanding.pop(request_no, None)
        if promise is not None:
            promise.try_set_result(response)

    def _chan_connected(self, channel: Channel) -> None:
        if self._on_dial_outcome is not None:
            self._on_dial_outcome(self.remote, True)

    def _chan_closed(self, channel: Channel, exc) -> None:
        with self.lock:
            if self.closed:
                return
            self.closed = True
            pending = list(self.outstanding.values())
            self.outstanding.clear()
        if not channel.connected and self._on_dial_outcome is not None:
            self._on_dial_outcome(self.remote, False)
        if self._on_closed is not None:
            self._on_closed(self)
        for promise in pending:
            if not promise.done():
                try:
                    promise.set_exception(ConnectionError("connection closed"))
                except Exception:  # noqa: BLE001 -- lost race with completion
                    pass

    def pending_bytes(self) -> int:
        return self.channel.pending_bytes()

    def forget(self, request_no: int) -> None:
        """Drop a correlation entry whose promise completed without a response
        frame (timeout/drop) -- otherwise entries accumulate for the life of
        the connection."""
        with self.lock:
            self.outstanding.pop(request_no, None)

    def close(self) -> None:
        self.channel.close(None)


def send_framed(conn: _Connection, request_no: int, frame: bytes,
                timeout_s: float, remote: Endpoint) -> Promise:
    """One framed request over a correlated connection: register the entry,
    queue the frame (the channel's outbound queue keeps concurrent senders'
    frames whole and ordered), arm the deadline as a reactor timer, and reap
    the correlation entry on completion. Shared by the node transport and
    the gateway-routed client so the scaffolding cannot drift between
    them."""
    out: Promise = Promise()
    with conn.lock:
        if conn.closed:
            already_closed = True
        else:
            already_closed = False
            conn.outstanding[request_no] = out
    if already_closed:
        out.set_exception(ConnectionError("connection closed"))
        return out
    try:
        conn.channel.send_frame(frame)
    except OSError as e:
        conn.forget(request_no)
        if not out.done():
            try:
                out.set_exception(e)
            except Exception:  # noqa: BLE001 -- lost race with close sweep
                pass
        return out
    # non-strict: a response arriving at exactly the deadline must win the
    # race, not crash the reactor thread
    timer = conn.reactor.call_later(
        timeout_s,
        lambda: out.try_set_exception(TimeoutError(f"no response from {remote}")),
    )

    def on_complete(_p: Promise, c=conn, rn=request_no, t=timer) -> None:
        t.cancel()
        c.forget(rn)

    out.add_callback(on_complete)
    return out


class _ChannelWriter:
    """Socket-shaped reply handle passed to ``on_frame`` callbacks: the
    write side of an accepted channel. ``sendall``/``send_frame`` only queue
    (the reactor flushes), so replies never block on a slow reader, and
    ``fileno()`` returns -1 once the peer is gone -- the contract the swarm
    gateway's writer lanes rely on."""

    __slots__ = ("_channel",)

    def __init__(self, channel: Channel) -> None:
        self._channel = channel

    def send_frame(self, frame: bytes) -> None:
        self._channel.send_frame(frame)

    def sendall(self, data: bytes) -> None:
        self._channel.send_buffers((data,))

    def fileno(self) -> int:
        return self._channel.fileno()

    def close(self) -> None:
        self._channel.close(None)


class FramedTcpServer:
    """Accept loop + connection lifecycle for length-prefixed framed servers.

    Owns the socket mechanics shared by every framed server (the node
    transport and the swarm gateway): a reactor ``Acceptor`` in place of the
    old accept thread, one multiplexed ``Channel`` per inbound connection in
    place of a thread per socket, and teardown that still delivers the FIN
    peers rely on to sense liveness. Inbound frames are handed to
    ``on_frame(writer, write_lock, frame)`` where ``writer`` is the
    connection's ``_ChannelWriter``; ``frame`` is ``bytes`` unless the
    server opts into ``frames_as_memoryview`` (valid only for the duration
    of the call). Constructed-but-never-started instances shut down as a
    safe no-op (the native transport relies on this).
    """

    def __init__(
        self,
        listen_address: Endpoint,
        on_frame: Callable[[object, threading.Lock, bytes], None],
        name: str = "tcp-server",
        reactor: Optional[Reactor] = None,
        metrics: Optional[Metrics] = None,
        frames_as_memoryview: bool = False,
    ) -> None:
        self.address = listen_address
        self._on_frame = on_frame
        self._name = name
        self._reactor = reactor
        self._owns_reactor = False
        self._metrics = metrics
        self._frames_as_memoryview = frames_as_memoryview
        self._acceptor: Optional[Acceptor] = None
        self._accepted_lock = make_lock("FramedTcpServer._accepted_lock")
        # channel -> (writer, per-connection write lock)
        self._accepted: Dict[Channel, tuple] = {}  # guarded-by: _accepted_lock
        self._running = False

    def start(self) -> None:
        if self._reactor is None:
            self._reactor = Reactor(f"{self._name}-io-{self.address.port}")
            self._owns_reactor = True
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self.address.hostname.decode(), self.address.port))
        sock.listen(128)
        self._running = True
        self._acceptor = Acceptor(self._reactor, sock, self._accept)

    def _accept(self, sock: socket.socket) -> None:
        channel = Channel(
            self._reactor, sock, self._chan_frame,
            on_close=self._chan_closed, metrics=self._metrics,
        )
        writer = _ChannelWriter(channel)
        write_lock = make_lock("FramedTcpServer.write_lock")
        with self._accepted_lock:
            if not self._running:
                accept_open = False  # lost the race with shutdown()
            else:
                accept_open = True
                self._accepted[channel] = (writer, write_lock)
        if not accept_open:
            channel.close(None)

    def _chan_frame(self, channel: Channel, frame: memoryview) -> None:
        with self._accepted_lock:
            entry = self._accepted.get(channel)
        if entry is None:
            return
        writer, write_lock = entry
        payload = frame if self._frames_as_memoryview else bytes(frame)
        self._on_frame(writer, write_lock, payload)

    def _chan_closed(self, channel: Channel, exc) -> None:
        with self._accepted_lock:
            self._accepted.pop(channel, None)

    def shutdown(self) -> None:
        self._running = False
        if self._acceptor is not None:
            self._acceptor.close()
            self._acceptor = None
        with self._accepted_lock:
            accepted = list(self._accepted)
            self._accepted.clear()
        for channel in accepted:
            channel.close(None)
        if self._owns_reactor and self._reactor is not None:
            self._reactor.stop()


class TcpClientServer(IMessagingClient, IMessagingServer):
    """Both halves of the transport in one object, like the reference's
    NettyClientServer -- server channels and client channels share one
    reactor (``self._io``), so the whole node does its socket I/O on a
    single thread."""

    def __init__(self, listen_address: Endpoint, settings: Optional[Settings] = None) -> None:
        self.address = listen_address
        self._settings = settings if settings is not None else Settings()
        self._service = None
        self._request_no = itertools.count()
        self._connections: Dict[Endpoint, _Connection] = {}
        self._conn_lock = make_lock("TcpClientServer._conn_lock")
        # per-peer dial backoff gate: remote -> {"until", "prev", "since"}
        # (monotonic ms); a peer inside its window fails fast instead of
        # issuing another connect syscall
        self._dial_gate: Dict[Endpoint, dict] = {}  # guarded-by: _conn_lock
        self._dial_rng = random.Random()
        self._dial_policy = RetryPolicy(
            base_delay_ms=self._settings.dial_backoff_base_ms,
            max_delay_ms=self._settings.dial_backoff_max_ms,
            jitter=self._settings.retry_jitter,
        )
        self.metrics = Metrics(
            parent=global_metrics(), plane="transport", node=str(listen_address)
        )
        # NOTE: named _io, not _reactor -- the native transport subclass
        # stores its C++ NativeReactor as self._reactor
        self._io = Reactor(f"tcp-io-{listen_address.port}")
        self._framed = FramedTcpServer(
            listen_address, self._on_frame, "tcp-server",
            reactor=self._io, metrics=self.metrics, frames_as_memoryview=True,
        )

    # -- server side ---------------------------------------------------------

    def start(self) -> None:
        self._framed.start()

    def _on_frame(self, sock, write_lock: threading.Lock, frame) -> None:
        request_no, msg = decode(frame)
        self._dispatch(msg).add_callback(
            lambda p, rn=request_no: self._reply(sock, write_lock, rn, p)
        )

    def _reply(self, sock, write_lock: threading.Lock,
               request_no: int, promise: Promise) -> None:
        if promise.exception() is not None:
            return  # no response; the caller's deadline handles it
        response = promise._result  # noqa: SLF001
        if response is None:
            return
        try:
            # replies from concurrent protocol tasks share one channel; its
            # outbound queue keeps frames whole, so no write lock is needed
            _write_frame(sock, encode(request_no, response))
        except OSError:
            pass

    def _dispatch(self, msg: RapidMessage) -> Promise:
        service = self._service
        if service is None:
            if isinstance(msg, ProbeMessage):
                return Promise.completed(ProbeResponse(NodeStatus.BOOTSTRAPPING))
            return Promise()  # dropped until the service is wired
        try:
            return service.handle_message(msg)
        except Exception as e:  # noqa: BLE001
            return Promise.failed(e)

    def set_membership_service(self, service) -> None:
        self._service = service

    # -- client side ---------------------------------------------------------

    def _connection(self, remote: Endpoint) -> _Connection:
        now_ms = time.monotonic() * 1000.0
        with self._conn_lock:
            conn = self._connections.get(remote)
            if conn is not None and not conn.closed:
                return conn
            gate = self._dial_gate.get(remote)
            if gate is not None and now_ms < gate["until"]:
                # inside the backoff window: one pending/failed dial already
                # represents this peer; fail fast instead of re-dialing
                self.metrics.incr("msg.dial_backoffs")
                raise ConnectionError(
                    f"dial backoff for {remote} "
                    f"({gate['until'] - now_ms:.0f}ms remaining)"
                )
        # dial OUTSIDE the lock: even a nonblocking connect does DNS + a
        # syscall, and the cache lock is shared across all remotes -- one
        # dead peer must not stall every sender on the node
        try:
            fresh = _Connection(
                remote, self._settings.message_timeout_ms / 1000.0,
                reactor=self._io, metrics=self.metrics,
                on_dial_outcome=self._dial_outcome,
                on_closed=self._forget_connection,
            )
        except OSError:
            self._dial_outcome(remote, False)
            raise
        with self._conn_lock:
            conn = self._connections.get(remote)
            if conn is not None and not conn.closed:
                winner = conn  # lost a dial race; keep the established one
            else:
                winner = self._connections[remote] = fresh
        if winner is not fresh:
            fresh.close()
        return winner

    def _forget_connection(self, conn: _Connection) -> None:
        """Evict a closed connection from the cache. Without this, every
        departed peer leaves a closed _Connection in ``_connections``
        forever -- the cache (and the transport_digest walk over it) grows
        monotonically with peer churn. Identity-checked so a dial-race
        loser's close can never evict the winning connection."""
        with self._conn_lock:
            if self._connections.get(conn.remote) is conn:
                del self._connections[conn.remote]

    def _dial_outcome(self, remote: Endpoint, ok: bool) -> None:
        """Advance or clear the per-peer backoff gate. Failure delays follow
        the decorrelated-jitter policy from messaging/retries.py; the epoch
        resets once the peer has been gated past its dial deadline, so a
        long-dead peer keeps getting (rate-limited) fresh dials."""
        now_ms = time.monotonic() * 1000.0
        with self._conn_lock:
            if ok:
                self._dial_gate.pop(remote, None)
                return
            gate = self._dial_gate.get(remote)
            if gate is None or now_ms - gate["since"] >= self._settings.dial_deadline_ms:
                gate = {"since": now_ms, "prev": 0.0, "until": 0.0}
                self._dial_gate[remote] = gate
            delay = self._dial_policy.next_delay_ms(gate["prev"], self._dial_rng)
            gate["prev"] = delay
            gate["until"] = now_ms + delay

    def _send_once(self, remote: Endpoint, msg: RapidMessage,
                   timeout_ms: Optional[int] = None) -> Promise:
        try:
            conn = self._connection(remote)
        except OSError as e:
            return Promise.failed(e)
        request_no = next(self._request_no)
        timeout = (
            timeout_ms if timeout_ms is not None
            else self._settings.timeout_for(msg)
        )
        return send_framed(
            conn, request_no, encode(request_no, msg), timeout / 1000.0,
            remote,
        )

    def _retry_kwargs(self, deadline_ms: int) -> dict:
        """Backoff/deadline wiring for the hardened retry combinator: only a
        nonzero settings backoff pays for the shared wall-clock scheduler."""
        if self._settings.retry_base_delay_ms <= 0:
            return {}
        return {
            "scheduler": wall_scheduler(),
            "policy": self._settings.retry_policy(),
            "deadline_ms": deadline_ms,
        }

    def send_message(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        return call_with_retries(
            lambda: self._send_once(remote, msg),
            self._settings.message_retries,
            **self._retry_kwargs(self._settings.deadline_for(msg)),
        )

    def send_message_with_timeout(
        self, remote: Endpoint, msg: RapidMessage, timeout_ms: int
    ) -> Promise:
        """send_message with an explicit per-attempt deadline, for callers
        whose message class deserves a different budget than the settings
        table (the gateway's decision-packet deliveries use the join-class
        deadline: the receiving member may be mid-bootstrap of a new view,
        busy rather than dead)."""
        return call_with_retries(
            lambda: self._send_once(remote, msg, timeout_ms),
            self._settings.message_retries,
            **self._retry_kwargs(
                timeout_ms * (self._settings.message_retries + 1)
            ),
        )

    def send_message_best_effort(self, remote: Endpoint, msg: RapidMessage) -> Promise:
        return self._send_once(remote, msg)

    # -- observability -------------------------------------------------------

    def transport_digest(self) -> Dict[str, float]:
        """Per-peer outbound queue depths (bytes waiting in each channel's
        coalescing buffer), merged into cluster_status()/statusz next to the
        counter snapshot. A persistently deep queue is the backpressure
        signature of a slow-reading peer."""
        with self._conn_lock:
            connections = dict(self._connections)
        digest: Dict[str, float] = {}
        for remote, conn in sorted(connections.items(), key=lambda kv: str(kv[0])):
            if not conn.closed:
                digest[f"msg.queue_depth{{peer={remote}}}"] = float(
                    conn.pending_bytes()
                )
        return digest

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self) -> None:
        self._framed.shutdown()
        self._shutdown_client_half()

    def _shutdown_client_half(self) -> None:
        """Close every cached outbound connection and stop the I/O reactor
        (shared with subclasses that replace the server half, e.g. the
        native-reactor transport)."""
        with self._conn_lock:
            connections = list(self._connections.values())
            self._connections.clear()
        for conn in connections:
            conn.close()
        self._io.stop()
