"""Loopback port-block reservation for examples, tools, and tests.

A rapid node's endpoint is its ring identity (MembershipView orders members
by seeded endpoint hashes), so it must be chosen BEFORE the server binds --
kernel-assigned port 0 cannot flow through the protocol. Everything that
launches multi-node scenarios on one machine therefore picks a base port and
derives node addresses base+i; this helper probes the whole block bindable
at pick time, so two concurrent batteries/examples cannot collide on
already-listening ports (the failure mode of blind random picks)."""

from __future__ import annotations

import random
import socket


def free_port_base(count: int = 1, tries: int = 64,
                   lo: int = 20000, hi: int = 32000) -> int:
    """A base port whose whole [base, base+count] block binds NOW.

    ``hi`` stays below the kernel's ephemeral source-port floor (32768 by
    default): a reserved port inside that range can be stolen between
    reservation and bind by any outgoing connection's kernel-assigned
    source port -- observed as EADDRINUSE on agents binding minutes after
    their block was probed free."""
    for _ in range(tries):
        base = random.randint(lo, hi - count - 1)
        socks = []
        try:
            for off in range(count + 1):
                s = socket.socket()
                s.bind(("127.0.0.1", base + off))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError(f"no free block of {count} ports after {tries} tries")


def free_port() -> int:
    return free_port_base(1)
