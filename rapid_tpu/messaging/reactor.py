"""Event-loop I/O core for the messaging plane.

One ``Reactor`` is one I/O thread multiplexing every socket of a transport
through a ``selectors`` loop -- the replacement for the thread-per-connection
design (a reader thread per ``_Connection``, a thread per accepted socket,
plus the shared ``_TimeoutWheel`` deadline thread). The reference stacks its
transport on Netty's shared NIO event-loop group the same way
(SharedResources.java:63-67); this is that shape in pure Python, sharing the
frame format and correlation protocol with the native epoll reactor
(native/rapid_io.cpp).

Three mechanisms carry the throughput win:

- **Connection multiplexing**: every channel (dialed or accepted) registers
  with one selector; one thread wakes once per readable/writable batch
  instead of one blocked thread per socket.
- **Write coalescing**: ``Channel.send_frame`` only queues buffers; the
  reactor drains each dirty channel once per tick with a single
  scatter-gather ``sendmsg`` covering every queued frame -- one syscall per
  tick per peer, not one per message.
- **Zero-copy framing**: the read path parses length-prefixed frames as
  ``memoryview`` slices over the receive buffer (released before
  compaction); the write path keeps header and body as separate iovecs, so
  no per-frame ``bytes`` concatenation happens on either side.

Timers (``call_later``) replace the timeout wheel: request deadlines become
heap entries drained by the same loop. Nonblocking ``connect`` support lets
dials ride the reactor too, so a dead peer never blocks a sender thread.

Lockdep story: the reactor never holds two locks at once. Senders take
``Channel._wlock`` to queue buffers, release it, then take ``Reactor._lock``
to mark the channel dirty; the loop takes ``Reactor._lock`` to swap out the
dirty/pending/timer sets, releases it, then takes each channel's ``_wlock``
to swap its buffer queue -- every syscall (``sendmsg``/``recv``/``select``)
runs with no lock held.
"""

from __future__ import annotations

import errno
import heapq
import itertools
import logging
import os
import selectors
import socket
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..observability import MSG_BATCH_BUCKETS
from ..runtime.lockdep import make_lock
from .codec import HEADER

LOG = logging.getLogger(__name__)

MAX_FRAME_BYTES = 64 * 1024 * 1024
_HEADER_SIZE = HEADER.size
_RECV_CHUNK = 1 << 18
# conservative scatter-gather window (Linux IOV_MAX is 1024); larger queues
# drain in consecutive sendmsg calls within the same tick
_IOV_MAX = 512
_HAS_SENDMSG = hasattr(socket.socket, "sendmsg")


class Timer:
    """Cancellable entry on a reactor's timer heap. ``cancel`` is a flag
    flip (GIL-atomic); a cancelled timer is skipped when it pops."""

    __slots__ = ("fn", "cancelled")

    def __init__(self, fn: Callable[[], None]) -> None:
        self.fn = fn
        self.cancelled = False  # guarded-by: gil-atomic

    def cancel(self) -> None:
        self.cancelled = True


class Reactor:
    """One I/O thread: selector + timer heap + pending-callable queue +
    dirty-channel flush set. The thread starts lazily on first use and runs
    as a daemon; ``stop()`` tears down every attached channel."""

    def __init__(self, name: str = "rapid-io") -> None:
        self._name = name
        self._selector = selectors.DefaultSelector()
        self._lock = make_lock("Reactor._lock")
        self._thread: Optional[threading.Thread] = None  # guarded-by: _lock
        self._running = True  # guarded-by: _lock
        self._stopped = False  # guarded-by: _lock
        self._pending: List[Callable[[], None]] = []  # guarded-by: _lock
        # dict used as an ordered set: flush order == first-dirty order
        self._dirty: Dict["Channel", bool] = {}  # guarded-by: _lock
        self._timers: List[Tuple[float, int, Timer]] = []  # guarded-by: _lock
        self._seq = itertools.count()
        self._channels: set = set()  # guarded-by: _lock
        # wake pipe: a byte written here breaks select() out of its wait
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._selector.register(self._wake_r, selectors.EVENT_READ, self)

    # -- scheduling (any thread) --------------------------------------------

    def call_soon(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if self._stopped:
                run_inline = True
            else:
                self._ensure_thread_locked()
                self._pending.append(fn)
                run_inline = False
        if run_inline:
            fn()  # post-stop cleanup (e.g. a late close) runs in place
        else:
            self._wake()

    def call_later(self, delay_s: float, fn: Callable[[], None]) -> Timer:
        timer = Timer(fn)
        with self._lock:
            self._ensure_thread_locked()
            heapq.heappush(
                self._timers,
                (time.monotonic() + delay_s, next(self._seq), timer),
            )
        self._wake()
        return timer

    def notify_dirty(self, channel: "Channel") -> None:
        with self._lock:
            self._ensure_thread_locked()
            self._dirty[channel] = True
        self._wake()

    @property
    def stopped(self) -> bool:
        with self._lock:
            return self._stopped

    def on_reactor_thread(self) -> bool:
        return threading.current_thread() is self._thread

    # -- channel lifecycle ---------------------------------------------------

    def _attach(self, channel: "Channel") -> None:
        with self._lock:
            self._ensure_thread_locked()
            self._channels.add(channel)
        if self.on_reactor_thread():
            self._register(channel)
        else:
            self.call_soon(lambda: self._register(channel))

    def _register(self, channel: "Channel") -> None:
        if channel._closed:  # noqa: SLF001 -- reactor/channel are one module
            return
        try:
            self._selector.register(channel.sock, channel._interest, channel)  # noqa: SLF001
            channel._registered = True  # noqa: SLF001
        except (KeyError, ValueError, OSError):
            channel.close(OSError(errno.EBADF, "socket not registrable"))

    def _detach(self, channel: "Channel") -> None:
        def finish() -> None:
            with self._lock:
                self._channels.discard(channel)
                self._dirty.pop(channel, None)
            if channel._registered:  # noqa: SLF001
                channel._registered = False  # noqa: SLF001
                try:
                    self._selector.unregister(channel.sock)
                except (KeyError, ValueError, OSError):
                    pass
            try:
                channel.sock.close()
            except OSError:
                pass

        if self.on_reactor_thread():
            finish()
        else:
            self.call_soon(finish)

    # -- loop ----------------------------------------------------------------

    def _ensure_thread_locked(self) -> None:
        if self._thread is None and self._running and not self._stopped:
            self._thread = threading.Thread(
                target=self._run, name=self._name, daemon=True
            )
            self._thread.start()

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\0")
        except (BlockingIOError, OSError):
            pass  # pipe full means a wake is already pending; closed = stop

    def _on_events(self, mask: int) -> None:
        """Drain the wake pipe (the reactor registers itself for it)."""
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def _run(self) -> None:
        while True:
            with self._lock:
                if not self._running:
                    break
                while self._timers and self._timers[0][2].cancelled:
                    heapq.heappop(self._timers)
                if self._pending or self._dirty:
                    timeout: Optional[float] = 0.0
                elif self._timers:
                    timeout = max(0.0, self._timers[0][0] - time.monotonic())
                else:
                    timeout = None
            try:
                events = self._selector.select(timeout)
            except OSError:
                events = []
            for key, mask in events:
                handler = key.data
                try:
                    handler._on_events(mask)  # noqa: SLF001
                except Exception:  # noqa: BLE001 -- one endpoint never kills the loop
                    LOG.exception("reactor handler failed")
            now = time.monotonic()
            with self._lock:
                due: List[Timer] = []
                while self._timers and (
                    self._timers[0][2].cancelled or self._timers[0][0] <= now
                ):
                    _, _, timer = heapq.heappop(self._timers)
                    if not timer.cancelled:
                        due.append(timer)
                pending, self._pending = self._pending, []
                dirty = list(self._dirty)
                self._dirty.clear()
            for timer in due:
                try:
                    timer.fn()
                except Exception:  # noqa: BLE001
                    LOG.exception("reactor timer failed")
            for fn in pending:
                try:
                    fn()
                except Exception:  # noqa: BLE001
                    LOG.exception("reactor callback failed")
            for channel in dirty:
                channel.flush()
        self._finalize()

    def _finalize(self) -> None:
        with self._lock:
            self._stopped = True
            channels = list(self._channels)
            self._channels.clear()
            self._pending.clear()
            self._dirty.clear()
            del self._timers[:]
        for channel in channels:
            try:
                channel.close(ConnectionError("reactor stopped"))
            except Exception:  # noqa: BLE001
                LOG.exception("channel close during reactor stop failed")
        try:
            self._selector.close()
        except OSError:
            pass
        for sock in (self._wake_r, self._wake_w):
            try:
                sock.close()
            except OSError:
                pass

    def stop(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._running = False
            thread = self._thread
        self._wake()
        if thread is None:
            self._finalize()
        elif thread is not threading.current_thread():
            thread.join(timeout=2.0)


class Channel:
    """One nonblocking socket on a reactor: framed zero-copy reads, queued
    scatter-gather writes, optional in-flight nonblocking connect.

    ``on_frame(channel, view)`` receives each complete frame as a
    ``memoryview`` valid only for the duration of the call (copy with
    ``bytes(view)`` to retain). ``on_close(channel, exc)`` fires exactly
    once, from whichever thread closed the channel; ``on_connect(channel)``
    fires on the reactor thread when an outbound dial completes.
    """

    def __init__(
        self,
        reactor: Reactor,
        sock: socket.socket,
        on_frame: Callable[["Channel", memoryview], None],
        *,
        on_close: Optional[Callable[["Channel", Optional[BaseException]], None]] = None,
        on_connect: Optional[Callable[["Channel"], None]] = None,
        metrics=None,
        connecting: bool = False,
        connect_timeout_s: Optional[float] = None,
    ) -> None:
        sock.setblocking(False)
        try:
            # coalescing happens in the channel queue, not the kernel: turn
            # Nagle off so a flushed batch leaves immediately
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.reactor = reactor
        self.sock = sock
        self.connected = not connecting
        self._on_frame = on_frame
        self._on_close = on_close  # guarded-by: _wlock
        self._on_connect = on_connect
        self._metrics = metrics
        self._rbuf = bytearray()  # guarded-by: reactor-thread
        self._wlock = make_lock("Channel._wlock")
        self._wbufs: Deque[memoryview] = deque()  # guarded-by: _wlock
        self._wbytes = 0  # guarded-by: _wlock
        self._wframes = 0  # guarded-by: _wlock
        self._closed = False  # guarded-by: _wlock
        self._registered = False  # guarded-by: reactor-thread
        self._interest = (
            selectors.EVENT_WRITE if connecting else selectors.EVENT_READ
        )  # guarded-by: reactor-thread
        self._connect_timer: Optional[Timer] = None
        if connecting and connect_timeout_s is not None:
            self._connect_timer = reactor.call_later(
                connect_timeout_s, self._connect_timed_out
            )
        reactor._attach(self)  # noqa: SLF001 -- reactor/channel are one module

    @classmethod
    def connect(
        cls,
        reactor: Reactor,
        address: Tuple[str, int],
        timeout_s: float,
        on_frame: Callable[["Channel", memoryview], None],
        **kwargs,
    ) -> "Channel":
        """Dial without blocking: ``connect_ex`` starts the handshake and
        the reactor observes completion as writability. Frames queued while
        connecting are flushed the moment the connect completes; on failure
        or timeout the channel closes and ``on_close`` fires."""
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        err = sock.connect_ex(address)
        if err not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK):
            try:
                sock.close()
            except OSError:
                pass
            raise OSError(err, os.strerror(err))
        return cls(
            reactor, sock, on_frame,
            connecting=(err != 0), connect_timeout_s=timeout_s, **kwargs,
        )

    # -- write side (any thread) --------------------------------------------

    def send_frame(self, frame: bytes) -> None:
        """Queue one length-prefixed frame. Header and body stay separate
        buffers all the way to the scatter-gather syscall -- no per-frame
        concatenation."""
        self.send_buffers((HEADER.pack(len(frame)), frame))

    def send_buffers(self, buffers: Tuple[bytes, ...], frames: int = 1) -> None:
        total = 0
        views = []
        for buf in buffers:
            if len(buf):
                views.append(memoryview(buf))
                total += len(buf)
        with self._wlock:
            if self._closed:
                raise OSError(errno.EPIPE, "channel closed")
            self._wbufs.extend(views)
            self._wbytes += total
            self._wframes += frames
        if self._metrics is not None:
            self._metrics.incr("msg.sent", frames)
        self.reactor.notify_dirty(self)

    def pending_bytes(self) -> int:
        with self._wlock:
            return self._wbytes

    def pending_frames(self) -> int:
        with self._wlock:
            return self._wframes

    def fileno(self) -> int:
        try:
            return self.sock.fileno()
        except OSError:
            return -1

    # -- reactor-thread handlers --------------------------------------------

    def _on_events(self, mask: int) -> None:
        if not self.connected and mask & selectors.EVENT_WRITE:
            err = self.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            if err:
                self.close(OSError(err, os.strerror(err)))
                return
            self._complete_connect()
            return  # _complete_connect flushed; reads start next tick
        if mask & selectors.EVENT_READ:
            self._on_readable()
        if mask & selectors.EVENT_WRITE and self.connected:
            self.flush()

    def _complete_connect(self) -> None:
        self.connected = True
        if self._connect_timer is not None:
            self._connect_timer.cancel()
            self._connect_timer = None
        self._update_interest(selectors.EVENT_READ)
        if self._on_connect is not None:
            try:
                self._on_connect(self)
            except Exception:  # noqa: BLE001
                LOG.exception("on_connect callback failed")
        self.flush()

    def _connect_timed_out(self) -> None:
        if not self.connected:
            self.close(socket.timeout("connect timed out"))

    def _update_interest(self, mask: int) -> None:
        if mask == self._interest:
            return
        self._interest = mask
        if self._registered:
            try:
                self.reactor._selector.modify(self.sock, mask, self)  # noqa: SLF001
            except (KeyError, ValueError, OSError):
                pass

    def _on_readable(self) -> None:
        try:
            data = self.sock.recv(_RECV_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError as e:
            self.close(e)
            return
        if not data:
            self.close(None)  # clean EOF
            return
        if self._metrics is not None:
            self._metrics.incr("msg.bytes_received", len(data))
        self._rbuf += data
        consumed, frames, err = self._parse_frames()
        if consumed:
            try:
                del self._rbuf[:consumed]
            except BufferError:
                # a frame handler leaked a view past its call; fall back to
                # a copying compaction rather than corrupting the stream
                self._rbuf = bytearray(bytes(self._rbuf[consumed:]))
        if frames and self._metrics is not None:
            self._metrics.incr("msg.received", frames)
        if err is not None:
            self.close(err)

    def _parse_frames(self) -> Tuple[int, int, Optional[BaseException]]:
        """Dispatch every complete frame in the read buffer as a memoryview
        slice. All views are released before returning, so the caller may
        compact the buffer in place."""
        total = len(self._rbuf)
        offset = 0
        frames = 0
        err: Optional[BaseException] = None
        view = memoryview(self._rbuf)
        try:
            while not self._closed and total - offset >= _HEADER_SIZE:
                (length,) = HEADER.unpack_from(view, offset)
                if length > MAX_FRAME_BYTES:
                    err = ValueError(f"oversized frame: {length}")
                    break
                end = offset + _HEADER_SIZE + length
                if end > total:
                    break
                frame = view[offset + _HEADER_SIZE:end]
                try:
                    self._on_frame(self, frame)
                except Exception as e:  # noqa: BLE001 -- poisoned frame closes
                    # the connection, never the reactor; drop the traceback
                    # so its frames stop pinning buffer views
                    e.__traceback__ = None
                    err = e
                finally:
                    try:
                        frame.release()
                    except BufferError:
                        pass
                if err is not None:
                    break
                offset = end
                frames += 1
        finally:
            try:
                view.release()
            except BufferError:
                pass
        return offset, frames, err

    def flush(self) -> None:
        """Drain the outbound queue: swap it out under the channel lock,
        then issue as few ``sendmsg`` syscalls as the iovec window allows
        with no lock held. Reactor thread only. Partial writes re-queue at
        the front and arm write interest."""
        if not self.connected:
            return
        with self._wlock:
            if self._closed or not self._wbufs:
                drained = True
                buffers: List[memoryview] = []
                frames = 0
            else:
                drained = False
                buffers = list(self._wbufs)
                self._wbufs.clear()
                frames = self._wframes
                self._wframes = 0
                self._wbytes = 0
        if drained:
            if self._interest & selectors.EVENT_WRITE:
                self._update_interest(selectors.EVENT_READ)
            return
        sent_bytes = 0
        syscalls = 0
        error: Optional[OSError] = None
        idx = 0
        while idx < len(buffers):
            window = buffers[idx:idx + _IOV_MAX]
            want = sum(len(b) for b in window)
            try:
                if _HAS_SENDMSG:
                    n = self.sock.sendmsg(window)
                else:  # pragma: no cover - platforms without scatter-gather
                    n = self.sock.send(b"".join(window))
            except (BlockingIOError, InterruptedError):
                break
            except OSError as e:
                error = e
                break
            syscalls += 1
            sent_bytes += n
            remaining = n
            while remaining > 0:
                buf = buffers[idx]
                if remaining >= len(buf):
                    remaining -= len(buf)
                    idx += 1
                else:
                    buffers[idx] = buf[remaining:]
                    remaining = 0
            if n < want:
                break  # kernel buffer full; wait for writability
        if self._metrics is not None and syscalls:
            self._metrics.incr("msg.flush_syscalls", syscalls)
            self._metrics.incr("msg.bytes_sent", sent_bytes)
            self._metrics.observe(
                "msg.batch_size", frames, buckets=MSG_BATCH_BUCKETS
            )
        if error is not None:
            self.close(error)
            return
        leftover = buffers[idx:]
        if leftover:
            nbytes = sum(len(b) for b in leftover)
            with self._wlock:
                if not self._closed:
                    self._wbufs.extendleft(reversed(leftover))
                    self._wbytes += nbytes
            self._update_interest(
                selectors.EVENT_READ | selectors.EVENT_WRITE
            )
        elif self._interest & selectors.EVENT_WRITE:
            self._update_interest(selectors.EVENT_READ)

    # -- lifecycle -----------------------------------------------------------

    def close(self, exc: Optional[BaseException] = None) -> None:
        with self._wlock:
            if self._closed:
                return
            self._closed = True
            self._wbufs.clear()
            self._wbytes = 0
            self._wframes = 0
            callback = self._on_close
            self._on_close = None
        if self._connect_timer is not None:
            self._connect_timer.cancel()
        try:
            # immediate FIN even when called off the reactor thread; the fd
            # itself is closed on the reactor thread via _detach
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.reactor._detach(self)  # noqa: SLF001
        if callback is not None:
            try:
                callback(self, exc)
            except Exception:  # noqa: BLE001
                LOG.exception("on_close callback failed")


class Acceptor:
    """A listening socket on the reactor: accepts until EAGAIN each tick
    and hands fresh sockets to ``on_accept`` on the reactor thread."""

    def __init__(
        self,
        reactor: Reactor,
        sock: socket.socket,
        on_accept: Callable[[socket.socket], None],
    ) -> None:
        sock.setblocking(False)
        self.reactor = reactor
        self.sock = sock
        self._on_accept = on_accept
        self._closed = False
        self._registered = False  # guarded-by: reactor-thread
        self._interest = selectors.EVENT_READ
        reactor._attach(self)  # type: ignore[arg-type]  # duck-typed channel

    def _on_events(self, mask: int) -> None:
        while True:
            try:
                conn, _ = self.sock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            try:
                self._on_accept(conn)
            except Exception:  # noqa: BLE001 -- one bad accept never kills the loop
                LOG.exception("accept handler failed")
                try:
                    conn.close()
                except OSError:
                    pass

    def close(self, exc: Optional[BaseException] = None) -> None:
        del exc  # listening sockets owe nobody an error; duck-typed Channel
        if self._closed:
            return
        self._closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.reactor._detach(self)  # type: ignore[arg-type]  # noqa: SLF001


# Process-wide reactor for clients that have no transport of their own
# (GatewayRoutedClient's single upstream connection): lazily created on the
# first dial, replaced if a test stopped it, never stopped by its users --
# the same lifetime discipline as the old module-global timeout wheel.
_shared_lock = make_lock("reactor._shared_lock")
_shared: Optional[Reactor] = None


def shared_reactor() -> Reactor:
    global _shared
    with _shared_lock:
        if _shared is None or _shared.stopped:
            _shared = Reactor(name="rapid-io-shared")
        return _shared
