"""The Rapid wire schema, constructed programmatically.

Wire compatibility with the reference is defined by field numbers and types
(rapid/src/main/proto/rapid.proto:13-206), not by .proto source text -- so the
schema lives here as a table and is compiled into protobuf message classes at
import time via FileDescriptorProto. A rapid-tpu node speaking this schema
over the gRPC transport is byte-compatible with JVM Rapid peers
(tests/test_grpc_transport.py proves it by round-tripping through classes
protoc-generated from the reference's own .proto).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

_F = descriptor_pb2.FieldDescriptorProto

_SCALARS = {
    "bytes": _F.TYPE_BYTES,
    "string": _F.TYPE_STRING,
    "int32": _F.TYPE_INT32,
    "int64": _F.TYPE_INT64,
}

# (name, type, number, repeated?) -- type "M:Name" = message, "E:Name" = enum
_MESSAGES: Dict[str, List[Tuple[str, str, int, bool]]] = {
    "Endpoint": [("hostname", "bytes", 1, False), ("port", "int32", 2, False)],
    "NodeId": [("high", "int64", 1, False), ("low", "int64", 2, False)],
    "PreJoinMessage": [
        ("sender", "M:Endpoint", 1, False),
        ("nodeId", "M:NodeId", 2, False),
        ("ringNumber", "int32", 3, True),
        ("configurationId", "int64", 4, False),
    ],
    "JoinMessage": [
        ("sender", "M:Endpoint", 1, False),
        ("nodeId", "M:NodeId", 2, False),
        ("ringNumber", "int32", 3, True),
        ("configurationId", "int64", 4, False),
        ("metadata", "M:Metadata", 5, False),
    ],
    "JoinResponse": [
        ("sender", "M:Endpoint", 1, False),
        ("statusCode", "E:JoinStatusCode", 2, False),
        ("configurationId", "int64", 3, False),
        ("endpoints", "M:Endpoint", 4, True),
        ("identifiers", "M:NodeId", 5, True),
        ("metadataKeys", "M:Endpoint", 6, True),
        ("metadataValues", "M:Metadata", 7, True),
    ],
    "BatchedAlertMessage": [
        ("sender", "M:Endpoint", 1, False),
        ("messages", "M:AlertMessage", 3, True),
    ],
    "AlertMessage": [
        ("edgeSrc", "M:Endpoint", 1, False),
        ("edgeDst", "M:Endpoint", 2, False),
        ("edgeStatus", "E:EdgeStatus", 3, False),
        ("configurationId", "int64", 4, False),
        ("ringNumber", "int32", 5, True),
        ("nodeId", "M:NodeId", 6, False),
        ("metadata", "M:Metadata", 7, False),
    ],
    "Response": [],
    "FastRoundPhase2bMessage": [
        ("sender", "M:Endpoint", 1, False),
        ("configurationId", "int64", 2, False),
        ("endpoints", "M:Endpoint", 3, True),
    ],
    "Rank": [("round", "int32", 1, False), ("nodeIndex", "int32", 2, False)],
    "Phase1aMessage": [
        ("sender", "M:Endpoint", 1, False),
        ("configurationId", "int64", 2, False),
        ("rank", "M:Rank", 3, False),
    ],
    "Phase1bMessage": [
        ("sender", "M:Endpoint", 1, False),
        ("configurationId", "int64", 2, False),
        ("rnd", "M:Rank", 3, False),
        ("vrnd", "M:Rank", 4, False),
        ("vval", "M:Endpoint", 5, True),
    ],
    "Phase2aMessage": [
        ("sender", "M:Endpoint", 1, False),
        ("configurationId", "int64", 2, False),
        ("rnd", "M:Rank", 3, False),
        ("vval", "M:Endpoint", 5, True),
    ],
    "Phase2bMessage": [
        ("sender", "M:Endpoint", 1, False),
        ("configurationId", "int64", 2, False),
        ("rnd", "M:Rank", 3, False),
        ("endpoints", "M:Endpoint", 4, True),
    ],
    "ConsensusResponse": [],
    "LeaveMessage": [("sender", "M:Endpoint", 1, False)],
    "ProbeMessage": [("sender", "M:Endpoint", 1, False), ("payload", "bytes", 3, True)],
    "ProbeResponse": [("status", "E:NodeStatus", 1, False)],
    # rapid-tpu extensions (not in the reference rapid.proto). Proto3 peers
    # that predate them ignore unknown fields/oneof entries natively, so the
    # schema stays wire-compatible in both directions.
    "TraceContext": [
        ("traceId", "int64", 1, False),
        ("parentSpanId", "int64", 2, False),
        ("origin", "string", 3, False),
        ("flags", "int32", 4, False),
    ],
    # forensics plane: hybrid logical clock stamp; rides outside the request
    # oneof like TraceContext, so pre-forensics peers skip it natively
    "HlcStamp": [
        ("physicalMs", "int64", 1, False),
        ("logical", "int64", 2, False),
        ("incarnation", "int64", 3, False),
    ],
    "ClusterStatusRequest": [
        ("sender", "M:Endpoint", 1, False),
        ("includeHistory", "int32", 2, False),
    ],
    "ClusterStatusResponse": [
        ("sender", "M:Endpoint", 1, False),
        ("configurationId", "int64", 2, False),
        ("membershipSize", "int32", 3, False),
        ("reportsTracked", "int32", 4, False),
        ("preProposalSize", "int32", 5, False),
        ("proposalSize", "int32", 6, False),
        ("updatesInProgress", "int32", 7, False),
        ("consensusDecided", "int32", 8, False),
        ("consensusVotes", "int32", 9, False),
        ("metricNames", "string", 10, True),
        ("metricValues", "int64", 11, True),
        ("journal", "string", 12, True),
        # placement plane exposure; proto3 unknown-field tolerance keeps
        # peers without placement interoperable
        ("placementVersion", "int64", 13, False),
        ("placementPartitions", "int32", 14, False),
        ("placementOwned", "int32", 15, False),
        # handoff plane exposure: session counts plus the local partition
        # store's (id, fingerprint) digest as parallel arrays
        ("handoffInFlight", "int32", 16, False),
        ("handoffCompleted", "int32", 17, False),
        ("handoffFailed", "int32", 18, False),
        ("handoffPartitions", "int64", 19, True),
        ("handoffFingerprints", "int64", 20, True),
        # serving plane exposure: request counters plus the local replica's
        # (partition id, leader "host:port") digest as parallel arrays
        ("servingGets", "int64", 21, False),
        ("servingPuts", "int64", 22, False),
        ("servingPutAcks", "int64", 23, False),
        ("servingPartitions", "int64", 24, True),
        ("servingLeaders", "string", 25, True),
        # failure-detector plane exposure: per-edge (subject, rtt micros,
        # suspicion milli) digest plus per-tier adapted FD parameters as
        # parallel arrays (integer units: proto3 floats are deliberately
        # absent from this schema)
        ("fdSubjects", "string", 26, True),
        ("fdRttMicros", "int64", 27, True),
        ("fdSuspicionMilli", "int64", 28, True),
        ("fdTiers", "string", 29, True),
        ("fdTierIntervalMs", "int64", 30, True),
        ("fdTierThreshold", "int64", 31, True),
        ("fdTierFlushMs", "int64", 32, True),
        # profiling plane exposure: the metric history-ring tail as JSON
        # lines (one snapshot per line, MetricsHistory.to_wire)
        ("history", "string", 33, True),
        # durability plane exposure: WAL segment count, last snapshot
        # version, and records replayed by the most recent recovery
        ("durabilitySegments", "int64", 34, False),
        ("durabilitySnapshotVersion", "int64", 35, False),
        ("durabilityReplayed", "int64", 36, False),
        # SLO plane exposure: per-alert ("slo:window" name, short-window
        # burn rate in thousandths, firing flag, attributed churn trace
        # id) as parallel arrays (integer milli units: no proto3 floats
        # in this schema)
        ("sloNames", "string", 37, True),
        ("sloBurnMilli", "int64", 38, True),
        ("sloFiring", "int64", 39, True),
        ("sloAttributedTrace", "int64", 40, True),
        # forensics plane exposure: journal truncation accounting plus the
        # node's current hybrid-logical-clock reading (append-only per the
        # PR 3/13 pattern: old peers ignore 41+, new peers read zeros from
        # old peers)
        ("journalDropped", "int64", 41, False),
        ("journalCapacity", "int64", 42, False),
        ("hlcPhysicalMs", "int64", 43, False),
        ("hlcLogical", "int64", 44, False),
        ("hlcIncarnation", "int64", 45, False),
        # hierarchy plane exposure: the member's cell, its cell-local
        # size, the parent (leader-set) configuration id, the composed
        # global fingerprint, and the per-cell rows of the composed view
        # as parallel arrays (append-only per the PR 3/13 pattern)
        ("cellId", "int64", 46, False),
        ("cellSize", "int64", 47, False),
        ("parentConfigurationId", "int64", 48, False),
        ("globalFingerprint", "int64", 49, False),
        ("globalCells", "int64", 50, True),
        ("globalEpochs", "int64", 51, True),
        ("globalSizes", "int64", 52, True),
        ("globalLeaders", "string", 53, True),
    ],
    "HandoffRequest": [
        ("sender", "M:Endpoint", 1, False),
        ("sessionId", "int64", 2, False),
        ("partition", "int64", 3, False),
        ("offset", "int64", 4, False),
        ("length", "int64", 5, False),
        ("mapVersion", "int64", 6, False),
    ],
    "HandoffChunk": [
        ("sender", "M:Endpoint", 1, False),
        ("sessionId", "int64", 2, False),
        ("partition", "int64", 3, False),
        ("offset", "int64", 4, False),
        ("data", "bytes", 5, False),
        ("totalSize", "int64", 6, False),
        ("fingerprint", "int64", 7, False),
        ("status", "int32", 8, False),
    ],
    "HandoffAck": [
        ("sender", "M:Endpoint", 1, False),
        ("sessionId", "int64", 2, False),
        ("partition", "int64", 3, False),
        ("fingerprint", "int64", 4, False),
        ("mapVersion", "int64", 5, False),
    ],
    "Get": [
        ("sender", "M:Endpoint", 1, False),
        ("key", "bytes", 2, False),
        ("quorum", "int32", 3, False),
        ("mapVersion", "int64", 4, False),
    ],
    "Put": [
        ("sender", "M:Endpoint", 1, False),
        ("key", "bytes", 2, False),
        ("value", "bytes", 3, False),
        ("requestId", "int64", 4, False),
        ("replicate", "int32", 5, False),
        ("version", "int64", 6, False),
        ("mapVersion", "int64", 7, False),
    ],
    "PutAck": [
        ("sender", "M:Endpoint", 1, False),
        ("status", "int32", 2, False),
        ("key", "bytes", 3, False),
        ("value", "bytes", 4, False),
        ("version", "int64", 5, False),
        ("requestId", "int64", 6, False),
        ("leader", "M:Endpoint", 7, False),
        ("mapVersion", "int64", 8, False),
    ],
    # transport batch envelope (messaging PR): whole RapidRequest envelopes
    # nested so each inner request keeps its own oneof discriminator (and
    # trace context); forward reference resolves at pool Add() time
    "MessageBatch": [
        ("sender", "M:Endpoint", 1, False),
        ("requests", "M:RapidRequest", 2, True),
    ],
    # hierarchy plane (PR 19): a leader's announcement of its cell's row
    # (leader-to-leader) and the composed global view a leader fans back
    # into its own cell (leader-to-cell), as parallel per-cell arrays
    "CellDigestMessage": [
        ("sender", "M:Endpoint", 1, False),
        ("cell", "int64", 2, False),
        ("configurationId", "int64", 3, False),
        ("membershipSize", "int64", 4, False),
        ("leader", "string", 5, False),
        ("fingerprint", "int64", 6, False),
        ("parentRound", "int64", 7, False),
    ],
    "GlobalViewMessage": [
        ("sender", "M:Endpoint", 1, False),
        ("parentConfigurationId", "int64", 2, False),
        ("globalFingerprint", "int64", 3, False),
        ("cells", "int64", 4, True),
        ("epochs", "int64", 5, True),
        ("sizes", "int64", 6, True),
        ("leaders", "string", 7, True),
        ("fingerprints", "int64", 8, True),
        ("parentRound", "int64", 9, False),
    ],
}

# Trace context rides OUTSIDE the request oneof (a sibling of `content`):
# field 15 on RapidRequest, chosen above the reference's last oneof number
# so a JVM peer's decoder skips it as an unknown field.
TRACE_CTX_FIELD_NUMBER = 15

# The hybrid-logical-clock stamp rides outside the oneof too: field 18,
# the next number above the oneof's current maximum (17), reserved the
# same way 15 is -- future oneof entries must skip both.
HLC_FIELD_NUMBER = 18

# The oneof envelopes (rapid.proto:21-45): (field, message type, number)
_REQUEST_ONEOF = [
    ("preJoinMessage", "PreJoinMessage", 1),
    ("joinMessage", "JoinMessage", 2),
    ("batchedAlertMessage", "BatchedAlertMessage", 3),
    ("probeMessage", "ProbeMessage", 4),
    ("fastRoundPhase2bMessage", "FastRoundPhase2bMessage", 5),
    ("phase1aMessage", "Phase1aMessage", 6),
    ("phase1bMessage", "Phase1bMessage", 7),
    ("phase2aMessage", "Phase2aMessage", 8),
    ("phase2bMessage", "Phase2bMessage", 9),
    ("leaveMessage", "LeaveMessage", 10),
    ("clusterStatusRequest", "ClusterStatusRequest", 11),
    # 12/13 are handoff-plane extensions, 14/16 serving-plane extensions,
    # 17 the transport batch envelope; 15 is reserved for traceCtx
    # (TRACE_CTX_FIELD_NUMBER) and 18 for hlc (HLC_FIELD_NUMBER), both of
    # which ride outside the oneof -- the extension messages skip them, so
    # the oneof is contiguous from 1 except for those documented gaps
    ("handoffRequest", "HandoffRequest", 12),
    ("handoffAck", "HandoffAck", 13),
    ("get", "Get", 14),
    ("put", "Put", 16),
    ("messageBatch", "MessageBatch", 17),
    # 19/20 are hierarchy-plane extensions (18 is reserved for hlc above)
    ("cellDigestMessage", "CellDigestMessage", 19),
    ("globalViewMessage", "GlobalViewMessage", 20),
]
_RESPONSE_ONEOF = [
    ("joinResponse", "JoinResponse", 1),
    ("response", "Response", 2),
    ("consensusResponse", "ConsensusResponse", 3),
    ("probeResponse", "ProbeResponse", 4),
    ("clusterStatusResponse", "ClusterStatusResponse", 5),
    ("handoffChunk", "HandoffChunk", 6),
    ("putAck", "PutAck", 7),
]

_ENUMS = {
    "JoinStatusCode": [
        ("HOSTNAME_ALREADY_IN_RING", 0),
        ("UUID_ALREADY_IN_RING", 1),
        ("SAFE_TO_JOIN", 2),
        ("CONFIG_CHANGED", 3),
        ("MEMBERSHIP_REJECTED", 4),
    ],
    "EdgeStatus": [("UP", 0), ("DOWN", 1)],
    "NodeStatus": [("OK", 0), ("BOOTSTRAPPING", 1)],
}

PACKAGE = "remoting"
SERVICE = "MembershipService"
METHOD = "sendRequest"
GRPC_METHOD_PATH = f"/{PACKAGE}.{SERVICE}/{METHOD}"


def _field(
    name: str, type_spec: str, number: int, repeated: bool,
    oneof_index: Optional[int] = None,
) -> _F:
    f = _F()
    f.name = name
    f.number = number
    f.label = _F.LABEL_REPEATED if repeated else _F.LABEL_OPTIONAL
    if type_spec in _SCALARS:
        f.type = _SCALARS[type_spec]
    elif type_spec.startswith("M:"):
        f.type = _F.TYPE_MESSAGE
        f.type_name = f".{PACKAGE}.{type_spec[2:]}"
    elif type_spec.startswith("E:"):
        f.type = _F.TYPE_ENUM
        f.type_name = f".{PACKAGE}.{type_spec[2:]}"
    else:
        raise ValueError(type_spec)
    if oneof_index is not None:
        f.oneof_index = oneof_index
    return f


def _build_file() -> descriptor_pb2.FileDescriptorProto:
    file_proto = descriptor_pb2.FileDescriptorProto()
    file_proto.name = "rapid_tpu_wire.proto"
    file_proto.package = PACKAGE
    file_proto.syntax = "proto3"

    for enum_name, values in _ENUMS.items():
        enum = file_proto.enum_type.add()
        enum.name = enum_name
        for value_name, number in values:
            v = enum.value.add()
            v.name = value_name
            v.number = number

    # Metadata with its map<string, bytes> (maps are a nested entry message
    # with the map_entry option set)
    metadata = file_proto.message_type.add()
    metadata.name = "Metadata"
    entry = metadata.nested_type.add()
    entry.name = "MetadataEntry"
    entry.options.map_entry = True
    entry.field.append(_field("key", "string", 1, False))
    entry.field.append(_field("value", "bytes", 2, False))
    map_field = _field("metadata", "M:Metadata.MetadataEntry", 1, True)
    metadata.field.append(map_field)

    for msg_name, fields in _MESSAGES.items():
        msg = file_proto.message_type.add()
        msg.name = msg_name
        for name, type_spec, number, repeated in fields:
            msg.field.append(_field(name, type_spec, number, repeated))

    for envelope_name, entries in (
        ("RapidRequest", _REQUEST_ONEOF),
        ("RapidResponse", _RESPONSE_ONEOF),
    ):
        msg = file_proto.message_type.add()
        msg.name = envelope_name
        oneof = msg.oneof_decl.add()
        oneof.name = "content"
        for name, type_name, number in entries:
            msg.field.append(_field(name, f"M:{type_name}", number, False, oneof_index=0))
        if envelope_name == "RapidRequest":
            msg.field.append(_field(
                "traceCtx", "M:TraceContext", TRACE_CTX_FIELD_NUMBER, False,
            ))
            msg.field.append(_field(
                "hlc", "M:HlcStamp", HLC_FIELD_NUMBER, False,
            ))

    service = file_proto.service.add()
    service.name = SERVICE
    method = service.method.add()
    method.name = METHOD
    method.input_type = f".{PACKAGE}.RapidRequest"
    method.output_type = f".{PACKAGE}.RapidResponse"
    return file_proto


_pool = descriptor_pool.DescriptorPool()
_file_descriptor = _pool.Add(_build_file())


def _message_class(name: str):
    return message_factory.GetMessageClass(_pool.FindMessageTypeByName(f"{PACKAGE}.{name}"))


MSG = {
    name: _message_class(name)
    for name in list(_MESSAGES) + ["Metadata", "RapidRequest", "RapidResponse"]
}
