"""Bit-exact xxHash64 and the Rapid hash derivations built on it.

The reference orders its K rings and derives configuration identities with
`net.openhft.hashing.LongHashFunction.xx(seed)` (Utils.java:211-230,
MembershipView.java:47,535-547), which is the original 64-bit xxHash (XXH64)
with the primitive inputs interpreted in little-endian byte order. Cut-set and
configuration-ID parity with the JVM reference therefore requires a bit-exact
XXH64. Two independent implementations live here and cross-validate in tests:

- ``xxh64``: a scalar implementation in pure Python ints (the spec, readably).
- ``xxh64_batch``: a vectorized numpy/uint64 implementation hashing N padded
  byte rows at once -- the host-side control-plane path used to build rings for
  up to 100k virtual nodes between jitted device steps.

All arithmetic is modulo 2**64. Java compares the resulting hashes as *signed*
longs (Long.compare in Utils.AddressComparator, Utils.java:216-221), so ring
order uses the int64 view of these uint64 values.
"""

from __future__ import annotations

from typing import Iterable, Sequence, Tuple

import numpy as np

_MASK = (1 << 64) - 1

_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _MASK


def _round(acc: int, lane: int) -> int:
    return (_rotl((acc + lane * _P2) & _MASK, 31) * _P1) & _MASK


def _merge_round(acc: int, val: int) -> int:
    return ((acc ^ _round(0, val)) * _P1 + _P4) & _MASK


def xxh64(data: bytes, seed: int = 0) -> int:
    """XXH64 of ``data`` with ``seed``; returns an unsigned 64-bit int."""
    seed &= _MASK
    n = len(data)
    pos = 0

    if n >= 32:
        v1 = (seed + _P1 + _P2) & _MASK
        v2 = (seed + _P2) & _MASK
        v3 = seed
        v4 = (seed - _P1) & _MASK
        while pos + 32 <= n:
            v1 = _round(v1, int.from_bytes(data[pos : pos + 8], "little"))
            v2 = _round(v2, int.from_bytes(data[pos + 8 : pos + 16], "little"))
            v3 = _round(v3, int.from_bytes(data[pos + 16 : pos + 24], "little"))
            v4 = _round(v4, int.from_bytes(data[pos + 24 : pos + 32], "little"))
            pos += 32
        acc = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) + _rotl(v4, 18)) & _MASK
        acc = _merge_round(acc, v1)
        acc = _merge_round(acc, v2)
        acc = _merge_round(acc, v3)
        acc = _merge_round(acc, v4)
    else:
        acc = (seed + _P5) & _MASK

    acc = (acc + n) & _MASK

    while pos + 8 <= n:
        lane = int.from_bytes(data[pos : pos + 8], "little")
        acc = (_rotl(acc ^ _round(0, lane), 27) * _P1 + _P4) & _MASK
        pos += 8
    if pos + 4 <= n:
        lane = int.from_bytes(data[pos : pos + 4], "little")
        acc = (_rotl(acc ^ ((lane * _P1) & _MASK), 23) * _P2 + _P3) & _MASK
        pos += 4
    while pos < n:
        acc = (_rotl(acc ^ ((data[pos] * _P5) & _MASK), 11) * _P1) & _MASK
        pos += 1

    acc ^= acc >> 33
    acc = (acc * _P2) & _MASK
    acc ^= acc >> 29
    acc = (acc * _P3) & _MASK
    acc ^= acc >> 32
    return acc


def xxh64_int(value: int, seed: int = 0) -> int:
    """LongHashFunction.xx(seed).hashInt: XXH64 of the 4 LE bytes of an int32."""
    return xxh64((value & 0xFFFFFFFF).to_bytes(4, "little"), seed)


def xxh64_long(value: int, seed: int = 0) -> int:
    """LongHashFunction.xx(seed).hashLong: XXH64 of the 8 LE bytes of an int64."""
    return xxh64((value & _MASK).to_bytes(8, "little"), seed)


def endpoint_hash(hostname: bytes, port: int, seed: int) -> int:
    """Ring key for an endpoint under ring seed ``seed``.

    Utils.AddressComparator.computeHash (Utils.java:227-230):
    ``xx(seed).hashBytes(hostname) * 31 + xx(seed).hashInt(port)`` with Java
    long wraparound; returned unsigned (view as int64 for ordering).
    """
    return (xxh64(hostname, seed) * 31 + xxh64_int(port, seed)) & _MASK


def to_signed(h: int) -> int:
    """uint64 -> Java signed long, the comparison domain for ring order."""
    return h - (1 << 64) if h >= (1 << 63) else h


def configuration_id(
    identifiers: Iterable[Tuple[int, int]], endpoints: Iterable[Tuple[bytes, int]]
) -> int:
    """Chained configuration identity hash.

    MembershipView.Configuration.getConfigurationId (MembershipView.java:535-547):
    ``h = 1``, then ``h = h*37 + xx(0).hashLong(id.high/low)`` over identifiers in
    NodeId order, then ``h = h*37 + xx(0).hashBytes(hostname)`` and
    ``h = h*37 + xx(0).hashInt(port)`` over the ring-0 endpoint order.
    Returns a Java signed long.
    """
    h = 1
    for high, low in identifiers:
        h = (h * 37 + xxh64_long(high)) & _MASK
        h = (h * 37 + xxh64_long(low)) & _MASK
    for hostname, port in endpoints:
        h = (h * 37 + xxh64(hostname)) & _MASK
        h = (h * 37 + xxh64_int(port)) & _MASK
    return to_signed(h)


# ---------------------------------------------------------------------------
# Vectorized batch implementation (numpy, uint64 lanes)
# ---------------------------------------------------------------------------

_U64 = np.uint64


def _np_rotl(x: np.ndarray, r: int) -> np.ndarray:
    return (x << _U64(r)) | (x >> _U64(64 - r))


def _np_round(acc: np.ndarray, lane: np.ndarray) -> np.ndarray:
    return _np_rotl(acc + lane * _U64(_P2), 31) * _U64(_P1)


def _np_merge_round(acc: np.ndarray, val: np.ndarray) -> np.ndarray:
    return (acc ^ _np_round(np.zeros_like(acc), val)) * _U64(_P1) + _U64(_P4)


def xxh64_batch(data: np.ndarray, lengths: np.ndarray, seed: int = 0) -> np.ndarray:
    """XXH64 of ``N`` byte rows at once.

    ``data`` is ``[N, max_len] uint8`` (rows zero-padded past their length) and
    ``lengths[N]`` gives each row's true byte length. Returns ``uint64[N]``.
    Used to build all K ring orderings for 100k endpoints host-side without a
    Python-level loop over nodes.
    """
    if data.ndim != 2 or data.dtype != np.uint8:
        raise ValueError("data must be [N, max_len] uint8")
    n_rows, max_len = data.shape
    lengths = lengths.astype(np.int64)
    if np.any(lengths > max_len) or np.any(lengths < 0):
        raise ValueError("lengths out of range")
    seed_u = _U64(seed & _MASK)

    # Zero out padding beyond each row's length so lane reads are deterministic,
    # then widen to uint64 once.
    col = np.arange(max_len, dtype=np.int64)[None, :]
    bytes64 = np.where(col < lengths[:, None], data, 0).astype(_U64)

    def lane8(base: np.ndarray) -> np.ndarray:
        """u64 little-endian lane at per-row byte offset ``base`` (may be ragged)."""
        idx = base[:, None] + np.arange(8, dtype=np.int64)[None, :]
        safe = np.clip(idx, 0, max_len - 1)
        b = np.take_along_axis(bytes64, safe, axis=1)
        b = np.where(idx < max_len, b, _U64(0))
        shifts = (np.arange(8, dtype=np.uint64) * _U64(8))[None, :]
        return (b << shifts).sum(axis=1, dtype=_U64)

    def lane4(base: np.ndarray) -> np.ndarray:
        idx = base[:, None] + np.arange(4, dtype=np.int64)[None, :]
        safe = np.clip(idx, 0, max_len - 1)
        b = np.take_along_axis(bytes64, safe, axis=1)
        b = np.where(idx < max_len, b, _U64(0))
        shifts = (np.arange(4, dtype=np.uint64) * _U64(8))[None, :]
        return (b << shifts).sum(axis=1, dtype=_U64)

    with np.errstate(over="ignore"):
        acc = np.full(n_rows, 0, dtype=_U64)
        long_rows = lengths >= 32
        # --- long-input accumulator phase (rows with >= 32 bytes) ---
        if np.any(long_rows):
            v1 = np.full(n_rows, (seed_u + _U64(_P1 & _MASK) + _U64(_P2)) & _U64(_MASK), dtype=_U64)
            v2 = np.full(n_rows, seed_u + _U64(_P2), dtype=_U64)
            v3 = np.full(n_rows, seed_u, dtype=_U64)
            v4 = np.full(n_rows, seed_u - _U64(_P1), dtype=_U64)
            n_stripes = lengths // 32
            max_stripes = int(n_stripes.max())
            for s in range(max_stripes):
                take = n_stripes > s
                base = np.where(take, s * 32, 0).astype(np.int64)
                nv1 = _np_round(v1, lane8(base))
                nv2 = _np_round(v2, lane8(base + 8))
                nv3 = _np_round(v3, lane8(base + 16))
                nv4 = _np_round(v4, lane8(base + 24))
                v1 = np.where(take, nv1, v1)
                v2 = np.where(take, nv2, v2)
                v3 = np.where(take, nv3, v3)
                v4 = np.where(take, nv4, v4)
            conv = _np_rotl(v1, 1) + _np_rotl(v2, 7) + _np_rotl(v3, 12) + _np_rotl(v4, 18)
            conv = _np_merge_round(conv, v1)
            conv = _np_merge_round(conv, v2)
            conv = _np_merge_round(conv, v3)
            conv = _np_merge_round(conv, v4)
            acc = np.where(long_rows, conv, acc)
        acc = np.where(long_rows, acc, seed_u + _U64(_P5))
        acc = acc + lengths.astype(_U64)

        # --- tail phase: consumed = stripes*32, then 8-byte, 4-byte, 1-byte ---
        consumed = (lengths // 32) * 32
        remaining = lengths - consumed
        # at most 3 u64 lanes remain (< 32 bytes)
        for _ in range(3):
            take = remaining >= 8
            if not np.any(take):
                break
            lane = lane8(consumed)
            new = _np_rotl(acc ^ _np_round(np.zeros_like(acc), lane), 27) * _U64(_P1) + _U64(_P4)
            acc = np.where(take, new, acc)
            consumed = np.where(take, consumed + 8, consumed)
            remaining = np.where(take, remaining - 8, remaining)
        take = remaining >= 4
        if np.any(take):
            lane = lane4(consumed)
            new = _np_rotl(acc ^ (lane * _U64(_P1)), 23) * _U64(_P2) + _U64(_P3)
            acc = np.where(take, new, acc)
            consumed = np.where(take, consumed + 4, consumed)
            remaining = np.where(take, remaining - 4, remaining)
        for _ in range(3):
            take = remaining >= 1
            if not np.any(take):
                break
            idx = np.clip(consumed, 0, max_len - 1)
            byte = np.take_along_axis(bytes64, idx[:, None], axis=1)[:, 0]
            new = _np_rotl(acc ^ (byte * _U64(_P5)), 11) * _U64(_P1)
            acc = np.where(take, new, acc)
            consumed = np.where(take, consumed + 1, consumed)
            remaining = np.where(take, remaining - 1, remaining)

        acc = acc ^ (acc >> _U64(33))
        acc = acc * _U64(_P2)
        acc = acc ^ (acc >> _U64(29))
        acc = acc * _U64(_P3)
        acc = acc ^ (acc >> _U64(32))
    return acc


def endpoint_hash_batch(
    hostnames: np.ndarray, lengths: np.ndarray, ports: np.ndarray, seed: int
) -> np.ndarray:
    """Vectorized ``endpoint_hash`` over N endpoints; returns uint64[N]."""
    host_h = xxh64_batch(hostnames, lengths, seed)
    port_bytes = np.zeros((len(ports), 4), dtype=np.uint8)
    p = ports.astype(np.uint32)
    for i in range(4):
        port_bytes[:, i] = ((p >> np.uint32(8 * i)) & np.uint32(0xFF)).astype(np.uint8)
    port_h = xxh64_batch(port_bytes, np.full(len(ports), 4, dtype=np.int64), seed)
    with np.errstate(over="ignore"):
        return host_h * _U64(31) + port_h


def xxh64_batch_auto(
    data: np.ndarray, lengths: np.ndarray, seed: int = 0
) -> np.ndarray:
    """``xxh64_batch`` through the native library when it is loadable, the
    vectorized-numpy implementation otherwise (identical outputs; the two
    are cross-validated in tests/test_hashing.py). Use this on hot
    construction paths -- the native lane loop is several times faster at
    million-row batches."""
    from . import native

    data = np.ascontiguousarray(data, dtype=np.uint8)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    out = native.xxh64_batch(data, lengths, seed)
    return out if out is not None else xxh64_batch(data, lengths, seed)


def pack_hostnames(hostnames: Sequence[bytes]) -> Tuple[np.ndarray, np.ndarray]:
    """Pack variable-length hostname byte strings into a padded uint8 matrix."""
    max_len = max((len(h) for h in hostnames), default=1)
    max_len = max(max_len, 1)
    data = np.zeros((len(hostnames), max_len), dtype=np.uint8)
    lengths = np.zeros(len(hostnames), dtype=np.int64)
    for i, h in enumerate(hostnames):
        data[i, : len(h)] = np.frombuffer(h, dtype=np.uint8)
        lengths[i] = len(h)
    return data, lengths
